"""Elastic mesh supervision: per-shard fault isolation for multi-chip verify.

``parallel/mesh.py`` can shard one fused verify dispatch across a device
mesh, but a sharded dispatch is only production-grade when one sick chip
costs a LANE, not the fleet (ROADMAP item 1: the supervisor must "degrade
*per shard*").  This module is the jax-free brain of that guarantee — the
mesh analog of ``crypto/backend_health`` + ``ops/supervisor``:

  * **per-device circuit breakers** — every stable physical ordinal gets a
    ``mesh_dev{N}`` breaker in the shared ``backend_health`` registry, so
    the existing backoff/half-open/re-promotion machinery (and the
    ``cometbft_crypto_backend_breaker_state{backend=}`` gauge) covers mesh
    lanes for free;
  * **shrink ladder** — a shard failure (raise, watchdog fire, malformed
    shard) records a breaker failure for THAT ordinal and re-dispatches
    once on the surviving devices (N -> N-1 -> ... -> 1); at width < 2 the
    batch falls into the existing single-chip degradation chain
    (pallas -> xla -> host), so an infrastructure failure can NEVER become
    a wrong verdict — the host ZIP-215 oracle is still the floor;
  * **proactive exclusion** — an OPEN ``mesh_dev*`` breaker (or an
    ``ops/device_health`` down-probe for that ordinal, which trips the
    breaker out-of-band) removes the chip from mesh membership BEFORE the
    next dispatch; re-admission happens through a one-bucket probe
    dispatch when the breaker's backoff elapses (HALF_OPEN), so a
    still-dead chip costs one tiny probe, never a full production batch;
  * **deterministic fault seam** — ``set_fault_injector`` +
    ``FaultyDevice`` raise/hang/wrong-shape/flap a CHOSEN ordinal
    (counter-based, so the sim's chip-death / mesh-brownout scenarios are
    byte-deterministic per seed), and ``set_mesh_runner`` swaps the real
    per-shard device work for the host oracle exactly like
    ``ops/supervisor.set_device_runner`` does for the single-chip path.

Everything lands on the existing observability rails: ``mesh.reconfig``
black-box events, ``mesh_shrink`` / ``mesh_restore`` /
``shard_watchdog_fire`` anomaly kinds (docs/observability.md), the
``cometbft_crypto_mesh_width`` gauge (via ``ops/dispatch_stats``), and
``mesh.shard`` spans keyed by stable physical ordinal.

Activation: ``configure()`` is called by the sim/tests (virtual ordinals)
or by ``ops/verify``'s one-time device probe (>= 2 devices, all-TPU or
``COMETBFT_TPU_MESH=1``), so single-chip CI never takes this path.  Kill
switch ``COMETBFT_TPU_MESH_SUPERVISOR=0`` restores the raw sharded call
(and the single-chip chain) bit-for-bit.

Deliberately free of jax imports at module level: metrics scrapes and the
verifysched dispatcher read ``healthy_width()`` and must never be the
thing that initializes an accelerator backend.  The real device path is
imported lazily inside the dispatch (``parallel/mesh.dispatch_elastic``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from cometbft_tpu.crypto import backend_health
from cometbft_tpu.libs import tracing
from cometbft_tpu.ops import dispatch_stats

logger = logging.getLogger("cometbft_tpu.crypto")

BREAKER_PREFIX = "mesh_dev"


def breaker_name(ordinal: int) -> str:
    return f"{BREAKER_PREFIX}{int(ordinal)}"


def enabled() -> bool:
    """``COMETBFT_TPU_MESH_SUPERVISOR=0`` is the kill switch: the raw
    sharded call (and the plain single-chip chain) come back bit-for-bit."""
    return os.environ.get("COMETBFT_TPU_MESH_SUPERVISOR", "1") != "0"


class ShardFailure(backend_health.BackendError):
    """One shard of a mesh dispatch failed, attributable to a stable
    physical ordinal — the typed seam between ``parallel/mesh`` (which
    detects it at fetch time) and the shrink ladder here (which removes
    the ordinal and re-dispatches).  Always wraps the underlying error."""

    def __init__(self, ordinal: int, err: BaseException):
        super().__init__(f"mesh shard on ordinal {ordinal} failed: {err!r}")
        self.ordinal = int(ordinal)
        self.err = err


# -- membership state ---------------------------------------------------------

_LOCK = threading.Lock()
_ORDINALS: "Optional[tuple[int, ...]]" = None  # None = mesh inactive


def configure(ordinals: "Sequence[int]") -> None:
    """Declare the full mesh membership as stable physical ordinals.  The
    sim passes virtual ordinals (no jax anywhere); production passes
    ``range(len(jax.devices()))`` from ``ops/verify``'s one-time probe.

    Per-ordinal health state recorded BEFORE configuration is folded in:
    a chip the watcher already marked down (a boot-time outage — the
    exact case proactive exclusion exists for) must not join membership
    just because its down-probe predated the mesh."""
    global _ORDINALS
    with _LOCK:
        _ORDINALS = tuple(int(o) for o in ordinals)
    _note_width(len(_ORDINALS))
    tracing.note_event(
        "mesh.reconfig", width=len(_ORDINALS), reason="configure"
    )
    from cometbft_tpu.ops import device_health

    for key, up in device_health.snapshot().get("ordinals", {}).items():
        if not up:
            note_probe(int(key), False)


def clear() -> None:
    """Deactivate the mesh and drop the injector/runner seams (tests, sim
    teardown).  Breaker state lives in ``backend_health`` and is reset by
    its own ``reset()``."""
    global _ORDINALS, _RUNNER, _FAULT_INJECTOR
    with _LOCK:
        _ORDINALS = None
        _RUNNER = None
        _FAULT_INJECTOR = None
    _note_width(0)


def configured() -> bool:
    return _ORDINALS is not None


def total_width() -> int:
    """Full configured membership (breakers ignored)."""
    o = _ORDINALS
    return len(o) if o is not None else 0


def active() -> bool:
    """Whether supervised verify should take the mesh path at all: the
    kill switch is on and >= 2 devices are configured.  Membership can
    still shrink below 2 at dispatch time — that falls into the
    single-chip chain per batch."""
    o = _ORDINALS
    return o is not None and len(o) >= 2 and enabled()


DEFAULT_MIN_BATCH = 256


def min_batch() -> int:
    """Smallest batch the supervised path routes through the mesh
    (``COMETBFT_TPU_MESH_MIN_BATCH``, default 256): a single gossip vote
    must not pay a cross-device collective plus per-shard fetches for
    work one chip's smallest bucket absorbs — sharding only wins once the
    batch outgrows a single chip.  The sim/dry-run/bench harnesses set 1
    (or call ``verify_elastic`` directly) to exercise the machinery on
    tiny batches."""
    try:
        return int(
            os.environ.get("COMETBFT_TPU_MESH_MIN_BATCH", "")
            or DEFAULT_MIN_BATCH
        )
    except ValueError:
        return DEFAULT_MIN_BATCH


def healthy_width() -> int:
    """Devices a new dispatch would currently target (CLOSED breakers
    only — a read, never a probe).  0 when the mesh is inactive.  The
    verifysched dispatcher sizes its flush target from this, so bucket
    targeting follows the live mesh width through shrinks and restores."""
    o = _ORDINALS
    if o is None or not enabled():
        return 0
    reg = backend_health.registry()
    return sum(
        1
        for ordinal in o
        if reg.breaker(breaker_name(ordinal)).state == backend_health.CLOSED
    )


def healthy_ordinals() -> "list[int]":
    """Ordinals a new lane dispatch may target (CLOSED breakers only — a
    read, never a probe; the mesh-wide ``_membership`` walk owns probe
    slots).  Empty when the mesh is inactive.  jax-free: the verifysched
    dispatcher round-robins its in-flight flushes over this list."""
    o = _ORDINALS
    if o is None or not enabled():
        return []
    reg = backend_health.registry()
    return [
        ordinal
        for ordinal in o
        if reg.breaker(breaker_name(ordinal)).state == backend_health.CLOSED
    ]


def admit_ordinals() -> "list[int]":
    """Ordinals a NEW lane dispatch may target, probes included: the
    same membership walk a mesh-wide dispatch runs (``_membership``) —
    CLOSED breakers join directly, a HALF_OPEN ordinal spends its probe
    slot on the one-bucket re-admission probe and joins only if it
    passes.  This is what the pipelined verifysched dispatcher calls per
    flush: without it, lane round-robin would orbit the healthy subset
    forever and an excluded chip could never re-earn its lane.  Empty
    when the mesh is inactive."""
    if _ORDINALS is None or not enabled():
        return []
    return _membership(set())


def _note_width(w: int) -> None:
    # unconditionally (one locked int store): a change-detection cache
    # here would desync from dispatch_stats.reset(), leaving the gauge at
    # 0 for an active mesh until the width next happened to change
    dispatch_stats.record_mesh_width(w)


# -- fault injection + runner seams ------------------------------------------

_RUNNER: Optional[Callable] = None
_FAULT_INJECTOR: Optional[Callable] = None


def host_oracle_runner(ordinal, pubs, msgs, sigs, lanes) -> np.ndarray:
    """THE reference per-shard runner for ``set_mesh_runner`` — the host
    ZIP-215 oracle over one shard, padding lanes False.  Sim scenarios,
    the bench stage and the test suite all share this single definition
    (the "verdict-identical by construction" argument needs ONE oracle,
    not five copies); the first argument is ignored so it also serves as
    a single-chip device-runner stand-in."""
    from cometbft_tpu.crypto import ed25519_ref as ref

    out = np.zeros(int(lanes), dtype=bool)
    out[: len(pubs)] = [
        ref.verify_zip215(p, m, s) for p, m, s in zip(pubs, msgs, sigs)
    ]
    return out


def set_mesh_runner(fn: Optional[Callable]) -> None:
    """Swap per-shard device work for ``fn(ordinal, pubs, msgs, sigs,
    lanes) -> (lanes,) bool`` (padding lanes False) — the mesh analog of
    ``ops/supervisor.set_device_runner``: the sim installs the host
    ZIP-215 oracle here so chip-death scenarios never pay a real XLA
    dispatch, while every elastic mechanism under test (breakers,
    membership, shrink ladder, probes, injector) runs unchanged above
    this seam.  ``None`` clears."""
    global _RUNNER
    _RUNNER = fn


def clear_mesh_runner() -> None:
    set_mesh_runner(None)


def set_fault_injector(fn: Optional[Callable]) -> None:
    """Install ``fn(ordinal, pubs, msgs, sigs) -> Optional[transform]``,
    consulted once per shard per dispatch (and per re-admission probe).
    It may raise (simulated shard error), sleep (simulated chip wedge —
    the shard watchdog fires), or return a callable applied to the
    shard's result (simulated corruption).  On the real device path the
    per-shard triples are not reconstructed at fetch time, so the
    injector is called with ``None`` batch args there — ``FaultyDevice``
    only keys on the ordinal.  ``None`` clears."""
    global _FAULT_INJECTOR
    _FAULT_INJECTOR = fn


def clear_fault_injector() -> None:
    set_fault_injector(None)


class FaultyDevice:
    """Deterministic per-ordinal fault shim for ``set_fault_injector`` —
    the mesh-granular sibling of ``ops/supervisor.FaultyBackend``.

    Modes:
      * ``raise``       — every dispatch touching a chosen ordinal raises
        (chip death);
      * ``hang``        — sleep ``hang_s`` then raise (a shard watchdog
        shorter than ``hang_s`` fires first);
      * ``wrong_shape`` — the shard's result loses a lane (must read as
        infrastructure, never as verdicts);
      * ``flap``        — bursty per-ordinal: ``fail_n`` failing calls,
        then ``pass_n`` clean ones, repeating (counter-based per ordinal,
        so sim brownouts are deterministic).
    """

    def __init__(
        self,
        mode: str,
        ordinals: Sequence[int] = (0,),
        hang_s: float = 30.0,
        fail_n: int = 4,
        pass_n: int = 2,
    ):
        assert mode in ("raise", "hang", "wrong_shape", "flap"), mode
        self.mode = mode
        self.ordinals = tuple(int(o) for o in ordinals)
        self.hang_s = hang_s
        self.fail_n = fail_n
        self.pass_n = pass_n
        self.calls = 0
        self.faults = 0
        self._per_ordinal: dict = {}
        self._lock = threading.Lock()

    def __call__(self, ordinal, pubs, msgs, sigs):
        if int(ordinal) not in self.ordinals:
            return None
        with self._lock:
            self.calls += 1
            if self.mode == "flap":
                seq = self._per_ordinal.get(int(ordinal), 0)
                self._per_ordinal[int(ordinal)] = seq + 1
                cycle = self.fail_n + self.pass_n
                if seq % cycle >= self.fail_n:
                    return None  # pass phase of the burst cycle
            self.faults += 1
        if self.mode == "hang":
            time.sleep(self.hang_s)
            raise RuntimeError(
                f"injected fault: mesh ordinal {ordinal} wedge (unwedged)"
            )
        if self.mode == "wrong_shape":
            return lambda out: out[:-1]
        raise RuntimeError(
            f"injected fault: {self.mode} on mesh ordinal {ordinal}"
        )


# -- device-health integration ------------------------------------------------


def note_probe(ordinal: int, up: bool) -> None:
    """Fold an out-of-band health probe (``ops/device_health`` — the
    in-process prober or the chip watcher's status file) into mesh
    membership.  A DOWN probe trips the ordinal's breaker so the chip
    leaves the mesh BEFORE the next dispatch; re-admission rides the
    breaker's own half-open probe on backoff (an UP probe does not force
    re-admission — the probe dispatch is the arbiter)."""
    o = _ORDINALS
    if o is None or int(ordinal) not in o:
        return
    if up:
        return
    br = backend_health.registry().breaker(breaker_name(ordinal))
    # only a chip still IN membership (CLOSED breaker) is a new
    # exclusion; an already-excluded chip (OPEN, or HALF_OPEN awaiting
    # its probe) stays on the breaker's own backoff schedule — re-tripping
    # would double the backoff, overcount shrinks, and journal a
    # membership change that never happened
    if br.state == backend_health.CLOSED:
        br.trip(f"device_probe reported ordinal {ordinal} down")
        dispatch_stats.record_mesh_shrink()
        tracing.record_anomaly(
            "mesh_shrink", ordinal=int(ordinal), reason="probe-down"
        )
        tracing.note_event(
            "mesh.reconfig",
            width=healthy_width(),
            excluded=int(ordinal),
            reason="probe-down",
        )
        logger.warning(
            "mesh ordinal %d excluded: health probe reported it down",
            ordinal,
        )


# -- re-admission probe -------------------------------------------------------

_PROBE_BATCH: "Optional[tuple]" = None


def _probe_batch() -> tuple:
    """One deterministic known-good (pub, msg, sig) triple — the
    one-bucket probe dispatch a HALF_OPEN ordinal must pass to rejoin the
    mesh.  A wrong verdict on it is an infrastructure failure: the
    signature is valid by construction."""
    global _PROBE_BATCH
    if _PROBE_BATCH is None:
        from cometbft_tpu.crypto import ed25519_ref as ref

        seed = b"\x5a" * 32
        msg = b"mesh-readmission-probe"
        _PROBE_BATCH = (
            [ref.pubkey_from_seed(seed)],
            [msg],
            [ref.sign(seed, msg)],
        )
    return _PROBE_BATCH


def _probe_ordinal(ordinal: int, br) -> bool:
    """Run the re-admission probe on one ordinal (the breaker's half-open
    slot is already claimed).  Success re-promotes the breaker and the
    chip rejoins membership; failure re-opens with doubled backoff."""
    reg = backend_health.registry()
    pubs, msgs, sigs = _probe_batch()
    try:
        with tracing.span("mesh.probe", device=int(ordinal)) as sp:
            out = np.asarray(_run_shard(ordinal, pubs, msgs, sigs, 1))
            if out.shape != (1,) or out.dtype != np.bool_:
                raise backend_health.BackendOutputError(
                    f"probe on mesh ordinal {ordinal} returned shape "
                    f"{out.shape} dtype {out.dtype}, want (1,) bool"
                )
            if not bool(out[0]):
                raise backend_health.BackendOutputError(
                    f"probe on mesh ordinal {ordinal} rejected a known-"
                    "good signature (device computing wrong results)"
                )
            sp.set(ok=True)
    except Exception as e:  # noqa: BLE001 — a failed probe re-opens
        br.record_failure(e)
        reg.record_demotion(breaker_name(ordinal))
        return False
    br.record_success()
    tracing.record_anomaly("mesh_restore", ordinal=int(ordinal))
    tracing.note_event(
        "mesh.reconfig",
        width=healthy_width(),
        restored=int(ordinal),
        reason="probe-pass",
    )
    dispatch_stats.record_mesh_restore()
    logger.info("mesh ordinal %d re-admitted (probe passed)", ordinal)
    return True


def _membership(banned: set) -> "list[int]":
    """Devices the NEXT dispatch targets: CLOSED breakers join directly;
    a HALF_OPEN breaker spends its probe slot on the one-bucket probe
    (never on a production batch) and joins only if it passes; OPEN (and
    locally banned) ordinals are excluded."""
    reg = backend_health.registry()
    out: "list[int]" = []
    for o in _ORDINALS or ():
        if o in banned:
            continue
        br = reg.breaker(breaker_name(o))
        st = br.state
        if st == backend_health.CLOSED:
            out.append(o)
        elif st == backend_health.HALF_OPEN and br.allow():
            if _probe_ordinal(o, br):
                out.append(o)
    return out


# -- per-shard execution ------------------------------------------------------


def _run_shard(ordinal: int, pubs, msgs, sigs, lanes: int) -> np.ndarray:
    """One shard's device work under the shard watchdog, with the fault
    injector consulted first (inside the watchdog worker, so a hanging
    injector exercises the real deadline path)."""
    from cometbft_tpu.ops import supervisor

    inj = _FAULT_INJECTOR
    runner = _RUNNER

    def run():
        transform = (
            inj(ordinal, pubs, msgs, sigs) if inj is not None else None
        )
        if runner is not None:
            out = np.asarray(runner(ordinal, pubs, msgs, sigs, lanes))
        else:
            from cometbft_tpu.parallel import mesh as pmesh

            out = pmesh.run_single_shard(ordinal, pubs, msgs, sigs, lanes)
        if transform is not None:
            out = transform(out)
        return out

    return supervisor.watchdog_call(
        run, backend=breaker_name(ordinal), note_anomaly=False
    )


def _attempt(devs: "list[int]", pubs, msgs, sigs) -> np.ndarray:
    """One elastic mesh attempt at the current width.  Raises
    ``ShardFailure`` (ordinal-attributed) on any shard problem; the
    caller shrinks and re-dispatches."""
    runner = _RUNNER
    if runner is None:
        from cometbft_tpu.parallel import mesh as pmesh

        return pmesh.dispatch_elastic(
            devs, pubs, msgs, sigs, injector=_FAULT_INJECTOR
        )

    # runner seam (sim/tests): host-side sharding mirrors the mesh layout
    # — bucket-padded lanes split contiguously across the width, one
    # ``mesh.shard`` span per ordinal, padding lanes False
    from cometbft_tpu.ops import verify as ov

    n = len(pubs)
    w = len(devs)
    lanes = ov.bucket_size(max(n, 1), ov._min_bucket())
    lanes += (-lanes) % w
    per = lanes // w
    dispatch_stats.record_dispatch(lanes, n)
    seq = dispatch_stats.dispatch_count()
    bits = np.zeros(lanes, dtype=bool)
    t0 = time.perf_counter()
    with tracing.span(
        "verify.dispatch",
        tier="oracle",
        lanes=lanes,
        n=n,
        dispatch=seq,
        mesh=w,
    ):
        for i, o in enumerate(devs):
            lo = min(i * per, n)
            hi = min((i + 1) * per, n)
            ts = time.perf_counter()
            with tracing.span(
                "mesh.shard", device=o, lanes=per, tier="oracle"
            ) as sp:
                try:
                    out = np.asarray(
                        _run_shard(o, pubs[lo:hi], msgs[lo:hi],
                                   sigs[lo:hi], per)
                    )
                    if out.shape != (per,) or out.dtype != np.bool_:
                        raise backend_health.BackendOutputError(
                            f"mesh shard {o} returned shape {out.shape} "
                            f"dtype {out.dtype}, want ({per},) bool"
                        )
                except ShardFailure:
                    raise
                except Exception as e:
                    raise ShardFailure(o, e) from e
                sp.set(ok=int(out.sum()))
            bits[i * per : (i + 1) * per] = out
            dispatch_stats.record_shard_time(
                "oracle", o, per, time.perf_counter() - ts
            )
    dispatch_stats.record_dispatch_time(
        "oracle", lanes, time.perf_counter() - t0
    )
    return bits[:n]


# -- single-lane dispatch/fetch seam (in-flight pipeline) ---------------------


class _LaneHandle:
    """One lane's deferred shard work (docs/verify-scheduler.md
    "In-flight pipeline").  ``run_single_shard`` blocks on the device
    result inside its jitted call, so the device work itself executes at
    ``fetch_lane`` time on the completion pool — the dispatch records the
    routing decision and returns immediately, which is what lets the
    dispatcher keep K lanes busy concurrently."""

    __slots__ = ("ordinal", "pubs", "msgs", "sigs", "n", "lanes", "t0")

    def __init__(self, ordinal, pubs, msgs, sigs, n, lanes, t0):
        self.ordinal = ordinal
        self.pubs = pubs
        self.msgs = msgs
        self.sigs = sigs
        self.n = n
        self.lanes = lanes
        self.t0 = t0


def dispatch_lane(ordinal: int, pubs, msgs, sigs) -> _LaneHandle:
    """Route one batch at a single mesh lane without blocking on its
    verdict.  Pair with ``fetch_lane``; a failed/wedged lane surfaces
    there as ``ShardFailure`` so the fetcher can degrade THAT lane alone
    (``note_lane_failure``) and re-verify on the single-chip chain."""
    from cometbft_tpu.ops import verify as ov

    pubs, msgs, sigs = list(pubs), list(msgs), list(sigs)
    n = len(pubs)
    lanes = ov.bucket_size(max(n, 1), ov._min_bucket())
    dispatch_stats.record_dispatch(lanes, n)
    return _LaneHandle(
        int(ordinal), pubs, msgs, sigs, n, lanes, time.perf_counter()
    )


def fetch_lane(h: _LaneHandle) -> np.ndarray:
    """Resolve one lane dispatch: the shard runs under the shard watchdog
    with the fault injector consulted, exactly like a shard of a mesh-wide
    dispatch.  Returns (n,) bool accept bits; raises ``ShardFailure``
    (ordinal-attributed) on any infrastructure problem."""
    ts = time.perf_counter()
    with tracing.span(
        "mesh.shard", device=h.ordinal, lanes=h.lanes, tier="lane"
    ) as sp:
        try:
            out = np.asarray(
                _run_shard(h.ordinal, h.pubs, h.msgs, h.sigs, h.lanes)
            )
            if out.shape != (h.lanes,) or out.dtype != np.bool_:
                raise backend_health.BackendOutputError(
                    f"mesh lane {h.ordinal} returned shape {out.shape} "
                    f"dtype {out.dtype}, want ({h.lanes},) bool"
                )
        except ShardFailure:
            raise
        except Exception as e:
            raise ShardFailure(h.ordinal, e) from e
        sp.set(ok=int(out.sum()))
    dt = time.perf_counter() - ts
    dispatch_stats.record_shard_time("lane", h.ordinal, h.lanes, dt)
    dispatch_stats.record_dispatch_time("lane", h.lanes, dt)
    # a clean lane resets the ordinal's consecutive-failure count, exactly
    # like a participant in a clean mesh-wide dispatch
    backend_health.registry().breaker(
        breaker_name(h.ordinal)
    ).record_success()
    return out[: h.n]


def note_lane_failure(ordinal: int, err: BaseException, width: int) -> None:
    """Record one lane/shard failure on every observability rail: breaker
    failure + demotion for THAT ordinal, shrink counters, anomalies and
    the ``mesh.reconfig`` journal event.  Shared by the mesh-wide shrink
    ladder (``verify_elastic``) and the in-flight pipeline's per-lane
    degradation (``ops/supervisor.fetch_verify``).  ``width`` is the
    healthy width the NEXT dispatch will see (after this exclusion)."""
    name = breaker_name(ordinal)
    reg = backend_health.registry()
    if isinstance(err, backend_health.DispatchTimeoutError):
        tracing.record_anomaly(
            "shard_watchdog_fire", ordinal=int(ordinal), width=width
        )
    reg.breaker(name).record_failure(err)
    reg.record_demotion(name)
    dispatch_stats.record_mesh_shrink()
    tracing.record_anomaly(
        "mesh_shrink",
        ordinal=int(ordinal),
        width=width,
        error=type(err).__name__,
    )
    tracing.note_event(
        "mesh.reconfig",
        width=width,
        excluded=int(ordinal),
        reason="shard-failure",
    )
    logger.warning(
        "mesh shard on ordinal %d failed (%r); shrinking to %d devices",
        ordinal,
        err,
        width,
    )


# -- the elastic verify entry -------------------------------------------------


def verify_elastic(pubs, msgs, sigs) -> np.ndarray:
    """Mesh-sharded supervised verify with the shrink ladder: returns
    (n,) bool accept bits and cannot raise for infrastructure reasons —
    every failure mode either shrinks the mesh and re-dispatches or falls
    into the single-chip degradation chain (whose floor is the host
    ZIP-215 oracle).  ``banned`` is per-call: a failed ordinal is out of
    THIS batch immediately regardless of its breaker's threshold, while
    the breaker decides when future dispatches stop probing it."""
    pubs, msgs, sigs = list(pubs), list(msgs), list(sigs)
    reg = backend_health.registry()
    banned: set = set()
    while True:
        devs = _membership(banned)
        _note_width(len(devs))
        if len(devs) < 2:
            # the bottom of the ladder: the existing single-chip chain
            # (pallas -> xla -> host) takes the whole batch
            from cometbft_tpu.ops import supervisor

            return supervisor.verify_supervised(pubs, msgs, sigs, mesh=False)
        try:
            bits = _attempt(devs, pubs, msgs, sigs)
            # a clean dispatch resets every participant's consecutive-
            # failure count (flap bursts below the threshold must not
            # accumulate across healthy dispatches)
            for o in devs:
                reg.breaker(breaker_name(o)).record_success()
            return bits
        except ShardFailure as e:
            banned.add(e.ordinal)
            note_lane_failure(e.ordinal, e.err, len(devs) - 1)
            continue
        except Exception as e:  # noqa: BLE001 — non-attributable mesh
            # failure (lowering, collective, compile): no ordinal to
            # blame, so the whole batch falls to the single-chip chain —
            # degraded, never a wrong verdict
            from cometbft_tpu.ops import supervisor

            logger.warning(
                "mesh dispatch failed without shard attribution (%r); "
                "falling back to the single-chip chain for this batch",
                e,
            )
            return supervisor.verify_supervised(pubs, msgs, sigs, mesh=False)
