"""Generated protobuf modules (see scripts/gen_proto.sh).

The generated files import each other with absolute ``cometbft.*`` module
paths (protoc's convention), so this package prepends itself to sys.path
on first import.
"""

import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
if _here not in sys.path:
    sys.path.insert(0, _here)
