"""Process-wide counters for the Merkle/hash plane and proof server.

Deliberately free of jax imports, exactly like ``verifysched/stats`` and
``ops/dispatch_stats``: ``libs/metrics.NodeMetrics`` reads these through
callback gauges and a /metrics scrape must never be the thing that
initializes an accelerator backend.  ``ops/sha256_tree.py`` writes the
tree-pass counters (it computes the padded lane count at dispatch time);
``proofserve/service.py`` writes the proof-query counters.

Counters (all guarded by one lock):

  * ``queries[kind]``      — proof queries admitted (``tx`` / ``header`` /
    ``valset``), including cache-hit submissions
  * ``cache_hits[kind]``   — queries resolved from the LRU root/proof cache
    without occupying a queue slot
  * ``cache_misses``       — coalesced groups that had to build an entry
    (hit rate = hits / (hits + misses))
  * ``shed[kind]``         — submissions rejected by admission control;
    the caller's serial fallback answers them (``serial_fallbacks``), so a
    shed query is never a lost response
  * ``queue_depth``        — queries currently pending (gauge-style)
  * ``flushes[reason]``    — dispatcher flushes by trigger:
    ``deadline`` / ``full`` / ``shutdown``
  * ``flush_queries`` / ``flush_groups`` — queries drained across all
    flushes and the (kind, height) groups they coalesced into
    (queries_per_flush = flush_queries / flushes)
  * ``tree_builds[kind]``  — root/proof-set builds (one per coalesced
    group miss — the number the bench gates as dispatches-per-1k-proofs)
  * ``trees_device`` / ``trees_host`` — tree passes by path (device
    kernel / runner seam vs host fallback)
  * ``tree_leaves`` / ``tree_lanes`` — leaves hashed and bucket-padded
    device lanes they occupied (lanes_occupancy = leaves / lanes)
  * ``device_fallbacks``   — device tree passes that degraded to the host
    oracle mid-flight (breaker records the failure; the root is never
    wrong, only slower)
  * ``oversize_host``      — trees sent straight to the host because a
    leaf or the lane budget exceeded the kernel's bucket ladder
"""

from __future__ import annotations

import threading

KINDS = ("tx", "header", "valset")
FLUSH_REASONS = ("deadline", "full", "shutdown")

_LOCK = threading.Lock()


def _zero() -> dict:
    return {
        "queries": {k: 0 for k in KINDS},
        "cache_hits": {k: 0 for k in KINDS},
        "cache_misses": 0,
        "shed": {k: 0 for k in KINDS},
        "serial_fallbacks": 0,
        "queue_depth": 0,
        "flushes": {r: 0 for r in FLUSH_REASONS},
        "flush_queries": 0,
        "flush_groups": 0,
        "tree_builds": {k: 0 for k in KINDS},
        "trees_device": 0,
        "trees_host": 0,
        "tree_leaves": 0,
        "tree_lanes": 0,
        "device_fallbacks": 0,
        "oversize_host": 0,
    }


_STATS = _zero()


def _kind(kind: str) -> str:
    return kind if kind in KINDS else KINDS[0]


def record_query(kind: str) -> None:
    with _LOCK:
        _STATS["queries"][_kind(kind)] += 1
        _STATS["queue_depth"] += 1


def record_cache_hit(kind: str) -> None:
    """A submission resolved from the LRU cache (it never occupied a
    queue slot, so queue_depth is untouched)."""
    with _LOCK:
        _STATS["queries"][_kind(kind)] += 1
        _STATS["cache_hits"][_kind(kind)] += 1


def record_cache_miss() -> None:
    with _LOCK:
        _STATS["cache_misses"] += 1


def record_shed(kind: str) -> None:
    with _LOCK:
        _STATS["shed"][_kind(kind)] += 1


def record_serial_fallback() -> None:
    with _LOCK:
        _STATS["serial_fallbacks"] += 1


def record_flush(reason: str, queries: int, groups: int) -> None:
    with _LOCK:
        _STATS["flushes"][reason] = _STATS["flushes"].get(reason, 0) + 1
        _STATS["flush_queries"] += int(queries)
        _STATS["flush_groups"] += int(groups)
        _STATS["queue_depth"] = max(0, _STATS["queue_depth"] - int(queries))


def record_build(kind: str) -> None:
    with _LOCK:
        _STATS["tree_builds"][_kind(kind)] += 1


def record_tree(leaves: int, lanes: int, device: bool) -> None:
    """One whole-tree pass: ``lanes`` is the bucket-padded lane count on
    the device path, 0 on the host path (it has no padding to waste)."""
    with _LOCK:
        if device:
            _STATS["trees_device"] += 1
            _STATS["tree_leaves"] += int(leaves)
            _STATS["tree_lanes"] += int(lanes)
        else:
            _STATS["trees_host"] += 1


def record_device_fallback() -> None:
    with _LOCK:
        _STATS["device_fallbacks"] += 1


def record_oversize() -> None:
    with _LOCK:
        _STATS["oversize_host"] += 1


def queue_depth() -> int:
    with _LOCK:
        return _STATS["queue_depth"]


def snapshot() -> dict:
    """Deep-enough copy for metrics/tests; adds derived aggregates."""
    with _LOCK:
        out = {
            k: (dict(v) if isinstance(v, dict) else v)
            for k, v in _STATS.items()
        }
    out["queries_total"] = sum(out["queries"].values())
    out["cache_hits_total"] = sum(out["cache_hits"].values())
    out["shed_total"] = sum(out["shed"].values())
    out["tree_builds_total"] = sum(out["tree_builds"].values())
    lookups = out["cache_hits_total"] + out["cache_misses"]
    out["proof_cache_hit_rate"] = (
        out["cache_hits_total"] / lookups if lookups else 0.0
    )
    out["lanes_occupancy"] = (
        out["tree_leaves"] / out["tree_lanes"] if out["tree_lanes"] else 0.0
    )
    flushes = sum(out["flushes"].values())
    out["queries_per_flush"] = (
        out["flush_queries"] / flushes if flushes else 0.0
    )
    return out


def reset() -> None:
    global _STATS
    with _LOCK:
        _STATS = _zero()
