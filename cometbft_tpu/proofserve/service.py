"""Coalescing proof server for light-client read traffic.

``rpc/core.py`` historically answered every ``tx(prove=True)``, header
and validator-hash query with its own serial Merkle walk — at 10k
concurrent light clients that is 10k redundant tree builds per block.
This module is the read-side sibling of ``verifysched/service.py`` (the
same continuous-batching shape, pointed at hashing instead of
signatures):

  * RPC handlers ``submit(kind, height)`` into a bounded queue and get a
    Future;
  * one dispatcher thread coalesces pending queries ACROSS all clients
    into (kind, height) groups and builds each group's tree ONCE through
    ``proofserve/plane.py`` (device kernel when trusted, host reference
    otherwise — bit-identical either way), flushing when the oldest
    query has waited ``COMETBFT_TPU_PROOFSERVE_FLUSH_US`` (~1000) or the
    queue fills;
  * an LRU cache keyed (kind, height) answers repeat queries for recent
    blocks without a queue slot — the steady-state stampede path is a
    lock + dict hit;
  * sheds (``QueueFullError``) and future timeouts fall back to the
    caller's serial build (``prove_tx``): a shed query costs the
    coalescing win, never a lost response, and NOTHING consensus-class
    ever rides this queue — proof serving is read-only traffic, so
    overload here cannot shed a vote by construction.

The server is decoupled from block/state types via three loaders
injected at ``configure`` time (``node/node.py`` wires them at start):
``tx_loader(h) -> list[tx bytes] | None``, ``header_hasher(h) -> bytes |
None``, ``valset_hasher(h) -> bytes | None``.  Kill switch
``COMETBFT_TPU_PROOFSERVE=0`` (shared with the plane) restores today's
serial RPC path bit for bit.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Callable, Optional

from cometbft_tpu.crypto import merkle
from cometbft_tpu.libs import tracing
from cometbft_tpu.proofserve import plane
from cometbft_tpu.proofserve import stats as pstats

logger = logging.getLogger("cometbft_tpu.proofserve")

DEFAULT_FLUSH_US = 1000.0
DEFAULT_QUEUE_CAP = 4096
DEFAULT_CACHE_CAP = 128

KINDS = pstats.KINDS


class QueueFullError(Exception):
    """Admission control rejected a proof query (backpressure).  The
    caller builds serially instead — shedding costs the coalescing win,
    never the response."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _Query:
    __slots__ = ("kind", "height", "future", "t0")

    def __init__(self, kind, height, future, t0):
        self.kind = kind
        self.height = height
        self.future = future
        self.t0 = t0


class ProofServer:
    """One dispatcher thread over one bounded queue of (kind, height)
    proof queries.  Thread-safe; lazily starts (and restarts) its thread
    on the first queued submission and drains everything (reason
    ``shutdown``) on ``close()`` — a future handed out is always
    eventually resolved.

    Resolution types: ``tx`` → ``(root, [Proof])`` for the whole block
    (the caller indexes its tx — that sharing is the coalescing win) or
    ``None`` when the height is missing; ``header``/``valset`` →
    ``bytes`` or ``None``."""

    def __init__(
        self,
        tx_loader: Callable[[int], Optional[list]],
        header_hasher: Callable[[int], Optional[bytes]],
        valset_hasher: Callable[[int], Optional[bytes]],
        flush_us: Optional[float] = None,
        queue_cap: Optional[int] = None,
        cache_cap: Optional[int] = None,
    ):
        self._loaders = {
            "tx": tx_loader,
            "header": header_hasher,
            "valset": valset_hasher,
        }
        if flush_us is None:
            flush_us = _env_float(
                "COMETBFT_TPU_PROOFSERVE_FLUSH_US", DEFAULT_FLUSH_US
            )
        if queue_cap is None:
            queue_cap = _env_int(
                "COMETBFT_TPU_PROOFSERVE_QUEUE", DEFAULT_QUEUE_CAP
            )
        if cache_cap is None:
            cache_cap = _env_int(
                "COMETBFT_TPU_PROOFSERVE_CACHE", DEFAULT_CACHE_CAP
            )
        self.flush_s = max(float(flush_us), 0.0) / 1e6
        self.queue_cap = max(int(queue_cap), 1)
        self.cache_cap = max(int(cache_cap), 1)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: "deque[_Query]" = deque()
        self._cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._paused = False

    # -- submission -------------------------------------------------------

    def submit(self, kind: str, height: int) -> "Future":
        """Queue one proof query; returns a Future.  An LRU hit resolves
        immediately without occupying a queue slot.  Raises
        ``QueueFullError`` at capacity — proof traffic is all
        read-class, so unlike the verify scheduler there is no
        shed-exempt tier."""
        if kind not in KINDS:
            raise ValueError(f"unknown proof kind {kind!r}")
        height = int(height)
        fut: "Future" = Future()
        try:
            with self._cond:
                if self._stopped:
                    raise RuntimeError("proof server is stopped")
                key = (kind, height)
                if key in self._cache:
                    self._cache.move_to_end(key)
                    val = self._cache[key]
                    pstats.record_cache_hit(kind)
                    fut.set_result(val)
                    return fut
                if len(self._queue) >= self.queue_cap:
                    pstats.record_shed(kind)
                    raise QueueFullError(
                        f"proof queue at capacity ({self.queue_cap}); "
                        f"shedding {kind} query"
                    )
                self._queue.append(
                    _Query(kind, height, fut, time.perf_counter())
                )
                pstats.record_query(kind)
                if self._thread is None or not self._thread.is_alive():
                    if self._thread is not None:
                        logger.error(
                            "proof dispatcher thread died; restarting "
                            "(%d queries pending)",
                            len(self._queue),
                        )
                    self._thread = threading.Thread(
                        target=self._run, name="proof-serve", daemon=True
                    )
                    self._thread.start()
                self._cond.notify_all()
        except QueueFullError:
            # anomaly recorded AFTER the cond is released: the flight
            # recorder's ring-dump IO must never block other submitters
            tracing.record_anomaly(
                "proof_shed", query_kind=kind, queue_cap=self.queue_cap
            )
            raise
        return fut

    # -- test/bench hooks -------------------------------------------------

    def pause(self) -> None:
        """Hold flushing (test/sim hook: build a deterministic backlog
        so a whole stampede coalesces into one flush)."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def cached(self, kind: str, height: int) -> bool:
        with self._lock:
            return (kind, int(height)) in self._cache

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting work, drain the queue (reason ``shutdown``)
        and join the dispatcher.  Every outstanding future resolves."""
        with self._cond:
            self._stopped = True
            self._paused = False
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout_s)
            if t.is_alive():
                logger.warning(
                    "proof dispatcher still alive %.1fs after close()",
                    timeout_s,
                )

    # -- dispatcher -------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and (
                    not self._queue or self._paused
                ):
                    self._cond.wait()
                if self._stopped and not self._queue:
                    return
                reason = "shutdown"
                if not self._stopped:
                    while True:
                        if self._stopped or self._paused:
                            break
                        if len(self._queue) >= self.queue_cap:
                            reason = "full"
                            break
                        if not self._queue:
                            break
                        remain = (
                            self._queue[0].t0
                            + self.flush_s
                            - time.perf_counter()
                        )
                        if remain <= 0:
                            reason = "deadline"
                            break
                        self._cond.wait(remain)
                    if self._paused and not self._stopped:
                        continue
                    if not self._queue:
                        continue
                items = list(self._queue)
                self._queue.clear()
            if items:
                self._execute(items, reason)

    # -- flush ------------------------------------------------------------

    def _build(self, kind: str, height: int):
        """One uncached build through the plane.  ``tx`` builds the
        whole block's proof set in one tree pass; ``header``/``valset``
        delegate to hashers whose own tree work already routes through
        the plane at the type layer."""
        if kind == "tx":
            txs = self._loaders["tx"](height)
            if txs is None:
                return None
            return plane.tree_proofs([bytes(t) for t in txs])
        return self._loaders[kind](height)

    def _execute(self, items: "list[_Query]", reason: str) -> None:
        recorded = [False]
        try:
            self._execute_inner(items, reason, recorded)
        except BaseException as e:  # noqa: BLE001 — futures must ALWAYS
            # resolve: these queries left the queue, so the submit-path
            # thread restart can never recover them
            logger.exception(
                "proof flush failed unexpectedly; failing %d queries",
                len(items),
            )
            if not recorded[0]:
                pstats.record_flush(reason, len(items), 0)
            for it in items:
                if not it.future.done():
                    it.future.set_exception(
                        e if isinstance(e, Exception) else RuntimeError(
                            type(e).__name__
                        )
                    )
            if not isinstance(e, Exception):
                raise

    def _execute_inner(
        self, items: "list[_Query]", reason: str, recorded: "list[bool]"
    ) -> None:
        groups: "OrderedDict[tuple, list[_Query]]" = OrderedDict()
        for it in items:
            groups.setdefault((it.kind, it.height), []).append(it)
        resolutions: "list[tuple[Future, object, Optional[Exception]]]" = []
        builds = 0
        with tracing.span(
            "proof.flush",
            reason=reason,
            queries=len(items),
            groups=len(groups),
        ) as sp:
            for (kind, height), members in groups.items():
                with self._lock:
                    hit = (kind, height) in self._cache
                    val = self._cache.get((kind, height))
                if not hit:
                    pstats.record_cache_miss()
                    try:
                        val = self._build(kind, height)
                    except Exception as e:  # noqa: BLE001 — fail the
                        # group, keep flushing the rest
                        for m in members:
                            resolutions.append((m.future, None, e))
                        continue
                    pstats.record_build(kind)
                    builds += 1
                    if val is not None:
                        with self._lock:
                            self._cache[(kind, height)] = val
                            self._cache.move_to_end((kind, height))
                            while len(self._cache) > self.cache_cap:
                                self._cache.popitem(last=False)
                for m in members:
                    resolutions.append((m.future, val, None))
            sp.set(builds=builds)
        # record BEFORE resolving (same discipline as verifysched): a
        # waiter reading stats right after its result must not race the
        # dispatcher's bookkeeping
        pstats.record_flush(reason, len(items), len(groups))
        recorded[0] = True
        for fut, val, exc in resolutions:
            if fut.done():
                continue
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(val)


# -- process-wide instance ----------------------------------------------------

_SERVER: Optional[ProofServer] = None
_SERVER_LOCK = threading.Lock()


def configure(
    tx_loader, header_hasher, valset_hasher, **kwargs
) -> ProofServer:
    """Install the process-wide proof server (``node/node.py`` calls
    this at start with store-backed loaders).  Replaces — and drains —
    any previous instance."""
    global _SERVER
    server = ProofServer(tx_loader, header_hasher, valset_hasher, **kwargs)
    with _SERVER_LOCK:
        prev, _SERVER = _SERVER, server
    if prev is not None:
        prev.close()
    return server


def get_server() -> Optional[ProofServer]:
    with _SERVER_LOCK:
        return _SERVER


def reset_server() -> None:
    """Drain + drop the process-wide server (node stop / tests / sim)."""
    global _SERVER
    with _SERVER_LOCK:
        server, _SERVER = _SERVER, None
    if server is not None:
        server.close()


def server_active() -> bool:
    """True when RPC proof queries should ride the coalescer: kill
    switch on AND a server configured."""
    return plane.enabled() and get_server() is not None


def prove_tx(
    tx_loader, height: int, index: int, timeout_s: float = 5.0
):
    """(root, Proof) for tx ``index`` of block ``height`` — THE wrapper
    ``rpc/core.py`` calls.  Coalesced through the server when active;
    a shed, a timeout, or no server at all degrades to the caller's
    serial build (``merkle.proofs_from_byte_slices``), so the response
    is never lost and the kill-switch path is exactly today's serial
    code.  Returns None when the height/index doesn't exist."""
    if server_active():
        try:
            res = get_server().submit("tx", height).result(timeout_s)
            if res is None:
                return None
            root, proofs = res
            if 0 <= index < len(proofs):
                return root, proofs[index]
            return None
        except QueueFullError:
            pstats.record_serial_fallback()
        except FutureTimeoutError:
            pstats.record_serial_fallback()
        except RuntimeError:
            # server torn down under us (stop race): serial fallback
            pstats.record_serial_fallback()
    txs = tx_loader(height)
    if txs is None:
        return None
    txs = [bytes(t) for t in txs]
    if not 0 <= index < len(txs):
        return None
    root, proofs = merkle.proofs_from_byte_slices(txs)
    return root, proofs[index]
