"""Batched Merkle/hash plane + coalesced proof serving.

Three jax-free-at-import layers (docs/proof-serving.md):

  * ``plane``   — the hashing front door every type-layer call site
    uses: routes to the device tree kernel (``ops/sha256_tree``) or the
    serial host reference, bit-identically, behind the
    ``COMETBFT_TPU_PROOFSERVE`` kill switch and a min-batch gate;
  * ``service`` — the proof-query coalescer (bounded queue, one tree
    build per (kind, height) group, LRU cache) that ``rpc/core.py``
    rides for ``tx(prove=True)`` / header / validator-hash traffic;
  * ``stats``   — process-wide counters behind ``cometbft_merkle_*``
    metrics and the ``trace_document()`` proofserve section.
"""

from cometbft_tpu.proofserve import plane, service, stats  # noqa: F401
from cometbft_tpu.proofserve.plane import (  # noqa: F401
    enabled,
    tree_hash,
    tree_proofs,
)
from cometbft_tpu.proofserve.service import (  # noqa: F401
    ProofServer,
    QueueFullError,
    configure,
    get_server,
    prove_tx,
    reset_server,
    server_active,
)
