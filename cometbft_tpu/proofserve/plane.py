"""The hashing front door: route Merkle work to the device tree kernel
or the serial host reference, bit-identically.

Every producer-side hashing call site (``types/block.py`` header/data/
commit hashes, ``types/validator.py`` valset hash, ``state/execution.py``
results hash, ``types/part_set.py`` part proofs) goes through
``tree_hash``/``tree_proofs`` instead of calling ``crypto/merkle.py``
directly — ``scripts/check_hash_callsites.py`` pins that.  The plane then
decides the path:

  * kill switch off (``COMETBFT_TPU_PROOFSERVE=0``) → the exact serial
    reference, restoring pre-plane behavior bit for bit;
  * tiny trees (fewer than ``COMETBFT_TPU_MERKLE_MIN_BATCH`` leaves,
    default 32 — a 14-field header hash, a 4-validator valset) → the
    reference as well: bucket padding + dispatch latency would dwarf the
    14 hashes, and the reference IS the correctness oracle so there is
    nothing to gate;
  * everything else → ``ops/sha256_tree.tree_root``/``tree_proofs``,
    which itself supervises device→host degradation behind the
    ``merkle_device`` breaker.

jax-free at import: the sha256_tree import happens only past the size
gate, and that module imports jax lazily in turn.
"""

from __future__ import annotations

import os

from cometbft_tpu.crypto import merkle

DEFAULT_MIN_BATCH = 32


def enabled() -> bool:
    """Master kill switch for the whole Merkle/hash plane (the proof
    server consults it too): ``COMETBFT_TPU_PROOFSERVE=0`` restores the
    serial host path everywhere, bit for bit."""
    return os.environ.get("COMETBFT_TPU_PROOFSERVE", "1") != "0"


def min_batch() -> int:
    try:
        return int(
            os.environ.get("COMETBFT_TPU_MERKLE_MIN_BATCH", "")
            or DEFAULT_MIN_BATCH
        )
    except ValueError:
        return DEFAULT_MIN_BATCH


def tree_hash(items) -> bytes:
    """Merkle root of ``items`` — bit-identical to
    ``merkle.hash_from_byte_slices`` on every path."""
    items = list(items)
    if not enabled() or len(items) < min_batch():
        return merkle.hash_from_byte_slices(items)
    from cometbft_tpu.ops import sha256_tree

    return sha256_tree.tree_root(items)


def tree_proofs(items):
    """(root, [Proof]) for ``items`` — bit-identical to
    ``merkle.proofs_from_byte_slices`` on every path."""
    items = list(items)
    if not enabled() or len(items) < min_batch():
        return merkle.proofs_from_byte_slices(items)
    from cometbft_tpu.ops import sha256_tree

    return sha256_tree.tree_proofs(items)
