"""Process-wide counters for the tx-ingestion pipeline.

Deliberately free of jax imports, exactly like ``verifysched/stats`` and
``ops/dispatch_stats``: ``libs/metrics.NodeMetrics`` reads these through
callback gauges as ``cometbft_mempool_*`` and a /metrics scrape must
never be the thing that initializes an accelerator backend.  The mempool
(cache dedup, rejections), the ingest coalescer (queue/flush/shed) and
the reactor (per-peer accounting) write them.

Counters (one lock):
  * ``cache_hits`` / ``cache_misses`` — tx LRU cache outcomes at admission
    (a hit is a gossip duplicate that cost no queue slot or app call)
  * ``queue_depth``        — txs waiting in the ingest queue (gauge-style)
  * ``enqueued``           — txs admitted to the ingest queue
  * ``shed_to_sync``       — txs that found the queue full and degraded to
    the per-tx synchronous CheckTx path (a shed costs the batching win,
    never a tx verdict)
  * ``flushes`` / ``flush_txs`` — ingest batches and the txs they carried;
    occupancy = flush_txs / (flushes * batch capacity)
  * ``flush_cap_total``    — summed batch capacity across flushes
  * ``app_batches`` / ``app_batch_txs`` — batched CheckTx round trips
    (admission + recheck) and the requests they carried
  * ``sig_prechecked``     — envelope signatures verified node-side before
    any app round trip
  * ``admitted``           — txs that entered the mempool
  * ``rejected[code]``     — CheckTx rejections by code (app codes plus the
    canonical txingest envelope codes)
  * ``errors[kind]``       — admission errors by kind: ``duplicate`` /
    ``full`` / ``too_large`` / ``pre_check``
  * ``recheck_batches`` / ``recheck_txs`` — post-commit rechecks that rode
    one batched round trip, and the txs re-checked
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()


def _zero() -> dict:
    return {
        "cache_hits": 0,
        "cache_misses": 0,
        "queue_depth": 0,
        "enqueued": 0,
        "shed_to_sync": 0,
        "flushes": 0,
        "flush_txs": 0,
        "flush_cap_total": 0,
        "app_batches": 0,
        "app_batch_txs": 0,
        "sig_prechecked": 0,
        "admitted": 0,
        "rejected": {},
        "errors": {},
        "recheck_batches": 0,
        "recheck_txs": 0,
    }


_STATS = _zero()


def record_cache(hit: bool) -> None:
    with _LOCK:
        _STATS["cache_hits" if hit else "cache_misses"] += 1


def record_enqueue(n: int = 1) -> None:
    with _LOCK:
        _STATS["enqueued"] += n
        _STATS["queue_depth"] += n


def record_shed_sync(n: int = 1) -> None:
    with _LOCK:
        _STATS["shed_to_sync"] += n


def record_flush(txs: int, cap: int) -> None:
    with _LOCK:
        _STATS["flushes"] += 1
        _STATS["flush_txs"] += int(txs)
        _STATS["flush_cap_total"] += int(cap)
        _STATS["queue_depth"] = max(0, _STATS["queue_depth"] - int(txs))


def record_app_batch(txs: int) -> None:
    with _LOCK:
        _STATS["app_batches"] += 1
        _STATS["app_batch_txs"] += int(txs)


def record_sig_precheck(n: int) -> None:
    if n:
        with _LOCK:
            _STATS["sig_prechecked"] += int(n)


def record_admitted(n: int = 1) -> None:
    with _LOCK:
        _STATS["admitted"] += n


def record_reject(code: int) -> None:
    with _LOCK:
        key = str(int(code))
        _STATS["rejected"][key] = _STATS["rejected"].get(key, 0) + 1


def record_error(kind: str) -> None:
    with _LOCK:
        _STATS["errors"][kind] = _STATS["errors"].get(kind, 0) + 1


def record_recheck(txs: int) -> None:
    with _LOCK:
        _STATS["recheck_batches"] += 1
        _STATS["recheck_txs"] += int(txs)


def queue_depth() -> int:
    with _LOCK:
        return _STATS["queue_depth"]


def snapshot() -> dict:
    """Deep-enough copy for metrics/tests; adds derived aggregates."""
    with _LOCK:
        out = {
            k: (dict(v) if isinstance(v, dict) else v)
            for k, v in _STATS.items()
        }
    total = out["cache_hits"] + out["cache_misses"]
    out["cache_hit_rate"] = out["cache_hits"] / total if total else 0.0
    out["batch_occupancy"] = (
        out["flush_txs"] / out["flush_cap_total"]
        if out["flush_cap_total"]
        else 0.0
    )
    out["rejected_total"] = sum(out["rejected"].values())
    return out


def reset() -> None:
    global _STATS
    with _LOCK:
        _STATS = _zero()
