"""Batched transaction ingestion (docs/tx-ingest.md).

The first *user-facing* workload on the crypto seam: signed-tx envelopes
(``envelope``), the ``SigVerifyingApp`` ABCI middleware that hoists tx
signature checks out of applications (``middleware``), and the ingest
coalescer that admits whole gossip bursts through one batched CheckTx
round trip with envelope signatures verified as the verifysched bulk
class (``coalescer``).  ``stats`` holds the jax-free process-wide
counters ``libs/metrics`` exposes as ``cometbft_mempool_*``.

Kill switch: ``COMETBFT_TPU_TXINGEST=0`` restores the per-tx
``check_tx`` admission path bit-for-bit.
"""

from cometbft_tpu.txingest import envelope, stats
from cometbft_tpu.txingest.coalescer import (
    IngestCoalescer,
    ingest_enabled,
    ingest_active,
)
from cometbft_tpu.txingest.envelope import (
    CODE_BAD_ENVELOPE,
    CODE_BAD_SIGNATURE,
    CODE_STALE_NONCE,
    CODESPACE,
    Envelope,
    EnvelopeError,
    decode,
    encode,
    is_envelope,
    sign_tx,
)
from cometbft_tpu.txingest.middleware import SigVerifyingApp

__all__ = [
    "CODE_BAD_ENVELOPE",
    "CODE_BAD_SIGNATURE",
    "CODE_STALE_NONCE",
    "CODESPACE",
    "Envelope",
    "EnvelopeError",
    "IngestCoalescer",
    "SigVerifyingApp",
    "decode",
    "encode",
    "envelope",
    "ingest_active",
    "ingest_enabled",
    "is_envelope",
    "sign_tx",
    "stats",
]
