"""``SigVerifyingApp`` — ABCI middleware hoisting tx signature checks
out of the application and onto the crypto seam (docs/tx-ingest.md).

Wrap any ``Application`` and the app stops caring about envelopes: the
middleware verifies signed-tx envelopes (``txingest/envelope.py``) on the
mempool and consensus connections and hands the *payload* to the inner
app, so a kvstore that understands ``key=value`` serves signed traffic
unchanged.  Verification rides the shared batch machinery —
``check_txs`` verifies a whole gossip burst's signatures in one pass
through the verifysched bulk class, and because every verdict goes
through the signature cache the apply-time re-checks (process-proposal,
finalize) resolve from cache instead of paying a second verification.

The middleware advertises itself via ``InfoResponse.envelope_sig_verified``
so the ingest coalescer knows it may pre-verify envelope signatures
node-side and reject forgeries with the SAME canonical codes before any
app round trip (the differential-parity contract both layers share).
"""

from __future__ import annotations

from typing import Optional

from cometbft_tpu.abci import types as at
from cometbft_tpu.abci.application import Application
from cometbft_tpu.txingest import envelope as ev


class SigVerifyingApp(Application):
    """Envelope-verifying wrapper around an inner ``Application``.

    ``require_envelope=True`` additionally rejects plain (non-envelope)
    txs at CheckTx — for chains where every user tx must be signed;
    the default passes plain txs through untouched so the wrapper can be
    dropped in front of existing traffic.
    """

    def __init__(self, app: Application, require_envelope: bool = False):
        self.app = app
        self.require_envelope = require_envelope

    # -- classification -----------------------------------------------------

    @staticmethod
    def _classify(tx: bytes):
        """('plain', None) | ('env', Envelope) | ('bad', reason)."""
        if not ev.is_envelope(tx):
            return "plain", None
        try:
            return "env", ev.decode(tx)
        except ev.EnvelopeError as e:
            return "bad", str(e)

    def _payload_or_reject(
        self, tx: bytes, verified: Optional[bool] = None
    ) -> "tuple[Optional[bytes], Optional[at.CheckTxResponse]]":
        """The inner-app payload for ``tx``, or the canonical rejection.
        ``verified`` carries a batch-verification verdict when the caller
        already has one; ``None`` means verify here (cache-through)."""
        kind, parsed = self._classify(tx)
        if kind == "bad":
            return None, ev.reject_bad_envelope(parsed)
        if kind == "plain":
            if self.require_envelope:
                return None, ev.reject_bad_envelope("envelope required")
            return tx, None
        if verified is None:
            verified = ev.verify_envelopes([parsed])[0]
        if not verified:
            return None, ev.reject_bad_signature()
        return parsed.payload, None

    # -- info ---------------------------------------------------------------

    def info(self, req):
        r = self.app.info(req)
        r.envelope_sig_verified = True
        return r

    # -- mempool connection -------------------------------------------------

    def check_tx(self, req):
        payload, reject = self._payload_or_reject(req.tx)
        if reject is not None:
            return reject
        return self.app.check_tx(at.CheckTxRequest(tx=payload, type_=req.type_))

    def check_txs(self, req):
        """One signature pass for the whole batch, then one inner-app
        batch for the survivors — the round-trip shape batched admission
        exists for."""
        kinds = [self._classify(r.tx) for r in req.requests]
        verdicts = ev.verify_envelopes(
            [p if k == "env" else None for k, p in kinds]
        )
        out: "list[Optional[at.CheckTxResponse]]" = [None] * len(req.requests)
        inner: "list[at.CheckTxRequest]" = []
        inner_ix: "list[int]" = []
        for i, (r, (kind, parsed)) in enumerate(zip(req.requests, kinds)):
            if kind == "bad":
                out[i] = ev.reject_bad_envelope(parsed)
            elif kind == "plain":
                if self.require_envelope:
                    out[i] = ev.reject_bad_envelope("envelope required")
                else:
                    inner.append(r)
                    inner_ix.append(i)
            elif not verdicts[i]:
                out[i] = ev.reject_bad_signature()
            else:
                inner.append(
                    at.CheckTxRequest(tx=parsed.payload, type_=r.type_)
                )
                inner_ix.append(i)
        if inner:
            resp = self.app.check_txs(at.CheckTxsRequest(requests=inner))
            for i, r in zip(inner_ix, resp.responses):
                out[i] = r
        return at.CheckTxsResponse(responses=out)

    # -- consensus connection -----------------------------------------------

    def prepare_proposal(self, req):
        """The inner app selects/orders payloads; selections map back to
        their envelope bytes (a payload appearing in several envelopes
        maps in arrival order).  Payloads the inner app invented — it may
        inject its own txs — pass through unwrapped."""
        payloads: "list[bytes]" = []
        by_payload: "dict[bytes, list[bytes]]" = {}
        for tx in req.txs:
            kind, parsed = self._classify(tx)
            if kind == "env":
                payloads.append(parsed.payload)
                by_payload.setdefault(parsed.payload, []).append(tx)
            else:
                # bad envelopes stay as raw bytes: the inner app sees the
                # same txs FinalizeBlock would, and drops what it can't parse
                payloads.append(tx)
        inner = self.app.prepare_proposal(
            at.PrepareProposalRequest(
                max_tx_bytes=req.max_tx_bytes,
                txs=payloads,
                local_last_commit=req.local_last_commit,
                misbehavior=req.misbehavior,
                height=req.height,
                time_unix_ns=req.time_unix_ns,
                next_validators_hash=req.next_validators_hash,
                proposer_address=req.proposer_address,
            )
        )
        out = []
        for tx in inner.txs:
            wrapped = by_payload.get(tx)
            out.append(wrapped.pop(0) if wrapped else tx)
        return at.PrepareProposalResponse(txs=out)

    def process_proposal(self, req):
        """A block carrying a malformed or forged envelope is rejected
        outright — verification batches through the seam (cache hits when
        CheckTx already saw these txs)."""
        kinds = [self._classify(tx) for tx in req.txs]
        if any(k == "bad" for k, _ in kinds):
            return at.ProcessProposalResponse(status=at.PROPOSAL_STATUS_REJECT)
        if self.require_envelope and any(k == "plain" for k, _ in kinds):
            return at.ProcessProposalResponse(status=at.PROPOSAL_STATUS_REJECT)
        verdicts = ev.verify_envelopes(
            [p if k == "env" else None for k, p in kinds]
        )
        if any(k == "env" and not v for (k, _), v in zip(kinds, verdicts)):
            return at.ProcessProposalResponse(status=at.PROPOSAL_STATUS_REJECT)
        return self.app.process_proposal(
            at.ProcessProposalRequest(
                txs=[
                    p.payload if k == "env" else tx
                    for tx, (k, p) in zip(req.txs, kinds)
                ],
                proposed_last_commit=req.proposed_last_commit,
                misbehavior=req.misbehavior,
                hash=req.hash,
                height=req.height,
                time_unix_ns=req.time_unix_ns,
                next_validators_hash=req.next_validators_hash,
                proposer_address=req.proposer_address,
            )
        )

    def finalize_block(self, req):
        """Execute payloads.  A decided block can still carry a bad
        envelope (a Byzantine quorum can decide anything); those txs get
        the canonical rejection code as their ExecTxResult and are NEVER
        executed — deterministic across nodes because the verdict depends
        only on the tx bytes."""
        kinds = [self._classify(tx) for tx in req.txs]
        verdicts = ev.verify_envelopes(
            [p if k == "env" else None for k, p in kinds]
        )
        results: "list[Optional[at.ExecTxResult]]" = [None] * len(req.txs)
        inner_txs: "list[bytes]" = []
        inner_ix: "list[int]" = []
        for i, (tx, (kind, parsed)) in enumerate(zip(req.txs, kinds)):
            if kind == "bad":
                results[i] = at.ExecTxResult(
                    code=ev.CODE_BAD_ENVELOPE,
                    log="malformed tx envelope",
                    codespace=ev.CODESPACE,
                )
            elif kind == "plain":
                if self.require_envelope:
                    results[i] = at.ExecTxResult(
                        code=ev.CODE_BAD_ENVELOPE,
                        log="envelope required",
                        codespace=ev.CODESPACE,
                    )
                else:
                    inner_txs.append(tx)
                    inner_ix.append(i)
            elif not verdicts[i]:
                results[i] = at.ExecTxResult(
                    code=ev.CODE_BAD_SIGNATURE,
                    log="invalid tx envelope signature",
                    codespace=ev.CODESPACE,
                )
            else:
                inner_txs.append(parsed.payload)
                inner_ix.append(i)
        inner = self.app.finalize_block(
            at.FinalizeBlockRequest(
                txs=inner_txs,
                decided_last_commit=req.decided_last_commit,
                misbehavior=req.misbehavior,
                hash=req.hash,
                height=req.height,
                time_unix_ns=req.time_unix_ns,
                next_validators_hash=req.next_validators_hash,
                proposer_address=req.proposer_address,
                syncing_to_height=req.syncing_to_height,
            )
        )
        for i, r in zip(inner_ix, inner.tx_results):
            results[i] = r
        return at.FinalizeBlockResponse(
            events=inner.events,
            tx_results=results,
            validator_updates=inner.validator_updates,
            consensus_param_updates=inner.consensus_param_updates,
            app_hash=inner.app_hash,
            next_block_delay_ms=inner.next_block_delay_ms,
        )

    # -- pure delegation ----------------------------------------------------

    def query(self, req):
        return self.app.query(req)

    def init_chain(self, req):
        return self.app.init_chain(req)

    def extend_vote(self, req):
        return self.app.extend_vote(req)

    def verify_vote_extension(self, req):
        return self.app.verify_vote_extension(req)

    def commit(self, req):
        return self.app.commit(req)

    def list_snapshots(self, req):
        return self.app.list_snapshots(req)

    def offer_snapshot(self, req):
        return self.app.offer_snapshot(req)

    def load_snapshot_chunk(self, req):
        return self.app.load_snapshot_chunk(req)

    def apply_snapshot_chunk(self, req):
        return self.app.apply_snapshot_chunk(req)
