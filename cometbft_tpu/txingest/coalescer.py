"""Ingest coalescer: gossip burst -> one batched CheckTx round trip.

The mempool reactor hands every incoming gossip tx to ``submit``; a
flusher drains the bounded queue when the oldest tx has waited
``COMETBFT_TPU_TXINGEST_FLUSH_US`` (default 5000) or when
``COMETBFT_TPU_TXINGEST_BATCH`` (default 256) txs are pending, and
admits the whole batch through ``CListMempool.check_tx_batch`` — cache
dedup before any queue slot, envelope signatures verified as the
verifysched BULK class, one ``check_txs`` app round trip for the
survivors (docs/tx-ingest.md).

Degradation is always to the per-tx synchronous path, never to a dropped
verdict: a full ingest queue (``COMETBFT_TPU_TXINGEST_QUEUE``, default
4096) sheds the submission to ``mempool.check_tx``; the kill switch
``COMETBFT_TPU_TXINGEST=0`` disables the pipeline entirely, restoring
per-tx admission bit-for-bit.  Activation additionally gates on the same
trusted-backend check as the verification scheduler: a CPU-backend node
has no dispatch floor to amortize, so it keeps today's synchronous
behavior untouched.

Thread model: a daemon flusher thread in production
(``start_thread=True``); the deterministic simulator builds coalescers
with ``start_thread=False`` and drives ``flush_now`` explicitly from
scripted virtual-time actions.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from cometbft_tpu.libs import tracing
from cometbft_tpu.txingest import stats

logger = logging.getLogger("cometbft_tpu.txingest")

DEFAULT_BATCH = 256
DEFAULT_FLUSH_US = 5000.0
DEFAULT_QUEUE_CAP = 4096
DEFAULT_NONCE_LRU = 4096


class _NonceLRU:
    """Last-seen *verified* envelope nonce per sender pubkey, LRU-bounded.

    Only nonces whose signatures actually verified are recorded — a forged
    envelope carrying a huge nonce must not be able to poison a sender's
    record and censor their future traffic.  A replayed or re-signed
    envelope at or below the recorded nonce dies at ingest with the
    canonical ``CODE_STALE_NONCE`` before costing a queue slot, a
    signature check, or an app round trip.

    Locked: reactor threads consult it at submit while the ingest thread
    records at flush, and OrderedDict relinking is not thread-safe."""

    def __init__(self, cap: int):
        self.cap = max(1, cap)
        self._d: "OrderedDict[bytes, int]" = OrderedDict()
        self._lock = threading.Lock()

    def last(self, pub: bytes) -> Optional[int]:
        with self._lock:
            v = self._d.get(pub)
            if v is not None:
                self._d.move_to_end(pub)
            return v

    def note(self, pub: bytes, nonce: int) -> None:
        with self._lock:
            cur = self._d.get(pub)
            if cur is None or nonce > cur:
                self._d[pub] = nonce
            self._d.move_to_end(pub)
            while len(self._d) > self.cap:
                self._d.popitem(last=False)


def ingest_enabled() -> bool:
    return os.environ.get("COMETBFT_TPU_TXINGEST", "1") != "0"


def ingest_active() -> bool:
    """Kill switch on AND the accelerator batch backend trusted — the
    scheduler's own gate (never triggers the jax auto-probe from a
    gossip-path check)."""
    from cometbft_tpu.verifysched import backend_trusted

    return ingest_enabled() and backend_trusted()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class IngestCoalescer:
    """Bounded ingest queue + deadline/size flusher over one mempool."""

    def __init__(
        self,
        mempool,
        batch_max: Optional[int] = None,
        flush_us: Optional[float] = None,
        queue_cap: Optional[int] = None,
        start_thread: bool = True,
        on_result: Optional[Callable[[str, object], None]] = None,
    ):
        self.mempool = mempool
        self.batch_max = max(
            1,
            batch_max
            if batch_max is not None
            else _env_int("COMETBFT_TPU_TXINGEST_BATCH", DEFAULT_BATCH),
        )
        self.flush_s = (
            max(
                0.0,
                flush_us
                if flush_us is not None
                else _env_float("COMETBFT_TPU_TXINGEST_FLUSH_US", DEFAULT_FLUSH_US),
            )
            / 1e6
        )
        self.queue_cap = max(
            1,
            queue_cap
            if queue_cap is not None
            else _env_int("COMETBFT_TPU_TXINGEST_QUEUE", DEFAULT_QUEUE_CAP),
        )
        # flush-time outcome callback: (sender, CheckTxResponse-or-
        # MempoolError) — the reactor uses it for per-peer accounting
        self.on_result = on_result
        self._nonces = _NonceLRU(
            _env_int("COMETBFT_TPU_TXINGEST_NONCES", DEFAULT_NONCE_LRU)
        )
        self._cond = threading.Condition()
        self._q: "deque[tuple[bytes, str, float]]" = deque()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._start_thread = start_thread

    # -- submission ---------------------------------------------------------

    def active(self) -> bool:
        return ingest_active()

    def pending(self) -> int:
        with self._cond:
            return len(self._q)

    def submit(self, tx: bytes, sender: str = ""):
        """Queue one gossiped tx for batched admission.

        Returns ``None`` when queued (verdict arrives at flush time via
        ``on_result``) or the ``CheckTxResponse`` when the tx took a
        synchronous path (pipeline inactive, or shed by the queue bound).
        Raises the same ``MempoolError`` family as ``check_tx`` for
        synchronous rejections — including ``TxInCacheError`` for the
        pre-queue cache dedup, which is the common gossip-duplicate case
        and costs neither a queue slot nor an app call."""
        from cometbft_tpu.mempool.clist_mempool import TxInCacheError

        if not self.active():
            return self.mempool.check_tx(tx, sender=sender)
        # dedup BEFORE taking a queue slot, with the same recency refresh
        # cache.push gives duplicates on the per-tx path
        key = self.mempool.tx_key(tx)
        if self.mempool.cache.touch(key):
            self.mempool.note_duplicate(key, sender)
            stats.record_cache(True)
            stats.record_error("duplicate")
            raise TxInCacheError()
        stale, pn = self._check_nonce(tx)
        if stale is not None:
            return stale
        with self._cond:
            if not self._stopped and len(self._q) < self.queue_cap:
                # the key and decoded (pubkey, nonce) ride along so
                # flush-time admission neither hashes nor decodes the tx
                # a second time
                self._q.append((tx, sender, key, time.perf_counter(), pn))
                stats.record_enqueue()
                if self._start_thread and (
                    self._thread is None or not self._thread.is_alive()
                ):
                    self._thread = threading.Thread(
                        target=self._run, name="tx-ingest", daemon=True
                    )
                    self._thread.start()
                self._cond.notify_all()
                return None
        # queue full (or closing): shed to the per-tx synchronous path —
        # shedding costs the batching win, never a tx verdict
        stats.record_shed_sync()
        tracing.record_anomaly("ingest_shed", queue_cap=self.queue_cap)
        with tracing.span("txingest.shed_sync"):
            res = self.mempool.check_tx(tx, sender=sender)
        self._note_verified_nonce(pn, res)
        return res

    # -- per-sender nonce replay protection ---------------------------------

    def _check_nonce(self, tx: bytes):
        """Canonical ``CODE_STALE_NONCE`` rejection for a replayed or
        re-signed envelope at/below the sender's last verified nonce, as
        ``(rejection-or-None, (pubkey, nonce)-or-None)`` — the decoded pair
        rides the queue so flush never re-decodes.  Only meaningful behind
        an envelope-aware app (same gate as the batched sig precheck)."""
        if not getattr(self.mempool, "envelope_aware", False):
            return None, None
        from cometbft_tpu.txingest import envelope as ev

        if not ev.is_envelope(tx):
            return None, None
        try:
            env = ev.decode(tx)
        except ev.EnvelopeError:
            return None, None  # malformed: the canonical 101 path downstream
        last = self._nonces.last(env.pubkey)
        if last is not None and env.nonce <= last:
            stats.record_reject(ev.CODE_STALE_NONCE)
            stats.record_error("stale_nonce")
            return ev.reject_stale_nonce(env.nonce, last), None
        return None, (env.pubkey, env.nonce)

    def _note_verified_nonce(self, pn, res) -> None:
        """Record a (pubkey, nonce) pair once its tx VERIFIED (res.ok)."""
        if pn is None:
            return
        from cometbft_tpu.abci import types as at

        if isinstance(res, at.CheckTxResponse) and res.ok:
            self._nonces.note(*pn)

    # -- flushing -----------------------------------------------------------

    def flush_now(self) -> int:
        """Drain everything queued, in batch_max chunks.  Synchronous —
        the simulator's deterministic drive path, and the thread's flush
        body."""
        total = 0
        while True:
            with self._cond:
                if not self._q:
                    return total
                items = [
                    self._q.popleft()
                    for _ in range(min(self.batch_max, len(self._q)))
                ]
            self._flush_chunk(items)
            total += len(items)

    def _flush_chunk(self, items) -> None:
        txs = [it[0] for it in items]
        senders = [it[1] for it in items]
        keys = [it[2] for it in items]
        stats.record_flush(len(items), self.batch_max)
        try:
            with tracing.span(
                "txingest.flush", txs=len(items), cap=self.batch_max
            ):
                results = self.mempool.check_tx_batch(txs, senders, keys=keys)
        except Exception:  # noqa: BLE001 — the flusher must survive
            logger.exception(
                "batched admission failed; re-admitting %d txs per-tx",
                len(txs),
            )
            results = []
            for tx, sender in zip(txs, senders):
                try:
                    results.append(self.mempool.check_tx(tx, sender=sender))
                except Exception as e:  # noqa: BLE001 — MempoolError family
                    results.append(e)
        for it, res in zip(items, results):
            self._note_verified_nonce(it[4], res)
        if self.on_result is not None:
            for sender, res in zip(senders, results):
                try:
                    self.on_result(sender, res)
                except Exception:  # noqa: BLE001 — accounting only
                    pass

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and not self._q:
                    self._cond.wait()
                if self._stopped and not self._q:
                    return
                while not self._stopped and len(self._q) < self.batch_max:
                    oldest = self._q[0][3] if self._q else None
                    if oldest is None:
                        break
                    remain = oldest + self.flush_s - time.perf_counter()
                    if remain <= 0:
                        break
                    self._cond.wait(remain)
                if not self._q:
                    continue
            self.flush_now()

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop accepting queued work and drain what's left — a tx handed
        to the coalescer always reaches the mempool exactly once."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
            t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout_s)
        self.flush_now()
