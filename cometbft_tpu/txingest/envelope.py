"""Signed-transaction envelope codec (docs/tx-ingest.md).

A fixed-layout, prefix-tagged wrapper any app payload can ride in:

    magic(4) | key_type(1) | nonce(8, BE) | pubkey(32|33) | sig(64) | payload

``key_type`` 0x01 is ed25519 (32-byte pubkey — rides the TPU verify
seam), 0x02 is secp256k1 (33-byte compressed pubkey — host/device ECDSA
path).  The signature covers a domain-separated preimage (``sign_bytes``)
binding key type, sender pubkey, nonce and payload, so an envelope can't
be replayed under a different key or nonce without re-signing.

The canonical CheckTx rejection responses live here too: the
``SigVerifyingApp`` middleware (app side) and the ingest coalescer's
mempool pre-verification (node side) both reject through
``reject_bad_envelope`` / ``reject_bad_signature``, which is what makes
batched admission byte-identical to the per-tx path — same codes, same
codespace, same log strings, whichever layer catches the forgery first.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from cometbft_tpu.abci import types as at

# First byte deliberately non-ASCII: no key=value style app payload — nor
# any UTF-8 text — starts with 0xD7, so plain txs can never be mistaken
# for an envelope.
MAGIC = b"\xd7TX1"

KEY_ED25519 = 0x01
KEY_SECP256K1 = 0x02

_PUB_LEN = {KEY_ED25519: 32, KEY_SECP256K1: 33}
_KEY_NAMES = {KEY_ED25519: "ed25519", KEY_SECP256K1: "secp256k1"}
SIG_LEN = 64
_NONCE_LEN = 8
_HEADER_LEN = len(MAGIC) + 1 + _NONCE_LEN

_DOMAIN = b"cometbft-tpu/tx/v1"

CODESPACE = "txingest"
CODE_BAD_ENVELOPE = 101
CODE_BAD_SIGNATURE = 102
CODE_STALE_NONCE = 103


class EnvelopeError(Exception):
    """Malformed envelope bytes (magic present, structure invalid)."""


@dataclass(frozen=True)
class Envelope:
    key_type: int
    pubkey: bytes
    nonce: int
    payload: bytes
    signature: bytes

    def sign_bytes(self) -> bytes:
        return sign_bytes(self.key_type, self.pubkey, self.nonce, self.payload)

    def pub_key(self):
        """The typed key object (``crypto.keys``) for this sender."""
        from cometbft_tpu.crypto import keys as ck

        if self.key_type == KEY_ED25519:
            return ck.Ed25519PubKey(self.pubkey)
        from cometbft_tpu.crypto.secp256k1 import Secp256k1PubKey

        return Secp256k1PubKey(self.pubkey)


def sign_bytes(key_type: int, pubkey: bytes, nonce: int, payload: bytes) -> bytes:
    return b"".join(
        (
            _DOMAIN,
            bytes([key_type]),
            nonce.to_bytes(_NONCE_LEN, "big"),
            pubkey,
            payload,
        )
    )


def is_envelope(tx: bytes) -> bool:
    return tx.startswith(MAGIC)


def encode(env: Envelope) -> bytes:
    if env.key_type not in _PUB_LEN:
        raise EnvelopeError(f"unknown key type {env.key_type:#x}")
    if len(env.pubkey) != _PUB_LEN[env.key_type]:
        raise EnvelopeError(
            f"{_KEY_NAMES[env.key_type]} pubkey must be "
            f"{_PUB_LEN[env.key_type]} bytes"
        )
    if len(env.signature) != SIG_LEN:
        raise EnvelopeError(f"signature must be {SIG_LEN} bytes")
    if not 0 <= env.nonce < 1 << (8 * _NONCE_LEN):
        raise EnvelopeError("nonce out of range")
    return b"".join(
        (
            MAGIC,
            bytes([env.key_type]),
            env.nonce.to_bytes(_NONCE_LEN, "big"),
            env.pubkey,
            env.signature,
            env.payload,
        )
    )


def decode(tx: bytes) -> Envelope:
    """Parse envelope bytes; raises ``EnvelopeError`` on any structural
    problem.  Callers gate on ``is_envelope`` first — a tx without the
    magic prefix is a plain app tx, not a malformed envelope."""
    if not is_envelope(tx):
        raise EnvelopeError("missing envelope magic")
    if len(tx) < _HEADER_LEN + 1:
        raise EnvelopeError("truncated envelope header")
    key_type = tx[len(MAGIC)]
    pub_len = _PUB_LEN.get(key_type)
    if pub_len is None:
        raise EnvelopeError(f"unknown key type {key_type:#x}")
    nonce = int.from_bytes(tx[len(MAGIC) + 1 : _HEADER_LEN], "big")
    body = tx[_HEADER_LEN:]
    if len(body) < pub_len + SIG_LEN:
        raise EnvelopeError("truncated envelope body")
    return Envelope(
        key_type=key_type,
        pubkey=body[:pub_len],
        nonce=nonce,
        payload=body[pub_len + SIG_LEN :],
        signature=body[pub_len : pub_len + SIG_LEN],
    )


def sign_tx(priv_key, payload: bytes, nonce: int = 0) -> bytes:
    """Build signed envelope bytes for ``payload`` under ``priv_key``
    (``Ed25519PrivKey`` or ``Secp256k1PrivKey``)."""
    from cometbft_tpu.crypto import keys as ck

    key_type = (
        KEY_ED25519
        if getattr(priv_key, "type_", None) == ck.ED25519_KEY_TYPE
        else KEY_SECP256K1
    )
    pub = priv_key.pub_key().bytes()
    sig = priv_key.sign(sign_bytes(key_type, pub, nonce, payload))
    return encode(
        Envelope(
            key_type=key_type,
            pubkey=pub,
            nonce=nonce,
            payload=payload,
            signature=sig,
        )
    )


# -- canonical rejections ----------------------------------------------------


def reject_bad_envelope(reason: str) -> at.CheckTxResponse:
    return at.CheckTxResponse(
        code=CODE_BAD_ENVELOPE,
        log=f"malformed tx envelope: {reason}",
        codespace=CODESPACE,
    )


def reject_bad_signature() -> at.CheckTxResponse:
    return at.CheckTxResponse(
        code=CODE_BAD_SIGNATURE,
        log="invalid tx envelope signature",
        codespace=CODESPACE,
    )


def reject_stale_nonce(nonce: int, last_seen: int) -> at.CheckTxResponse:
    return at.CheckTxResponse(
        code=CODE_STALE_NONCE,
        log=f"stale envelope nonce {nonce} (last seen {last_seen})",
        codespace=CODESPACE,
    )


# -- batched verification ----------------------------------------------------


def verify_envelopes(envs: Sequence[Optional[Envelope]]) -> "list[bool]":
    """Batch-verify envelope signatures on the crypto seam: ed25519
    entries ride the verifysched BULK class (shed entries degrade to a
    per-item synchronous host verify — shedding costs the batching win,
    never a verdict), secp256k1 entries verify on their own host/device
    path, and every verdict goes through the signature cache so the
    apply-time re-check (middleware, process-proposal) is near-free.
    ``None`` entries (non-envelope or malformed txs the caller already
    classified) come back ``False`` placeholders."""
    idx = [i for i, e in enumerate(envs) if e is not None]
    out = [False] * len(envs)
    if not idx:
        return out
    from cometbft_tpu import verifysched

    with verifysched.priority_class(verifysched.PRIO_MEMPOOL):
        bits = verifysched.verify_many_cached(
            [envs[i].pub_key() for i in idx],
            [envs[i].sign_bytes() for i in idx],
            [envs[i].signature for i in idx],
            priority=verifysched.PRIO_MEMPOOL,
        )
    for i, b in zip(idx, bits):
        out[i] = bool(b)
    return out
