"""Key interfaces and the Ed25519 implementation.

Mirrors the reference seam (crypto/crypto.go:22-52): ``PubKey`` /
``PrivKey`` with 20-byte SHA-256-truncated addresses, plus a key-type
registry used by genesis validation (reference: internal/keytypes).

Single verification uses a two-tier strategy: the C-speed `cryptography`
library first (strict RFC 8032 — acceptance there implies ZIP-215
acceptance, since the cofactorless equation implies the cofactored one),
falling back to the pure-Python ZIP-215 oracle for the edge cases the
strict verifier rejects.
"""

from __future__ import annotations

from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ed25519 as _lib_ed25519
except ImportError:  # no C-speed verifier: pure-Python ZIP-215 only
    InvalidSignature = None
    _lib_ed25519 = None

from cometbft_tpu.crypto import ed25519_ref, tmhash

ED25519_KEY_TYPE = "ed25519"
SECP256K1_KEY_TYPE = "secp256k1"
BLS12381_KEY_TYPE = "bls12_381"


@dataclass(frozen=True)
class Ed25519PubKey:
    data: bytes  # 32-byte compressed point

    type_ = ED25519_KEY_TYPE

    def __post_init__(self):
        if len(self.data) != 32:
            raise ValueError("ed25519 pubkey must be 32 bytes")

    def address(self) -> bytes:
        # memoized: address() sits on hot paths (validator lookups, proposer
        # rotation) — bypass the frozen-dataclass setattr via __dict__.
        addr = self.__dict__.get("_addr")
        if addr is None:
            addr = tmhash.sum_truncated(self.data)
            self.__dict__["_addr"] = addr
        return addr

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != 64:
            return False
        if _lib_ed25519 is None:
            return ed25519_ref.verify_zip215(self.data, msg, sig)
        try:
            _lib_ed25519.Ed25519PublicKey.from_public_bytes(self.data).verify(
                sig, msg
            )
            return True
        except (InvalidSignature, ValueError):
            # Strict verifier rejected: may still be ZIP-215-valid
            # (small-order / non-canonical encodings, cofactored equation).
            return ed25519_ref.verify_zip215(self.data, msg, sig)

    def bytes(self) -> bytes:
        return self.data


@dataclass(frozen=True)
class Ed25519PrivKey:
    seed: bytes  # 32-byte seed

    type_ = ED25519_KEY_TYPE

    @staticmethod
    def generate() -> "Ed25519PrivKey":
        return Ed25519PrivKey(ed25519_ref.generate_seed())

    @staticmethod
    def from_seed(seed: bytes) -> "Ed25519PrivKey":
        return Ed25519PrivKey(seed)

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(ed25519_ref.pubkey_from_seed(self.seed))

    def sign(self, msg: bytes) -> bytes:
        return ed25519_ref.sign(self.seed, msg)

    def bytes(self) -> bytes:
        return self.seed


@dataclass(frozen=True)
class Bls12381PubKey:
    """96-byte uncompressed-G1 public key (min-pubkey-size convention).

    Reference: crypto/bls12381/key_bls12381.go:150-216 (blst-backed) and
    const.go PubKeySize=96; implementation is the from-spec pure-python
    pairing in cometbft_tpu.crypto.bls12381."""

    data: bytes

    type_ = BLS12381_KEY_TYPE

    def __post_init__(self):
        if len(self.data) != 96:
            raise ValueError("bls12_381 pubkey must be 96 bytes")

    def address(self) -> bytes:
        addr = self.__dict__.get("_addr")
        if addr is None:
            addr = tmhash.sum_truncated(self.data)
            self.__dict__["_addr"] = addr
        return addr

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        from cometbft_tpu.crypto import bls12381 as _bls

        if len(sig) != _bls.SIGNATURE_SIZE:
            return False
        return _bls.verify(self.data, msg, sig)

    def bytes(self) -> bytes:
        return self.data


@dataclass(frozen=True)
class Bls12381PrivKey:
    """32-byte BLS secret scalar (big-endian), reference PrivKey.Bytes."""

    data: bytes

    type_ = BLS12381_KEY_TYPE

    @staticmethod
    def generate() -> "Bls12381PrivKey":
        from cometbft_tpu.crypto import bls12381 as _bls

        return Bls12381PrivKey(_bls.sk_to_bytes(_bls.gen_privkey()))

    @staticmethod
    def from_secret(secret: bytes) -> "Bls12381PrivKey":
        """Reference GenPrivKeyFromSecret (key_bls12381.go:66-74)."""
        from cometbft_tpu.crypto import bls12381 as _bls

        return Bls12381PrivKey(
            _bls.sk_to_bytes(_bls.gen_privkey_from_secret(secret))
        )

    def _sk(self) -> int:
        from cometbft_tpu.crypto import bls12381 as _bls

        sk = _bls.sk_from_bytes(self.data)
        if sk is None:
            raise ValueError("invalid bls12_381 private key bytes")
        return sk

    def pub_key(self) -> Bls12381PubKey:
        from cometbft_tpu.crypto import bls12381 as _bls

        return Bls12381PubKey(_bls.pubkey(self._sk()))

    def sign(self, msg: bytes) -> bytes:
        from cometbft_tpu.crypto import bls12381 as _bls

        return _bls.sign(self._sk(), msg)

    def bytes(self) -> bytes:
        return self.data


def pub_key_from_type(key_type: str, data: bytes):
    if key_type == ED25519_KEY_TYPE:
        return Ed25519PubKey(data)
    if key_type == SECP256K1_KEY_TYPE:
        from cometbft_tpu.crypto.secp256k1 import Secp256k1PubKey

        return Secp256k1PubKey(data)
    if key_type == BLS12381_KEY_TYPE:
        return Bls12381PubKey(data)
    raise ValueError(f"unsupported key type: {key_type}")


def priv_key_generate(key_type: str = ED25519_KEY_TYPE):
    """Reference: internal/keytypes registry + privval key generation."""
    if key_type == ED25519_KEY_TYPE:
        return Ed25519PrivKey.generate()
    if key_type == SECP256K1_KEY_TYPE:
        from cometbft_tpu.crypto.secp256k1 import Secp256k1PrivKey

        return Secp256k1PrivKey.generate()
    if key_type == BLS12381_KEY_TYPE:
        return Bls12381PrivKey.generate()
    raise ValueError(f"unsupported key type: {key_type}")


def supported_key_types() -> list[str]:
    """All three key types the reference registers (internal/keytypes
    with the bls12381 build tag enabled; crypto/bls12381/const.go)."""
    return [ED25519_KEY_TYPE, SECP256K1_KEY_TYPE, BLS12381_KEY_TYPE]
