"""RFC 6962 Merkle trees (reference: crypto/merkle/{tree,hash,proof}.go).

Leaf hash = SHA-256(0x00 || leaf); inner hash = SHA-256(0x01 || left || right).
Empty tree hashes to SHA-256("").  The split point for n leaves is the largest
power of two strictly less than n.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Sequence

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _leaf_hash(leaf: bytes) -> bytes:
    return hashlib.sha256(LEAF_PREFIX + leaf).digest()


def _inner_hash(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(INNER_PREFIX + left + right).digest()


def _split_point(n: int) -> int:
    """Largest power of two < n."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Merkle root of a list of byte slices (reference: crypto/merkle/tree.go:11).

    Iterative bottom-up construction equivalent to the recursive RFC 6962
    definition (the reference optimizes the same way, tree.go:68)."""
    n = len(items)
    if n == 0:
        return hashlib.sha256(b"").digest()
    level = [_leaf_hash(it) for it in items]
    # Reduce respecting the split-point structure: recursion on sizes.
    def reduce(lo: int, hi: int) -> bytes:
        cnt = hi - lo
        if cnt == 1:
            return level[lo]
        k = _split_point(cnt)
        return _inner_hash(reduce(lo, lo + k), reduce(lo + k, hi))

    return reduce(0, n)


@dataclass
class Proof:
    """Merkle inclusion proof (reference: crypto/merkle/proof.go)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes]

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if _leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = _compute_root(self.leaf_hash, self.index, self.total, self.aunts)
        return computed == root


def _compute_root(leaf_hash: bytes, index: int, total: int, aunts: list[bytes]):
    if total == 0:
        return None
    if total == 1:
        if aunts:
            return None
        return leaf_hash
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_root(leaf_hash, index, k, aunts[:-1])
        if left is None:
            return None
        return _inner_hash(left, aunts[-1])
    right = _compute_root(leaf_hash, index - k, total - k, aunts[:-1])
    if right is None:
        return None
    return _inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]) -> tuple[bytes, list[Proof]]:
    """Root plus an inclusion proof per item."""
    n = len(items)
    leaves = [_leaf_hash(it) for it in items]
    if n == 0:
        return hashlib.sha256(b"").digest(), []

    proofs: list[list[bytes]] = [[] for _ in range(n)]

    def build(lo: int, hi: int) -> bytes:
        cnt = hi - lo
        if cnt == 1:
            return leaves[lo]
        k = _split_point(cnt)
        left = build(lo, lo + k)
        right = build(lo + k, hi)
        for i in range(lo, lo + k):
            proofs[i].append(right)
        for i in range(lo + k, hi):
            proofs[i].append(left)
        return _inner_hash(left, right)

    root = build(0, n)
    # aunts are accumulated leaf-level-first; _compute_root consumes the
    # root-level aunt from the tail, so the order is already correct.
    return root, [
        Proof(total=n, index=i, leaf_hash=leaves[i], aunts=proofs[i])
        for i in range(n)
    ]
