"""BLS12-381 signatures (min-pubkey-size): pure-Python host implementation.

Mirrors the reference's blst-backed key type
(/root/reference/crypto/bls12381/key_bls12381.go:31-188,
/root/reference/crypto/bls12381/const.go:1-17) bit-for-bit in its
conventions:

  * public keys are sk*G1, serialized **uncompressed** (96 bytes, ZCash
    flag encoding) — const.go PubKeySize = 96;
  * signatures are sk*H(msg) in G2, serialized **compressed** (96 bytes);
  * the hash-to-curve DST is the literal byte string the reference passes
    to blst — ``BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_``
    (key_bls12381.go:30; note the G1 label is historical — the hash runs
    on G2, exactly as blst's P2Affine.Sign does with that tag);
  * key generation is the BLS-signature-draft HKDF KeyGen blst implements
    (salt "BLS-SIG-KEYGEN-SALT-", re-hashed until sk != 0);
  * verification parses the pubkey with a subgroup + non-infinity check
    (KeyValidate, key_bls12381.go:160-165) and the signature with a
    subgroup check that *allows* infinity (SigValidate(false),
    key_bls12381.go:180-185), then checks
    e(pk, H(msg)) == e(G1, sig).

Everything below — Fp/Fp2/Fp6/Fp12 towers, SSWU + 3-isogeny hash-to-curve
(RFC 9380 section 8.8.2), optimal-ate Miller loop and final exponentiation —
is implemented from the public specifications, not translated from any
library.  Offline we cannot fetch external interop vectors; correctness is
established by algebraic gates in tests/test_bls12381.py (pairing
bilinearity and non-degeneracy, curve/subgroup membership of hash outputs,
serialization round-trips, aggregate consistency).

Speed: python big-int; a verify costs ~100 ms.  That is acceptable for the
host/oracle role (validator sets using bls12_381 keys verify one signature
per vote, and aggregate verification amortizes the pairing); a TPU
aggregate-verify kernel over this seam is the planned round-4 follow-up.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Base field and curve constants.
# ---------------------------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001  # group order
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551
# BLS parameter x (the Miller loop count is -x; x < 0 for BLS12-381).
X_ABS = 0xD201000000010000

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

DST = b"BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_"
KEYGEN_SALT = b"BLS-SIG-KEYGEN-SALT-"

PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 96       # uncompressed G1 (reference const.go:7)
SIGNATURE_SIZE = 96     # compressed G2 (reference const.go:9)


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2+1); elements are (a, b) = a + b*u as int tuples.
# ---------------------------------------------------------------------------

def _f2_add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def _f2_sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def _f2_neg(x):
    return (-x[0] % P, -x[1] % P)


def _f2_mul(x, y):
    a, b = x
    c, d = y
    ac = a * c % P
    bd = b * d % P
    return ((ac - bd) % P, ((a + b) * (c + d) - ac - bd) % P)


def _f2_sq(x):
    a, b = x
    return ((a + b) * (a - b) % P, 2 * a * b % P)


def _f2_scalar(x, k: int):
    return (x[0] * k % P, x[1] * k % P)


def _f2_conj(x):
    return (x[0], -x[1] % P)


def _f2_inv(x):
    a, b = x
    t = pow(a * a + b * b, P - 2, P)
    return (a * t % P, -b * t % P)


def _f2_pow(x, e: int):
    out = (1, 0)
    base = x
    while e:
        if e & 1:
            out = _f2_mul(out, base)
        base = _f2_sq(base)
        e >>= 1
    return out


F2_ZERO = (0, 0)
F2_ONE = (1, 0)


def _f2_sgn0(x) -> int:
    """RFC 9380 sgn0 for m=2."""
    s0 = x[0] % 2
    z0 = x[0] == 0
    s1 = x[1] % 2
    return s0 | (z0 & s1)


def _f2_is_square(x) -> bool:
    # norm(x) = a^2+b^2 must be a QR in Fp  <=>  x is a square in Fp2
    n = (x[0] * x[0] + x[1] * x[1]) % P
    return n == 0 or pow(n, (P - 1) // 2, P) == 1


def _f2_sqrt(x) -> Optional[tuple]:
    """sqrt in Fp2 (p ≡ 3 mod 4): candidate x^((p^2+7)/16) ... use the
    standard complex method via norms instead — deterministic and simple."""
    a, b = x
    if b == 0:
        if pow(a, (P - 1) // 2, P) in (0, 1):
            return (pow(a, (P + 1) // 4, P), 0)
        # sqrt(a) = sqrt(-a) * u since u^2 = -1
        return (0, pow(-a % P, (P + 1) // 4, P))
    n = (a * a + b * b) % P
    if pow(n, (P - 1) // 2, P) != 1:
        return None
    alpha = pow(n, (P + 1) // 4, P)  # sqrt of the norm
    for sgn in (1, -1):
        delta = (a + sgn * alpha) * pow(2, P - 2, P) % P
        if pow(delta, (P - 1) // 2, P) in (0, 1):
            x0 = pow(delta, (P + 1) // 4, P)
            if x0 == 0:
                continue
            x1 = b * pow(2 * x0, P - 2, P) % P
            cand = (x0, x1)
            if _f2_sq(cand) == (a % P, b % P):
                return cand
    return None


# ---------------------------------------------------------------------------
# Fp12 as a pair-of-Fp6, Fp6 as triple-of-Fp2.  Represented as nested
# tuples; xi = 1 + u is the Fp6 non-residue, v (Fp12) with v^2 = w in Fp6.
# ---------------------------------------------------------------------------

XI = (1, 1)  # 1 + u


def _f6_add(x, y):
    return tuple(_f2_add(a, b) for a, b in zip(x, y))


def _f6_sub(x, y):
    return tuple(_f2_sub(a, b) for a, b in zip(x, y))


def _f6_neg(x):
    return tuple(_f2_neg(a) for a in x)


def _f6_mul(x, y):
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0 = _f2_mul(a0, b0)
    t1 = _f2_mul(a1, b1)
    t2 = _f2_mul(a2, b2)
    c0 = _f2_add(t0, _f2_mul(XI, _f2_sub(_f2_mul(_f2_add(a1, a2), _f2_add(b1, b2)), _f2_add(t1, t2))))
    c1 = _f2_add(_f2_sub(_f2_mul(_f2_add(a0, a1), _f2_add(b0, b1)), _f2_add(t0, t1)), _f2_mul(XI, t2))
    c2 = _f2_add(_f2_sub(_f2_mul(_f2_add(a0, a2), _f2_add(b0, b2)), _f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def _f6_mul_by_xi(x):
    # multiply by w (the cubic generator): (a0,a1,a2) * w = (xi*a2, a0, a1)
    return (_f2_mul(XI, x[2]), x[0], x[1])


def _f6_inv(x):
    a0, a1, a2 = x
    t0 = _f2_sq(a0)
    t1 = _f2_sq(a1)
    t2 = _f2_sq(a2)
    t3 = _f2_mul(a0, a1)
    t4 = _f2_mul(a0, a2)
    t5 = _f2_mul(a1, a2)
    c0 = _f2_sub(t0, _f2_mul(XI, t5))
    c1 = _f2_sub(_f2_mul(XI, t2), t3)
    c2 = _f2_sub(t1, t4)
    t6 = _f2_add(_f2_mul(a0, c0), _f2_mul(XI, _f2_add(_f2_mul(a2, c1), _f2_mul(a1, c2))))
    t6i = _f2_inv(t6)
    return (_f2_mul(c0, t6i), _f2_mul(c1, t6i), _f2_mul(c2, t6i))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def _f12_mul(x, y):
    a0, a1 = x
    b0, b1 = y
    t0 = _f6_mul(a0, b0)
    t1 = _f6_mul(a1, b1)
    c0 = _f6_add(t0, _f6_mul_by_xi(t1))
    c1 = _f6_sub(_f6_mul(_f6_add(a0, a1), _f6_add(b0, b1)), _f6_add(t0, t1))
    return (c0, c1)


def _f12_sq(x):
    return _f12_mul(x, x)


def _f12_inv(x):
    a0, a1 = x
    t = _f6_inv(_f6_sub(_f6_mul(a0, a0), _f6_mul_by_xi(_f6_mul(a1, a1))))
    return (_f6_mul(a0, t), _f6_neg(_f6_mul(a1, t)))


def _f12_conj(x):
    return (x[0], _f6_neg(x[1]))


def _f12_pow(x, e: int):
    out = F12_ONE
    base = x
    while e:
        if e & 1:
            out = _f12_mul(out, base)
        base = _f12_sq(base)
        e >>= 1
    return out


F12_ONE = (F6_ONE, F6_ZERO)

# Frobenius coefficients for Fp2: (a + bu)^p = a - bu.  For Fp6/Fp12 we
# apply frobenius by mapping through the tower with precomputed gammas.
_FROB_GAMMA1 = [
    _f2_pow(XI, (P - 1) * k // 6) for k in range(6)
]  # xi^((p-1)k/6), k = 0..5


def _f12_frobenius(x):
    """x^p for x in Fp12 (one application)."""
    (a0, a1, a2), (b0, b1, b2) = x
    a0 = _f2_conj(a0)
    a1 = _f2_mul(_f2_conj(a1), _FROB_GAMMA1[2])
    a2 = _f2_mul(_f2_conj(a2), _FROB_GAMMA1[4])
    b0 = _f2_mul(_f2_conj(b0), _FROB_GAMMA1[1])
    b1 = _f2_mul(_f2_conj(b1), _FROB_GAMMA1[3])
    b2 = _f2_mul(_f2_conj(b2), _FROB_GAMMA1[5])
    return ((a0, a1, a2), (b0, b1, b2))


# ---------------------------------------------------------------------------
# G1 (E: y^2 = x^3 + 4 over Fp) and G2 (E': y^2 = x^3 + 4(1+u) over Fp2),
# Jacobian coordinates (X, Y, Z): x = X/Z^2, y = Y/Z^3.
# ---------------------------------------------------------------------------

class _Curve:
    """Generic short-Weierstrass Jacobian arithmetic over a field given by
    add/sub/mul/sq/inv/eq-zero callables — one implementation drives both
    G1 (Fp) and G2 (Fp2)."""

    def __init__(self, add, sub, neg, mul, sq, inv, zero, one, b):
        self.add, self.sub, self.neg = add, sub, neg
        self.mul, self.sq, self.inv = mul, sq, inv
        self.zero, self.one, self.b = zero, one, b

    def infinity(self):
        return (self.one, self.one, self.zero)

    def is_infinity(self, pt) -> bool:
        return pt[2] == self.zero

    def double(self, pt):
        X, Y, Z = pt
        if Z == self.zero:
            return pt
        A = self.sq(X)
        B = self.sq(Y)
        C = self.sq(B)
        t = self.sub(self.sq(self.add(X, B)), self.add(A, C))
        D = self.add(t, t)
        E = self.add(self.add(A, A), A)
        F = self.sq(E)
        X3 = self.sub(F, self.add(D, D))
        c8 = self.add(self.add(self.add(C, C), self.add(C, C)), self.add(self.add(C, C), self.add(C, C)))
        Y3 = self.sub(self.mul(E, self.sub(D, X3)), c8)
        Z3 = self.mul(self.add(Y, Y), Z)
        return (X3, Y3, Z3)

    def add_pts(self, p1, p2):
        if p1[2] == self.zero:
            return p2
        if p2[2] == self.zero:
            return p1
        X1, Y1, Z1 = p1
        X2, Y2, Z2 = p2
        Z1Z1 = self.sq(Z1)
        Z2Z2 = self.sq(Z2)
        U1 = self.mul(X1, Z2Z2)
        U2 = self.mul(X2, Z1Z1)
        S1 = self.mul(self.mul(Y1, Z2), Z2Z2)
        S2 = self.mul(self.mul(Y2, Z1), Z1Z1)
        if U1 == U2:
            if S1 == S2:
                return self.double(p1)
            return self.infinity()
        H = self.sub(U2, U1)
        I = self.sq(self.add(H, H))
        J = self.mul(H, I)
        rr = self.add(self.sub(S2, S1), self.sub(S2, S1))
        V = self.mul(U1, I)
        X3 = self.sub(self.sub(self.sq(rr), J), self.add(V, V))
        S1J = self.mul(S1, J)
        Y3 = self.sub(self.mul(rr, self.sub(V, X3)), self.add(S1J, S1J))
        Z3 = self.mul(self.sub(self.sq(self.add(Z1, Z2)), self.add(Z1Z1, Z2Z2)), H)
        return (X3, Y3, Z3)

    def neg_pt(self, pt):
        return (pt[0], self.neg(pt[1]), pt[2])

    def mul_scalar(self, pt, k: int):
        if k < 0:
            return self.mul_scalar(self.neg_pt(pt), -k)
        out = self.infinity()
        add = pt
        while k:
            if k & 1:
                out = self.add_pts(out, add)
            add = self.double(add)
            k >>= 1
        return out

    def affine(self, pt):
        if pt[2] == self.zero:
            return None
        zi = self.inv(pt[2])
        zi2 = self.sq(zi)
        return (self.mul(pt[0], zi2), self.mul(pt[1], self.mul(zi2, zi)))

    def on_curve(self, pt) -> bool:
        if pt[2] == self.zero:
            return True
        aff = self.affine(pt)
        return self.sq(aff[1]) == self.add(self.mul(self.sq(aff[0]), aff[0]), self.b)


def _fp_ops():
    return _Curve(
        add=lambda a, b: (a + b) % P,
        sub=lambda a, b: (a - b) % P,
        neg=lambda a: -a % P,
        mul=lambda a, b: a * b % P,
        sq=lambda a: a * a % P,
        inv=lambda a: pow(a, P - 2, P),
        zero=0,
        one=1,
        b=4,
    )


def _fp2_ops():
    return _Curve(
        add=_f2_add,
        sub=_f2_sub,
        neg=_f2_neg,
        mul=_f2_mul,
        sq=_f2_sq,
        inv=_f2_inv,
        zero=F2_ZERO,
        one=F2_ONE,
        b=_f2_scalar(XI, 4),  # 4(1+u)
    )


E1 = _fp_ops()
E2 = _fp2_ops()
G1_GEN = (G1_X, G1_Y, 1)
G2_GEN = (G2_X, G2_Y, F2_ONE)


def _g1_subgroup(pt) -> bool:
    return E1.is_infinity(E1.mul_scalar(pt, R))


def _g2_subgroup(pt) -> bool:
    return E2.is_infinity(E2.mul_scalar(pt, R))


# ---------------------------------------------------------------------------
# Pairing: optimal ate.  e(P in G1, Q in G2) via Miller loop over -x.
# ---------------------------------------------------------------------------

def _g2_affine(r):
    zi = _f2_inv(r[2])
    zi2 = _f2_sq(zi)
    return (_f2_mul(r[0], zi2), _f2_mul(r[1], _f2_mul(zi2, zi)))


def _fp12_from_coeffs(c0_f2, c2_f2, c3_f2):
    """Element c0 + c2*w^2 + c3*w^3 of Fp12 in the (Fp6, Fp6) tower where
    w^2 has Fp6 coordinate index 1 of the even part and w^3 index 1 of the
    odd part... concretely: Fp12 = Fp6[v]/(v^2 - w6gen); basis
    {1, w, w^2, w^3, w^4, w^5} maps to even part (1, w^2, w^4) and odd
    part (w, w^3, w^5)."""
    even = (c0_f2, c2_f2, F2_ZERO)
    odd = (F2_ZERO, c3_f2, F2_ZERO)
    return (even, odd)


def _line_eval_generic(r_old, r_new, p_aff, tangent: bool, q_aff=None):
    """Evaluate the line through r_old (tangent) or through r_old and
    q_aff (chord) at the G1 point p_aff, in Fp12.

    The line through two G2 points (x1,y1),(x2,y2) (affine over Fp2) is
      l(x, y) = (y - y1) - m (x - x1),  m = slope (in Fp2).
    With the untwist x = x' * w^2, y = y' * w^3 for G1 coordinates
    embedded... (standard M-twist embedding: G1 point (px, py) maps into
    Fp12 as (px, py); G2 points map via multiplication by powers of w).
    Evaluated: l = py*w^3... — we use:
      l(P) = (y1*? ...)
    Concretely with the G2-on-twist convention:
      l(P) = py * w^3 - y1 - m * (px * w^2 - x1)
           = (m*x1 - y1) + (-m*px) * w^2 + (py) * w^3
    all coefficients in Fp2 (px, py lift to (px, 0), (py, 0)).
    """
    x1, y1 = _g2_affine(r_old)
    if tangent:
        # m = 3*x1^2 / (2*y1)
        num = _f2_scalar(_f2_sq(x1), 3)
        den = _f2_scalar(y1, 2)
    else:
        x2, y2 = q_aff
        if x1 == x2 and y1 == y2:
            return _line_eval_generic(r_old, r_new, p_aff, tangent=True)
        num = _f2_sub(y2, y1)
        den = _f2_sub(x2, x1)
        if den == F2_ZERO:
            # vertical line: l(P) = px - x1 (w^2 component)
            px, _py = p_aff
            c0 = _f2_neg(x1)
            return _fp12_from_coeffs(c0, ((px % P), 0), F2_ZERO)
    m = _f2_mul(num, _f2_inv(den))
    px, py = p_aff
    c0 = _f2_sub(_f2_mul(m, x1), y1)
    c2 = _f2_neg(_f2_scalar(m, px % P))
    c3 = ((py % P), 0)
    return _fp12_from_coeffs(c0, c2, c3)


def _miller_loop(p_aff, q_jac):
    """f_{-x, Q}(P) without final exponentiation (the -x handled by
    conjugation at the end, standard for BLS12 with negative x)."""
    f = F12_ONE
    r = q_jac
    q_affine = _g2_affine(q_jac)
    bits = bin(X_ABS)[3:]  # skip MSB
    for bit in bits:
        line = _line_eval_generic(r, None, p_aff, tangent=True)
        r = E2.double(r)
        f = _f12_mul(_f12_sq(f), line)
        if bit == "1":
            line = _line_eval_generic(r, None, p_aff, tangent=False, q_aff=q_affine)
            r = E2.add_pts(r, (q_affine[0], q_affine[1], F2_ONE))
            f = _f12_mul(f, line)
    # x is negative for BLS12-381: f <- conj(f)
    return _f12_conj(f)


def _final_exponentiation(f):
    """f^((p^12-1)/r): easy part then hard part (naive exponent — slow but
    transparently correct)."""
    # easy part: f^(p^6-1) = conj(f) * f^-1 ; then ^(p^2+1)
    f = _f12_mul(_f12_conj(f), _f12_inv(f))
    f = _f12_mul(_f12_frobenius(_f12_frobenius(f)), f)
    # hard part: exponent (p^4 - p^2 + 1)/r, naive square-and-multiply.
    e = (P**4 - P**2 + 1) // R
    return _f12_pow(f, e)


def pairing(p1_jac, q2_jac) -> tuple:
    """e(P, Q) for P in G1 (Jacobian ints), Q in G2 (Jacobian Fp2)."""
    if E1.is_infinity(p1_jac) or E2.is_infinity(q2_jac):
        return F12_ONE
    p_aff = E1.affine(p1_jac)
    return _final_exponentiation(_miller_loop(p_aff, q2_jac))


def _pairing_product_is_one(pairs) -> bool:
    """prod e(Pi, Qi) == 1, with one shared final exponentiation."""
    f = F12_ONE
    any_term = False
    for p1, q2 in pairs:
        if E1.is_infinity(p1) or E2.is_infinity(q2):
            continue
        any_term = True
        f = _f12_mul(f, _miller_loop(E1.affine(p1), q2))
    if not any_term:
        return True
    return _final_exponentiation(f) == F12_ONE


# ---------------------------------------------------------------------------
# Serialization (ZCash flag convention, as blst Serialize/Compress).
# ---------------------------------------------------------------------------

def g1_serialize(pt) -> bytes:
    """Uncompressed 96-byte G1 (the reference's PubKey.Bytes)."""
    if E1.is_infinity(pt):
        out = bytearray(96)
        out[0] = 0x40
        return bytes(out)
    x, y = E1.affine(pt)
    out = x.to_bytes(48, "big") + y.to_bytes(48, "big")
    return out


def g1_negate_serialized(pub: bytes) -> bytes:
    """-P over the 96-byte uncompressed encoding (flip y; pure bytes, no
    curve arithmetic — used to feed negated terms to the native pairing
    product)."""
    if pub[0] & 0x40:  # infinity
        return pub
    y = int.from_bytes(pub[48:], "big")
    return pub[:48] + ((P - y) % P).to_bytes(48, "big")


def g1_deserialize(b: bytes):
    """Uncompressed or compressed G1 with ZCash flags; returns Jacobian or
    None.  On-curve is checked; subgroup is NOT (callers decide)."""
    if len(b) == 96 and not (b[0] & 0x80):
        flags = b[0]
        if flags & 0x40:
            if any(b) and b != b"\x40" + bytes(95):
                return None
            return E1.infinity()
        x = int.from_bytes(b[:48], "big")
        y = int.from_bytes(b[48:], "big")
        if x >= P or y >= P:
            return None
        pt = (x, y, 1)
        return pt if E1.on_curve(pt) else None
    if len(b) == 48 and (b[0] & 0x80):
        flags = b[0]
        if flags & 0x40:
            if (flags & 0x3F) or any(b[1:]):
                return None
            return E1.infinity()
        x = int.from_bytes(bytes([flags & 0x1F]) + b[1:], "big")
        if x >= P:
            return None
        y2 = (pow(x, 3, P) + 4) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P != y2:
            return None
        y_is_larger = y > (P - 1) // 2
        want_larger = bool(flags & 0x20)
        if y_is_larger != want_larger:
            y = P - y
        return (x, y, 1)
    return None


def g2_compress(pt) -> bytes:
    """Compressed 96-byte G2 (the reference's signature encoding)."""
    if E2.is_infinity(pt):
        out = bytearray(96)
        out[0] = 0xC0
        return bytes(out)
    (x0, x1), (y0, y1) = _g2_affine(pt)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= 0x80
    # sign: lexicographically larger y (compare (y1, y0) big-endian pair)
    if (y1, y0) > ((P - y1) % P, (P - y0) % P):
        out[0] |= 0x20
    return bytes(out)


def g2_uncompress(b: bytes):
    """Compressed G2 -> Jacobian (or None).  On-curve checked, subgroup
    NOT (SigValidate does that separately, infinity allowed)."""
    if len(b) != 96 or not (b[0] & 0x80):
        return None
    flags = b[0]
    if flags & 0x40:
        if (flags & 0x3F) or any(b[1:]):
            return None
        return E2.infinity()
    x1 = int.from_bytes(bytes([flags & 0x1F]) + b[1:48], "big")
    x0 = int.from_bytes(b[48:], "big")
    if x0 >= P or x1 >= P:
        return None
    x = (x0, x1)
    y2 = _f2_add(_f2_mul(_f2_sq(x), x), E2.b)
    y = _f2_sqrt(y2)
    if y is None:
        return None
    neg = _f2_neg(y)
    y_larger = (y[1], y[0]) > (neg[1], neg[0])
    if y_larger != bool(flags & 0x20):
        y = neg
    return (x, y, F2_ONE)


# ---------------------------------------------------------------------------
# Hash-to-curve G2 (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_).
# ---------------------------------------------------------------------------

def _expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    ell = -(-length // 32)
    assert ell <= 255
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(64)
    l_i_b = length.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bvals = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        prev = bytes(x ^ y for x, y in zip(b0, bvals[-1]))
        bvals.append(hashlib.sha256(prev + bytes([i]) + dst_prime).digest())
    return b"".join(bvals)[:length]


def _hash_to_field_fp2(msg: bytes, count: int, dst: bytes):
    L = 64
    uniform = _expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off : off + L], "big") % P)
        out.append((coords[0], coords[1]))
    return out


# SSWU constants for E2': y^2 = x^3 + A'x + B', Z = -(2 + u)
_SSWU_A = (0, 240)
_SSWU_B = (1012, 1012)
_SSWU_Z = (-2 % P, -1 % P)

# 3-isogeny map E2' -> E2 coefficients (RFC 9380 appendix E.3).
_ISO_XNUM = [
    ((0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6, 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6)),
    ((0, 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A)),
    ((0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E, 0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D)),
    ((0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1, 0)),
]
_ISO_XDEN = [
    ((0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63)),
    ((0xC, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F)),
    ((1, 0)),
]
_ISO_YNUM = [
    ((0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706, 0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706)),
    ((0, 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE)),
    ((0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C, 0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F)),
    ((0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10, 0)),
]
_ISO_YDEN = [
    ((P - 0x1B0, P - 0x1B0)),  # k_(4,0) = (p - 0x1b0) * (1 + u)
    ((0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3)),
    ((0x12, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99)),
    ((1, 0)),
]


def _sswu_map(u):
    """Simplified SWU for E2' (RFC 9380 section 6.6.2)."""
    A, B, Z = _SSWU_A, _SSWU_B, _SSWU_Z
    u2 = _f2_sq(u)
    tv1 = _f2_mul(Z, u2)  # Z*u^2
    tv2 = _f2_add(_f2_sq(tv1), tv1)
    x1num = _f2_mul(B, _f2_add(tv2, F2_ONE))
    x1den = _f2_mul(_f2_neg(A), tv2)
    if x1den == F2_ZERO:
        x1den = _f2_mul(Z, A)
    x1 = _f2_mul(x1num, _f2_inv(x1den))
    gx1 = _f2_add(_f2_add(_f2_mul(_f2_sq(x1), x1), _f2_mul(A, x1)), B)
    if _f2_is_square(gx1):
        x, y = x1, _f2_sqrt(gx1)
    else:
        # g(Z*u^2*x1) = (Z*u^2)^3 * g(x1); Z non-square => exactly one of
        # g(x1), g(x2) is square
        x = _f2_mul(tv1, x1)
        y = _f2_sqrt(_f2_mul(_f2_mul(_f2_sq(tv1), tv1), gx1))
    assert y is not None
    if _f2_sgn0(u) != _f2_sgn0(y):
        y = _f2_neg(y)
    return (x, y)


def _iso_map(x, y):
    """3-isogeny E2' -> E2 via Horner evaluation of the rational maps."""

    def horner(coeffs, xv):
        acc = coeffs[-1]
        for c in reversed(coeffs[:-1]):
            acc = _f2_add(_f2_mul(acc, xv), c)
        return acc

    xnum = horner(_ISO_XNUM, x)
    xden = horner(_ISO_XDEN, x)
    ynum = horner(_ISO_YNUM, x)
    yden = horner(_ISO_YDEN, x)
    xo = _f2_mul(xnum, _f2_inv(xden))
    yo = _f2_mul(y, _f2_mul(ynum, _f2_inv(yden)))
    return (xo, yo)


def hash_to_g2(msg: bytes, dst: bytes = DST):
    """hash_to_curve for G2 (random oracle variant), returns Jacobian."""
    if dst == DST:
        lib = _nat()
        if lib is not None:
            import ctypes

            out = ctypes.create_string_buffer(96)
            if lib.bls_hash_to_g2(msg, len(msg), out) == 0:
                pt = g2_uncompress(out.raw)
                if pt is not None:
                    return pt
    u0, u1 = _hash_to_field_fp2(msg, 2, dst)
    q0 = _iso_map(*_sswu_map(u0))
    q1 = _iso_map(*_sswu_map(u1))
    s = E2.add_pts((q0[0], q0[1], F2_ONE), (q1[0], q1[1], F2_ONE))
    return E2.mul_scalar(s, H_EFF_G2)


# ---------------------------------------------------------------------------
# Native (C++) fast path — the blst analog (SURVEY §2.1.1).  The public
# API functions below dispatch to cometbft_tpu/native/csrc/bls12381.cpp
# when it builds and passes its pairing self-check; every operation keeps
# this module's big-int implementation as the oracle fallback, and
# tests/test_bls_native.py differential-tests the two.  Kill-switch:
# COMETBFT_TPU_NO_NATIVE=1 (same switch as the WAL/packer sidecar).
# ---------------------------------------------------------------------------


def _nat():
    """The native BLS library or None; isolated for test monkeypatching."""
    from cometbft_tpu import native

    return native.bls()


# ---------------------------------------------------------------------------
# KeyGen / sign / verify / aggregate (the reference's API surface).
# ---------------------------------------------------------------------------

def keygen(ikm: bytes, key_info: bytes = b"") -> int:
    """BLS-signature-draft KeyGen (what blst.KeyGen implements): HKDF with
    the fixed salt, re-hashing the salt until sk != 0."""
    if len(ikm) < 32:
        raise ValueError("ikm must be >= 32 bytes")
    salt = KEYGEN_SALT
    L = 48
    while True:
        salt = hashlib.sha256(salt).digest()
        prk = hmac.new(salt, ikm + b"\x00", hashlib.sha256).digest()
        okm = b""
        t = b""
        info = key_info + L.to_bytes(2, "big")
        i = 1
        while len(okm) < L:
            t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
            okm += t
            i += 1
        sk = int.from_bytes(okm[:L], "big") % R
        if sk != 0:
            return sk


def gen_privkey_from_secret(secret: bytes) -> int:
    """Reference GenPrivKeyFromSecret (key_bls12381.go:66-74): sha256 the
    secret to 32 bytes unless it already is 32."""
    if len(secret) != 32:
        secret = hashlib.sha256(secret).digest()
    return keygen(secret)


def gen_privkey() -> int:
    return keygen(os.urandom(32))


def sk_to_bytes(sk: int) -> bytes:
    return sk.to_bytes(32, "big")


def sk_from_bytes(b: bytes) -> Optional[int]:
    if len(b) != 32:
        return None
    v = int.from_bytes(b, "big")
    if v == 0 or v >= R:
        return None
    return v


def pubkey(sk: int) -> bytes:
    """96-byte uncompressed G1 (reference PubKey.Bytes)."""
    lib = _nat()
    if lib is not None:
        import ctypes

        out = ctypes.create_string_buffer(96)
        if lib.bls_pubkey_from_sk(sk.to_bytes(32, "big"), out) == 0:
            return out.raw
    return g1_serialize(E1.mul_scalar(G1_GEN, sk))


def pubkey_validate(pub: bytes) -> bool:
    """KeyValidate: on curve, in subgroup, not infinity."""
    lib = _nat()
    if lib is not None:
        return lib.bls_pubkey_validate(pub, len(pub)) == 1
    pt = g1_deserialize(pub)
    if pt is None or E1.is_infinity(pt):
        return False
    return _g1_subgroup(pt)


def sign(sk: int, msg: bytes) -> bytes:
    """96-byte compressed G2: sk * H(msg)."""
    lib = _nat()
    if lib is not None:
        import ctypes

        out = ctypes.create_string_buffer(96)
        if lib.bls_sign(sk.to_bytes(32, "big"), msg, len(msg), out) == 0:
            return out.raw
    return g2_compress(E2.mul_scalar(hash_to_g2(msg), sk))


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Reference VerifySignature semantics (key_bls12381.go:174-188)."""
    lib = _nat()
    if lib is not None and len(sig) == SIGNATURE_SIZE:
        return lib.bls_verify(pub, len(pub), msg, len(msg), sig) == 1
    pk = g1_deserialize(pub)
    if pk is None or E1.is_infinity(pk) or not _g1_subgroup(pk):
        return False
    s = g2_uncompress(sig)
    if s is None:
        return False
    # SigValidate(false): subgroup check, infinity allowed
    if not _g2_subgroup(s):
        return False
    h = hash_to_g2(msg)
    # e(pk, H(msg)) == e(G1, sig)  <=>  e(-pk, H) * e(G1, sig) == 1
    return _pairing_product_is_one(
        [(E1.neg_pt(pk), h), (G1_GEN, s)]
    )


def aggregate_signatures(sigs: Sequence[bytes]) -> Optional[bytes]:
    """Sum of G2 signatures (basic scheme aggregation)."""
    lib = _nat()
    if lib is not None and sigs and all(
        len(s) == SIGNATURE_SIZE for s in sigs
    ):
        import ctypes

        out = ctypes.create_string_buffer(96)
        if lib.bls_aggregate_sigs(b"".join(sigs), len(sigs), out) == 0:
            return out.raw
        return None
    acc = E2.infinity()
    for sg in sigs:
        pt = g2_uncompress(sg)
        if pt is None:
            return None
        acc = E2.add_pts(acc, pt)
    return g2_compress(acc)


def aggregate_verify(
    pubs: Sequence[bytes], msgs: Sequence[bytes], agg_sig: bytes
) -> bool:
    """Basic-scheme AggregateVerify: distinct-message requirement per the
    NUL (basic) ciphersuite the reference's DST names."""
    if len(pubs) != len(msgs) or not pubs:
        return False
    if len({bytes(m) for m in msgs}) != len(msgs):
        return False  # basic scheme forbids repeated messages
    lib = _nat()
    if lib is not None and len(agg_sig) == SIGNATURE_SIZE and all(
        len(p) == PUB_KEY_SIZE for p in pubs
    ):
        import ctypes

        off = [0]
        for m in msgs:
            off.append(off[-1] + len(m))
        offs = (ctypes.c_int64 * len(off))(*off)
        return (
            lib.bls_aggregate_verify(
                b"".join(pubs), b"".join(msgs), offs, len(pubs), agg_sig
            )
            == 1
        )
    s = g2_uncompress(agg_sig)
    if s is None or not _g2_subgroup(s):
        return False
    pairs = []
    for pub, msg in zip(pubs, msgs):
        pk = g1_deserialize(pub)
        if pk is None or E1.is_infinity(pk) or not _g1_subgroup(pk):
            return False
        pairs.append((pk, hash_to_g2(msg)))
    pairs = [(E1.neg_pt(pk), h) for pk, h in pairs]
    pairs.append((G1_GEN, s))
    return _pairing_product_is_one(pairs)
