"""Crypto-backend health: circuit breakers + degradation bookkeeping.

This module is the jax-free state half of the backend supervisor
(``ops/supervisor.py`` is the dispatch half): per-backend circuit breakers
with exponential-backoff half-open probes, plus the process-wide counters
the ``cometbft_crypto_backend_*`` metrics read at scrape time.  Keeping it
free of jax imports matters for the same reason ``ops/dispatch_stats`` is:
a /metrics scrape (or a sim scenario script) must never be the thing that
initializes an accelerator backend.

Failure taxonomy (docs/backend-supervisor.md): everything recorded here is
an INFRASTRUCTURE failure — a dispatch that raised, wedged past the
watchdog, or returned a malformed result.  A signature that verifies False
is a *verdict* and never touches this module; conversely nothing recorded
here may ever surface as a False accept bit (the supervisor re-verifies on
the next backend down instead).

Breaker state machine (per backend):

    CLOSED --(>= threshold consecutive failures)--> OPEN
    OPEN   --(backoff elapsed)--> HALF_OPEN (one probe dispatch allowed)
    HALF_OPEN --probe success--> CLOSED   (re-promotion; backoff resets)
    HALF_OPEN --probe failure--> OPEN     (backoff doubles, capped)

Env knobs:
  * ``COMETBFT_TPU_BREAKER_THRESHOLD``      consecutive failures to open
    (default 3; the affected batch already fell through to the next
    backend, so threshold > 1 only controls how long the *next* batches
    keep probing a flaky device);
  * ``COMETBFT_TPU_BREAKER_BACKOFF_MS``     initial open->half-open delay
    (default 1000);
  * ``COMETBFT_TPU_BREAKER_BACKOFF_MAX_MS`` backoff cap (default 30000).

The clock is injectable (``set_clock``) so the deterministic simulator
drives backoff on its ``VirtualClock`` and tests use a fake clock; the
default is ``time.monotonic``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

DEFAULT_THRESHOLD = 3
DEFAULT_BACKOFF_MS = 1000.0
DEFAULT_BACKOFF_MAX_MS = 30000.0

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

# the ed25519 degradation-chain tiers (ops/supervisor.device_chain plus the
# aggregate "tpu" backend name) share the "breaker_open" anomaly kind; any
# breaker outside this set gets its own per-name kind in record_failure
_ED25519_CHAIN_TIERS = ("pallas", "xla", "tpu")


class BackendError(RuntimeError):
    """Base class for infrastructure failures the supervisor attributes to
    a backend (never to a signature)."""


class DispatchTimeoutError(BackendError):
    """A device dispatch wedged past the watchdog deadline."""


class BackendOutputError(BackendError):
    """A dispatch returned, but with a malformed result (wrong shape or
    dtype) — treated exactly like a raise: infrastructure, not verdict."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class CircuitBreaker:
    """Per-backend breaker; all methods are thread-safe.

    ``allow()`` is the admission check the supervisor runs before every
    dispatch: True in CLOSED, True exactly once per backoff window in
    HALF_OPEN (the probe), False in OPEN.  ``record_success`` /
    ``record_failure`` resolve the attempt.
    """

    def __init__(
        self,
        name: str,
        threshold: Optional[int] = None,
        backoff_s: Optional[float] = None,
        backoff_max_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self.threshold = max(
            1,
            int(
                threshold
                if threshold is not None
                else _env_float("COMETBFT_TPU_BREAKER_THRESHOLD", DEFAULT_THRESHOLD)
            ),
        )
        self.backoff_initial_s = (
            backoff_s
            if backoff_s is not None
            else _env_float("COMETBFT_TPU_BREAKER_BACKOFF_MS", DEFAULT_BACKOFF_MS)
            / 1000.0
        )
        self.backoff_max_s = (
            backoff_max_s
            if backoff_max_s is not None
            else _env_float(
                "COMETBFT_TPU_BREAKER_BACKOFF_MAX_MS", DEFAULT_BACKOFF_MAX_MS
            )
            / 1000.0
        )
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0  # consecutive
        self._backoff_s = self.backoff_initial_s
        self._open_until = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        # cumulative stats
        self._opens = 0
        self._probes = 0
        self._repromotions = 0
        self._failures_total = 0
        self._successes_total = 0
        self._last_error: str = ""

    # -- admission ---------------------------------------------------------

    def allow(self) -> bool:
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self.clock()
            if self._state == OPEN:
                if now < self._open_until:
                    return False
                self._state = HALF_OPEN
                self._probe_inflight = True
                self._probe_started = now
                self._probes += 1
                return True
            # HALF_OPEN: one probe at a time — but a probe whose caller
            # died before resolving (e.g. raised between allow() and the
            # dispatch) must not wedge the breaker forever: past the cap
            # window the probe slot is reclaimed
            if (
                self._probe_inflight
                and now - self._probe_started < self.backoff_max_s
            ):
                return False
            self._probe_inflight = True
            self._probe_started = now
            self._probes += 1
            return True

    # -- resolution --------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            repromoted = self._state != CLOSED
            self._state = CLOSED
            self._failures = 0
            self._backoff_s = self.backoff_initial_s
            self._probe_inflight = False
            self._successes_total += 1
            if repromoted:
                self._repromotions += 1
        if repromoted:
            # the close half of the breaker's state transitions: opens are
            # journaled through the anomaly path; re-promotions are not
            # anomalies, so they go straight to the black box (no-op
            # without a journal) — outside the breaker lock, like the open
            from cometbft_tpu.libs import tracing

            tracing.note_event(
                "breaker_close",
                backend=self.name,
                repromotions=self._repromotions,
            )

    def _open_locked(self) -> bool:
        """The OPEN transition, under the lock — shared by organic
        failures (``record_failure``) and proactive trips (``trip``) so
        the two can never drift.  Returns True when this call NEWLY
        opened the breaker (an already-open, unelapsed window only has
        its backoff refreshed, without re-counting the open)."""
        already_open = self._state == OPEN and self.clock() < self._open_until
        self._state = OPEN
        if not already_open:
            self._opens += 1
        self._open_until = self.clock() + self._backoff_s
        # exponential backoff for the NEXT half-open window
        self._backoff_s = min(self._backoff_s * 2, self.backoff_max_s)
        return not already_open

    def record_failure(self, err: Optional[BaseException] = None) -> None:
        opened = False
        with self._lock:
            self._failures += 1
            self._failures_total += 1
            if err is not None:
                self._last_error = repr(err)[:200]
            was_probe = self._state == HALF_OPEN
            if was_probe or (
                self._state == CLOSED and self._failures >= self.threshold
            ):
                opened = self._open_locked()
            self._probe_inflight = False
        if opened:
            self._emit_open_anomaly()

    def trip(self, reason: str = "") -> None:
        """Force the breaker OPEN immediately — proactive exclusion: an
        out-of-band health signal (an ``ops/device_health`` down-probe, a
        chip-watcher status flip) reported the backend dead, so the next
        dispatch must not pay a failure to find out.  Counts as one
        failure; re-admission rides the normal half-open backoff."""
        opened = False
        with self._lock:
            self._failures += 1
            self._failures_total += 1
            if reason:
                self._last_error = reason[:200]
            opened = self._open_locked()
            self._probe_inflight = False
        if opened:
            self._emit_open_anomaly()

    def _emit_open_anomaly(self) -> None:
        # flight-recorder anomaly (docs/observability.md), recorded
        # OUTSIDE the breaker lock: the first open since reset dumps
        # the span ring for postmortem.  The ed25519 degradation-chain
        # tiers share one taxonomy kind (one chain, one story); every
        # OTHER breaker — secp_device, bls_g1, the per-ordinal mesh_dev*
        # breakers, and any single-tier backend added later —
        # automatically gets its own ``breaker_open_<name>`` kind, so its
        # first open still dumps even after an ed25519-tier open latched
        # the shared kind.
        from cometbft_tpu.libs import tracing

        kind = (
            "breaker_open"
            if self.name in _ED25519_CHAIN_TIERS
            else f"breaker_open_{self.name}"
        )
        tracing.record_anomaly(
            kind,
            backend=self.name,
            opens=self._opens,
            error=self._last_error,
        )

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            # surface the would-be transition so observers see HALF_OPEN as
            # soon as the backoff elapses, not only after the next allow()
            if self._state == OPEN and self.clock() >= self._open_until:
                return HALF_OPEN
            return self._state

    def reset(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._backoff_s = self.backoff_initial_s
            self._probe_inflight = False

    def stats(self) -> dict:
        with self._lock:
            # same mapped view as the ``state`` property (elapsed-OPEN
            # reads as HALF_OPEN) so the breaker_state gauge, the
            # open_breakers gauge, and sim snapshots can never disagree
            # about whether a tier is available on the same scrape
            st = self._state
            if st == OPEN and self.clock() >= self._open_until:
                st = HALF_OPEN
            return {
                "state": st,
                "state_code": _STATE_CODE[st],
                "consecutive_failures": self._failures,
                "failures_total": self._failures_total,
                "successes_total": self._successes_total,
                "opens": self._opens,
                "probes": self._probes,
                "repromotions": self._repromotions,
                "backoff_s": self._backoff_s,
                "last_error": self._last_error,
            }


class HealthRegistry:
    """All breakers + the cross-backend degradation counters, in one place
    so metrics and sim assertions read one snapshot."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}
        self._counters = {
            "demotions": 0,  # a batch fell through to a lower backend
            "watchdog_fires": 0,  # dispatches abandoned past the deadline
            "fallback_signatures": 0,  # signatures verified on the host ref
            "quarantined": 0,  # poisoned inputs isolated by bisection
        }

    def breaker(self, name: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                br = CircuitBreaker(name, clock=self._clock)
                self._breakers[name] = br
            return br

    def set_clock(self, clock: Callable[[], float]) -> None:
        """Swap the time source (sim/tests) for the registry AND every
        existing breaker; pass ``time.monotonic`` to restore."""
        with self._lock:
            self._clock = clock
            for br in self._breakers.values():
                br.clock = clock

    # -- counters ----------------------------------------------------------

    def record_demotion(self, backend: str) -> None:
        with self._lock:
            self._counters["demotions"] += 1

    def record_watchdog_fire(self, backend: str) -> None:
        with self._lock:
            self._counters["watchdog_fires"] += 1

    def record_fallback(self, n_signatures: int) -> None:
        with self._lock:
            self._counters["fallback_signatures"] += int(n_signatures)

    def record_quarantine(self, backend: str) -> None:
        with self._lock:
            self._counters["quarantined"] += 1

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            breakers = dict(self._breakers)
        out["breakers"] = {n: b.stats() for n, b in breakers.items()}
        # re-promotions live in each breaker's state machine (a half-open
        # probe passing); the cross-backend total is their sum
        out["repromotions"] = sum(
            s["repromotions"] for s in out["breakers"].values()
        )
        out["open_breakers"] = sum(
            1 for s in out["breakers"].values() if s["state"] == OPEN
        )
        out["half_open_breakers"] = sum(
            1 for s in out["breakers"].values() if s["state"] == HALF_OPEN
        )
        return out

    def breaker_states(self) -> dict:
        """{backend: state_code} for the labeled metrics gauge
        (0=closed, 1=half-open, 2=open)."""
        with self._lock:
            breakers = dict(self._breakers)
        return {n: _STATE_CODE[b.state] for n, b in breakers.items()}


_REGISTRY: Optional[HealthRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def registry() -> HealthRegistry:
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = HealthRegistry()
    return _REGISTRY


def reset() -> None:
    """Fresh registry (tests, sim scenario setup); also restores the real
    clock and re-reads the env knobs on next breaker creation."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = None


def snapshot() -> dict:
    return registry().snapshot()
