"""Pure-Python X25519 (RFC 7748) and ChaCha20-Poly1305 (RFC 8439).

Reference fallback for environments without the ``cryptography`` C
library: the SecretConnection handshake (p2p/secret_connection.py) needs
an X25519 ECDH and an AEAD, nothing else.  Orders of magnitude slower
than the C implementations, fine for the 1 KiB frames the p2p transport
moves in tests; production deployments ship ``cryptography``.

Both primitives are deliberately dependency-free big-int/word code so the
differential tests can pin them against the C library when it IS present.
"""

from __future__ import annotations

import os
import struct

# ----------------------------------------------------------------------
# X25519 (RFC 7748 §5)
# ----------------------------------------------------------------------

_P = 2**255 - 19
_A24 = 121665


def _decode_u(u: bytes) -> int:
    # mask the MSB per RFC 7748 §5 u-coordinate decoding
    return int.from_bytes(u[:31] + bytes([u[31] & 0x7F]), "little")


def _decode_scalar(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def x25519(scalar: bytes, u_bytes: bytes) -> bytes:
    """Montgomery ladder scalar multiplication; constant-structure (the
    swap is arithmetic, not a branch), though pure Python makes no real
    timing guarantees."""
    k = _decode_scalar(scalar)
    u = _decode_u(u_bytes) % _P
    x1, x2, z2, x3, z3 = u, 1, 0, u, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (k >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * (z3 * z3) % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, _P - 2, _P) % _P
    return out.to_bytes(32, "little")


_BASE_U = (9).to_bytes(32, "little")


class X25519PrivateKeyRef:
    """API shim matching the slice of ``cryptography``'s X25519PrivateKey
    the SecretConnection uses."""

    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 private key must be 32 bytes")
        self._raw = raw

    @classmethod
    def generate(cls) -> "X25519PrivateKeyRef":
        return cls(os.urandom(32))

    def public_key(self) -> "X25519PublicKeyRef":
        return X25519PublicKeyRef(x25519(self._raw, _BASE_U))

    def exchange(self, peer: "X25519PublicKeyRef") -> bytes:
        shared = x25519(self._raw, peer.public_bytes_raw())
        if shared == b"\x00" * 32:
            raise ValueError("X25519 exchange produced a low-order result")
        return shared


class X25519PublicKeyRef:
    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("X25519 public key must be 32 bytes")
        self._raw = raw

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "X25519PublicKeyRef":
        return cls(raw)

    def public_bytes_raw(self) -> bytes:
        return self._raw


# ----------------------------------------------------------------------
# ChaCha20 (RFC 8439 §2.3)
# ----------------------------------------------------------------------

_MASK32 = 0xFFFFFFFF


def _quarter(st, a, b, c, d):
    st[a] = (st[a] + st[b]) & _MASK32
    st[d] ^= st[a]
    st[d] = ((st[d] << 16) | (st[d] >> 16)) & _MASK32
    st[c] = (st[c] + st[d]) & _MASK32
    st[b] ^= st[c]
    st[b] = ((st[b] << 12) | (st[b] >> 20)) & _MASK32
    st[a] = (st[a] + st[b]) & _MASK32
    st[d] ^= st[a]
    st[d] = ((st[d] << 8) | (st[d] >> 24)) & _MASK32
    st[c] = (st[c] + st[d]) & _MASK32
    st[b] ^= st[c]
    st[b] = ((st[b] << 7) | (st[b] >> 25)) & _MASK32


def _chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    st = [
        0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
        *struct.unpack("<8I", key),
        counter & _MASK32,
        *struct.unpack("<3I", nonce),
    ]
    w = st[:]
    for _ in range(10):
        _quarter(w, 0, 4, 8, 12)
        _quarter(w, 1, 5, 9, 13)
        _quarter(w, 2, 6, 10, 14)
        _quarter(w, 3, 7, 11, 15)
        _quarter(w, 0, 5, 10, 15)
        _quarter(w, 1, 6, 11, 12)
        _quarter(w, 2, 7, 8, 13)
        _quarter(w, 3, 4, 9, 14)
    return struct.pack("<16I", *((a + b) & _MASK32 for a, b in zip(w, st)))


def _chacha20_xor_scalar(
    key: bytes, counter: int, nonce: bytes, data: bytes
) -> bytes:
    out = bytearray(len(data))
    for i in range(0, len(data), 64):
        block = _chacha20_block(key, counter + i // 64, nonce)
        chunk = data[i : i + 64]
        out[i : i + len(chunk)] = bytes(
            x ^ y for x, y in zip(chunk, block)
        )
    return bytes(out)


try:
    import numpy as _np
except ImportError:
    _np = None


def _np_rotl(x, n):
    return (x << _np.uint32(n)) | (x >> _np.uint32(32 - n))


def _np_quarter(st, a, b, c, d):
    st[a] += st[b]
    st[d] ^= st[a]
    st[d] = _np_rotl(st[d], 16)
    st[c] += st[d]
    st[b] ^= st[c]
    st[b] = _np_rotl(st[b], 12)
    st[a] += st[b]
    st[d] ^= st[a]
    st[d] = _np_rotl(st[d], 8)
    st[c] += st[d]
    st[b] ^= st[c]
    st[b] = _np_rotl(st[b], 7)


def _chacha20_xor_np(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    """All of the message's 64-byte blocks in one vectorized pass —
    byte-identical to the scalar path (the differential test pins it)."""
    nblocks = (len(data) + 63) // 64
    st = _np.empty((16, nblocks), dtype=_np.uint32)
    st[0:4] = _np.array(
        [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], _np.uint32
    )[:, None]
    st[4:12] = _np.frombuffer(key, "<u4")[:, None]
    st[12] = (counter + _np.arange(nblocks, dtype=_np.uint64)).astype(
        _np.uint32
    )
    st[13:16] = _np.frombuffer(nonce, "<u4")[:, None]
    w = st.copy()
    for _ in range(10):
        _np_quarter(w, 0, 4, 8, 12)
        _np_quarter(w, 1, 5, 9, 13)
        _np_quarter(w, 2, 6, 10, 14)
        _np_quarter(w, 3, 7, 11, 15)
        _np_quarter(w, 0, 5, 10, 15)
        _np_quarter(w, 1, 6, 11, 12)
        _np_quarter(w, 2, 7, 8, 13)
        _np_quarter(w, 3, 4, 9, 14)
    w += st
    keystream = w.T.astype("<u4").tobytes()[: len(data)]
    return (
        _np.frombuffer(data, _np.uint8)
        ^ _np.frombuffer(keystream, _np.uint8)
    ).tobytes()


# -- lane-packed bigint ChaCha20 ---------------------------------------
#
# One Python bigint per state word, holding every block's 32-bit lane in
# its own 64-bit slot (value in the low 32 bits, slack above).  CPython
# bigint +/^/<< run in C over all lanes at once, so the 80 quarter-rounds
# cost ~32 bigint ops each regardless of message size — an order of
# magnitude faster than per-op numpy dispatch on small frames.  Carries
# from + stay inside the 64-bit slot (32-bit values + carry < 2^33);
# shift cross-lane contamination lands in the slack and is masked off.

_LANE_CACHE: dict = {}


def _lane_consts(nblocks: int):
    cached = _LANE_CACHE.get(nblocks)
    if cached is None:
        spread = sum(1 << (64 * i) for i in range(nblocks))
        mask = 0xFFFFFFFF * spread
        cached = (spread, mask)
        if len(_LANE_CACHE) < 64:
            _LANE_CACHE[nblocks] = cached
    return cached


def _lane_rotl(x: int, n: int, mask: int) -> int:
    return ((x << n) & mask) | ((x >> (32 - n)) & mask)


def _chacha20_xor_packed(
    key: bytes, counter: int, nonce: bytes, data: bytes
) -> bytes:
    nblocks = (len(data) + 63) // 64
    spread, mask = _lane_consts(nblocks)
    init = [
        c * spread
        for c in (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
        + struct.unpack("<8I", key)
    ]
    init.append(
        sum(((counter + i) & _MASK32) << (64 * i) for i in range(nblocks))
    )
    init += [c * spread for c in struct.unpack("<3I", nonce)]
    w = list(init)
    for _ in range(10):
        for a, b, c, d in (
            (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
            (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
        ):
            wa, wb, wc, wd = w[a], w[b], w[c], w[d]
            wa = (wa + wb) & mask
            wd = _lane_rotl(wd ^ wa, 16, mask)
            wc = (wc + wd) & mask
            wb = _lane_rotl(wb ^ wc, 12, mask)
            wa = (wa + wb) & mask
            wd = _lane_rotl(wd ^ wa, 8, mask)
            wc = (wc + wd) & mask
            wb = _lane_rotl(wb ^ wc, 7, mask)
            w[a], w[b], w[c], w[d] = wa, wb, wc, wd
    # serialize: per-word lane extraction (u64 slots -> low u32), then
    # interleave back to block order
    out = _np.empty((nblocks, 16), dtype="<u4")
    size = 8 * nblocks
    for j in range(16):
        word = (w[j] + init[j]) & mask
        out[:, j] = _np.frombuffer(
            word.to_bytes(size, "little"), dtype="<u8"
        ).astype(_np.uint32)
    keystream = out.tobytes()[: len(data)]
    return (
        _np.frombuffer(data, _np.uint8)
        ^ _np.frombuffer(keystream, _np.uint8)
    ).tobytes()


def _chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    if _np is not None and data:
        return _chacha20_xor_packed(key, counter, nonce, data)
    return _chacha20_xor_scalar(key, counter, nonce, data)


# ----------------------------------------------------------------------
# Poly1305 (RFC 8439 §2.5) and the AEAD construction (§2.8)
# ----------------------------------------------------------------------

_P1305 = (1 << 130) - 5


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little")
    r &= 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block + b"\x01", "little")
        acc = (acc + n) * r % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return b"" if rem == 0 else b"\x00" * (16 - rem)


class InvalidTagRef(Exception):
    """Raised when AEAD authentication fails (``InvalidTag`` analog)."""


class ChaCha20Poly1305Ref:
    """RFC 8439 AEAD with the same encrypt/decrypt API slice as
    ``cryptography``'s ChaCha20Poly1305."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = key

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        otk = _chacha20_block(self._key, 0, nonce)[:32]
        mac_data = (
            aad + _pad16(aad) + ct + _pad16(ct)
            + struct.pack("<QQ", len(aad), len(ct))
        )
        return _poly1305(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        aad = aad or b""
        ct = _chacha20_xor(self._key, 1, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        aad = aad or b""
        if len(data) < 16:
            raise InvalidTagRef("ciphertext shorter than the tag")
        ct, tag = data[:-16], data[-16:]
        want = self._tag(nonce, ct, aad)
        # hmac.compare_digest without importing hmac: fixed 16-byte tags
        diff = 0
        for x, y in zip(tag, want):
            diff |= x ^ y
        if diff:
            raise InvalidTagRef("AEAD tag mismatch")
        return _chacha20_xor(self._key, 1, nonce, ct)
