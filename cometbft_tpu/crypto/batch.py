"""The pluggable batch-verification seam — where the TPU plugs in.

Reference: crypto/batch/batch.go:10-27 and crypto.BatchVerifier
(crypto/crypto.go:44-52).  ``create_batch_verifier(pub_key)`` hands back a
backend-selected verifier; everything above this seam (VoteSet, commit
verification, the light client) is backend-agnostic, exactly as in the
reference design.

Backends:
  * ``tpu``  — batched JAX kernel (cometbft_tpu.ops.verify): decompression,
    ladder and cofactored check on the accelerator; per-signature accept
    bits come back in one shot.
  * ``cpu``  — two-tier host verification (C-speed strict path + ZIP-215
    python fallback), used as oracle and when no accelerator is present.

Unlike the reference (which needs a second pass to attribute failures when a
random-linear-combination batch fails, types/validation.go:308-317), both
backends report per-signature validity directly.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from cometbft_tpu.crypto import keys as ck

_DEFAULT_BACKEND: Optional[str] = None
_LOCK = threading.Lock()


def _tpu_self_check() -> bool:
    """Startup safety net: verify a known-good + known-bad signature pair on
    the accelerator before trusting it for consensus.  A kernel regression
    (round 2 shipped one) otherwise makes the node reject every valid commit
    on TPU hardware.  Returns True iff the backend is trustworthy."""
    try:
        from cometbft_tpu.crypto import ed25519_ref as ref
        from cometbft_tpu.ops import verify as _ops_verify

        seed = b"\x42" * 32
        pub = ref.pubkey_from_seed(seed)
        msg = b"cometbft-tpu backend self-check"
        sig = ref.sign(seed, msg)
        bad = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        bits = _ops_verify.verify_batch([pub, pub], [msg, msg], [sig, bad])
        ok = bool(bits[0]) and not bool(bits[1])
        if not ok:
            logging.getLogger("cometbft_tpu.crypto").error(
                "TPU crypto backend FAILED its known-answer self-check "
                "(valid=%s, tampered=%s) — falling back to the CPU verify "
                "path; consensus is safe but orders of magnitude slower",
                bool(bits[0]),
                bool(bits[1]),
            )
        return ok
    except Exception:
        logging.getLogger("cometbft_tpu.crypto").exception(
            "TPU crypto backend self-check raised — falling back to the "
            "CPU verify path"
        )
        return False


def default_backend() -> str:
    """'tpu' when an accelerator is visible to JAX *and* it passes a
    known-answer self-check, else 'cpu'.  Overridable via config
    (config.crypto.backend) or COMETBFT_TPU_CRYPTO_BACKEND."""
    global _DEFAULT_BACKEND
    env = os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND")
    if env and env != "auto":
        return env
    with _LOCK:
        if _DEFAULT_BACKEND is None:
            try:
                import jax

                platform = jax.devices()[0].platform
                if platform == "cpu":
                    _DEFAULT_BACKEND = "cpu"
                else:
                    _DEFAULT_BACKEND = "tpu" if _tpu_self_check() else "cpu"
            except Exception:
                _DEFAULT_BACKEND = "cpu"
        return _DEFAULT_BACKEND


def set_default_backend(backend: Optional[str]) -> None:
    global _DEFAULT_BACKEND
    _DEFAULT_BACKEND = backend


class BatchVerifier:
    """Collects (pubkey, msg, sig) triples; verify() returns the overall
    result plus per-signature validity bits."""

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        raise NotImplementedError

    def verify(self) -> tuple[bool, list[bool]]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class _CollectingVerifier(BatchVerifier):
    """Shared collection + the two pre-device filters every backend wants:

    1. signature-cache prefilter — verdicts already known (e.g. votes
       verified at gossip time) never reach the backend again; only cache
       MISSES are verified, and fresh verdicts are written back;
    2. structural short-circuit — entries whose pub/sig lengths make them
       impossible for the key type are resolved to False on the host, so a
       batch of garbage does not occupy real device lanes (or inflate the
       padding bucket).

    Subclasses implement ``_verify_pending(pubs, msgs, sigs)`` over the
    surviving entries.  ``COMETBFT_TPU_SIGCACHE=0`` turns filter 1 off,
    restoring uncached behavior exactly (filter 2 only resolves entries
    every backend already reports False for)."""

    PUB_SIZES: tuple = ()  # empty = no structural filter on that field
    SIG_SIZES: tuple = ()

    def __init__(self):
        self.pubs: list[bytes] = []
        self.msgs: list[bytes] = []
        self.sigs: list[bytes] = []

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        data = pub_key.bytes() if hasattr(pub_key, "bytes") else bytes(pub_key)
        self.pubs.append(data)
        self.msgs.append(msg)
        self.sigs.append(sig)

    def __len__(self) -> int:
        return len(self.pubs)

    def _verify_pending(
        self, pubs: list[bytes], msgs: list[bytes], sigs: list[bytes]
    ) -> list[bool]:
        raise NotImplementedError

    def verify(self) -> tuple[bool, list[bool]]:
        if not self.pubs:
            return False, []
        from cometbft_tpu.crypto import sigcache

        bits, pending = sigcache.partition_misses(
            self.pubs, self.msgs, self.sigs, self.PUB_SIZES, self.SIG_SIZES
        )
        if pending:
            # Attribution contract: ``_verify_pending`` returns DEFINITIVE
            # verdicts only.  An infrastructure failure must either raise
            # (propagates — nothing is cached, the caller sees an error,
            # not a False bit) or yield ``None`` for the affected entries
            # (skipped by writeback so a possibly-valid signature is never
            # negative-cached, then surfaced as a BackendError below).
            got = self._verify_pending(
                [self.pubs[i] for i in pending],
                [self.msgs[i] for i in pending],
                [self.sigs[i] for i in pending],
            )
            sigcache.writeback(
                self.pubs, self.msgs, self.sigs, bits, pending, got
            )
        if any(b is None for b in bits):
            from cometbft_tpu.crypto import backend_health

            raise backend_health.BackendError(
                "batch backend produced no definitive verdict for some "
                "entries (infrastructure failure, not a signature verdict)"
            )
        bits = [bool(b) for b in bits]
        return all(bits) and len(bits) > 0, bits


class CpuBatchVerifier(_CollectingVerifier):
    PUB_SIZES = (32,)
    SIG_SIZES = (64,)

    def _verify_pending(self, pubs, msgs, sigs) -> list[bool]:
        return [
            ck.Ed25519PubKey(p).verify_signature(m, s)
            for p, m, s in zip(pubs, msgs, sigs)
        ]


class TpuBatchVerifier(_CollectingVerifier):
    PUB_SIZES = (32,)
    SIG_SIZES = (64,)

    def _verify_pending(self, pubs, msgs, sigs) -> list[bool]:
        from cometbft_tpu import verifysched

        if verifysched.scheduler_active():
            # the cache misses ride the process-wide continuous-batching
            # scheduler (at the caller's ambient priority class), so this
            # commit's segment coalesces with concurrent gossip/evidence/
            # light/catchup work into one fused dispatch — the scheduler
            # resolves only definitive supervised verdicts, matching this
            # method's attribution contract
            return verifysched.verify_segment_sync(pubs, msgs, sigs)
        from cometbft_tpu.ops import verify as _ops_verify

        return [bool(b) for b in _ops_verify.verify_batch(pubs, msgs, sigs)]


_SECP_DEVICE_OK: Optional[bool] = None


def _secp_device_ok() -> bool:
    """Lazy gate for the TPU ECDSA path: a known-answer accept/reject pair
    must match the host library before consensus trusts the device ladder
    (same discipline as ``_tpu_self_check``).  COMETBFT_TPU_SECP_DEVICE=1/0
    forces."""
    global _SECP_DEVICE_OK
    env = os.environ.get("COMETBFT_TPU_SECP_DEVICE")
    if env == "1":
        return True
    if env == "0":
        return False
    with _LOCK:
        if _SECP_DEVICE_OK is None:
            try:
                from cometbft_tpu.crypto.secp256k1 import Secp256k1PrivKey
                from cometbft_tpu.ops import secp_verify as sv

                priv = Secp256k1PrivKey.from_secret(
                    b"cometbft-tpu secp self-check"
                )
                pub = priv.pub_key().bytes()
                msg = b"secp backend self-check"
                sig = priv.sign(msg)
                bad = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
                bits = sv.verify_batch([pub, pub], [msg, msg], [sig, bad])
                _SECP_DEVICE_OK = bool(bits[0]) and not bool(bits[1])
                if not _SECP_DEVICE_OK:
                    logging.getLogger("cometbft_tpu.crypto").error(
                        "TPU secp256k1 backend FAILED its known-answer "
                        "self-check - using sequential host verification"
                    )
            except Exception:
                _SECP_DEVICE_OK = False
        return _SECP_DEVICE_OK


class Secp256k1BatchVerifier(_CollectingVerifier):
    """Per-lane batched ECDSA on the device (ops/secp_verify) — a TPU-era
    extension past the reference, which verifies secp256k1 sequentially
    (crypto/secp256k1/secp256k1.go; BASELINE config #4 tracks this).
    Falls back to the host `cryptography` library when the device fails
    its self-check or ``backend='cpu'`` pins it off."""

    PUB_SIZES = (33,)
    SIG_SIZES = (64,)

    def __init__(self, backend: Optional[str] = None):
        super().__init__()
        self._backend = backend

    def _verify_pending(self, pubs, msgs, sigs) -> list[bool]:
        if self._backend != "cpu" and _secp_device_ok():
            from cometbft_tpu.ops import supervisor

            if not supervisor.enabled():
                try:
                    from cometbft_tpu.ops import secp_verify as sv

                    return [bool(b) for b in sv.verify_batch(pubs, msgs, sigs)]
                except Exception:
                    logging.getLogger("cometbft_tpu.crypto").exception(
                        "device secp verify failed; host fallback"
                    )
            else:
                # supervised: the breaker decides whether the device is
                # probed at all, the watchdog bounds a wedge, and a failure
                # demotes (metrics + backoff) instead of silently retrying
                # the dead device on every batch
                from cometbft_tpu.crypto import backend_health

                def _device():
                    from cometbft_tpu.ops import secp_verify as sv

                    return [bool(b) for b in sv.verify_batch(pubs, msgs, sigs)]

                def _validate(bits):
                    if len(bits) != len(pubs):
                        raise backend_health.BackendOutputError(
                            f"secp device returned {len(bits)} bits "
                            f"for {len(pubs)} inputs"
                        )

                bits = supervisor.supervised_device_call(
                    "secp_device", _device, _validate,
                    fallback_units=len(pubs),
                )
                if bits is not None:
                    return bits
        from cometbft_tpu.crypto.secp256k1 import Secp256k1PubKey

        bits = []
        for p, m, s in zip(pubs, msgs, sigs):
            try:
                bits.append(Secp256k1PubKey(p).verify_signature(m, s))
            except ValueError:
                bits.append(False)
        return bits


_BLS_DEVICE_OK: Optional[bool] = None


def _bls_device_ok() -> bool:
    """Lazy gate for the TPU G1 path inside BLS batch verification: an
    accelerator must be visible AND a known-answer scalar-mul must match
    the host oracle before consensus trusts it (same discipline as
    ``_tpu_self_check``).  COMETBFT_TPU_BLS_DEVICE=1/0 forces."""
    global _BLS_DEVICE_OK
    env = os.environ.get("COMETBFT_TPU_BLS_DEVICE")
    if env == "1":
        return True
    if env == "0":
        return False
    with _LOCK:
        if _BLS_DEVICE_OK is None:
            try:
                import jax

                if jax.devices()[0].platform == "cpu":
                    # XLA-CPU runs the limb kernels orders of magnitude
                    # slower than host bigints — device path is TPU-only
                    _BLS_DEVICE_OK = False
                else:
                    from cometbft_tpu.crypto import bls12381 as bls
                    from cometbft_tpu.ops import bls_g1 as g1

                    gen = bls.E1.affine(bls.G1_GEN)
                    got = g1.batch_scalar_mul([gen], [0x1234], nbits=16)[0]
                    want = bls.E1.affine(
                        bls.E1.mul_scalar(bls.G1_GEN, 0x1234)
                    )
                    _BLS_DEVICE_OK = got == want
                    if not _BLS_DEVICE_OK:
                        logging.getLogger("cometbft_tpu.crypto").error(
                            "TPU BLS G1 backend FAILED its known-answer "
                            "self-check - using host arithmetic"
                        )
            except Exception:
                _BLS_DEVICE_OK = False
        return _BLS_DEVICE_OK


class BlsBatchVerifier(_CollectingVerifier):
    """Random-linear-combination batch verification for bls12_381.

    Check (basic scheme, per-vote distinct messages NOT required):

        e(G1, Σ rᵢ·Sᵢ)  ==  Π e(rᵢ·pkᵢ, H(mᵢ)),   rᵢ random 128-bit

    which costs n+1 Miller loops + ONE final exponentiation instead of the
    2n + n of sequential verifies.  The rᵢ·pkᵢ multi-scalar-mul runs on
    the TPU G1 kernel (ops/bls_g1) when the accelerator passes its
    self-check; G2 scalar work and the pairing product stay on the host
    (SURVEY §2.1.1 allows host pairing — one pair per batch after MSM).
    A failed combination falls back to per-signature verification for
    attribution, mirroring the reference's recheck pass
    (types/validation.go:308-317; key type crypto/bls12381/key_bls12381.go:
    160-188).

    ``backend='cpu'`` (the operator's accelerator kill-switch — config
    crypto.backend / COMETBFT_TPU_CRYPTO_BACKEND) pins the scalar-mul work
    to the host regardless of the device self-check."""

    PUB_SIZES = (96,)  # bls12381.PUB_KEY_SIZE (uncompressed G1)
    SIG_SIZES = (96,)  # bls12381.SIGNATURE_SIZE (compressed G2)

    def __init__(self, backend: Optional[str] = None):
        super().__init__()
        self._backend = backend

    def _verify_pending(self, pubs, msgs, sigs) -> list[bool]:
        import secrets

        from cometbft_tpu.crypto import bls12381 as bls

        n = len(pubs)
        lib = bls._nat()
        if lib is not None:
            return self._verify_native(lib, pubs, msgs, sigs)
        bits = [False] * n
        entries = []  # (index, pk_jac, h_jac, sig_jac)
        for i in range(n):
            pub, msg, sig = pubs[i], msgs[i], sigs[i]
            if len(pub) != bls.PUB_KEY_SIZE or len(sig) != bls.SIGNATURE_SIZE:
                continue
            pk = bls.g1_deserialize(pub)
            if pk is None or bls.E1.is_infinity(pk) or not bls._g1_subgroup(pk):
                continue
            s = bls.g2_uncompress(sig)
            if s is None or not bls._g2_subgroup(s):
                continue
            entries.append((i, pk, bls.hash_to_g2(msg), s))
        if not entries:
            return bits
        if len(entries) == 1:
            i, _, _, _ = entries[0]
            bits[i] = bls.verify(pubs[i], msgs[i], sigs[i])
            return bits

        rs = [secrets.randbits(128) | 1 for _ in entries]
        scaled = self._scaled_pubkeys(
            [e[1] for e in entries], rs, self._backend
        )
        agg = bls.E2.infinity()
        for (_, _, _, s), r in zip(entries, rs):
            agg = bls.E2.add_pts(agg, bls.E2.mul_scalar(s, r))
        pairs = [
            (bls.E1.neg_pt(rpk), h)
            for rpk, (_, _, h, _) in zip(scaled, entries)
        ]
        pairs.append((bls.G1_GEN, agg))
        if bls._pairing_product_is_one(pairs):
            for i, _, _, _ in entries:
                bits[i] = True
            return bits
        # attribution fallback: the combination failed, find the culprits
        return self._per_signature(pubs, msgs, sigs, [e[0] for e in entries], bits)

    @staticmethod
    def _per_signature(pubs, msgs, sigs, entries, bits) -> list[bool]:
        """Verify each structurally-valid entry on its own.  This is the
        refuge when a native batch op errors: such an error is an
        infrastructure failure, not evidence against any signature, so it
        must not surface as all-False bits (which would misattribute the
        failure to every signer in the batch)."""
        from cometbft_tpu.crypto import bls12381 as bls

        for i in entries:
            bits[i] = bls.verify(pubs[i], msgs[i], sigs[i])
        return bits

    def _verify_native(self, lib, pubs, msgs, sigs) -> list[bool]:
        """RLC batch verification with every host-side group/pairing op in
        the native library; the TPU G1 MSM still handles the rᵢ·pkᵢ
        multi-scalar-mul when the device passes its self-check.  Same
        check and attribution semantics as the pure-Python path.  Any
        native-op *error* (nonzero return) drops to ``_per_signature``."""
        import ctypes
        import secrets

        from cometbft_tpu.crypto import bls12381 as bls

        n = len(pubs)
        bits = [False] * n
        entries = []  # index of each structurally-valid (pub, msg, sig)
        for i in range(n):
            pub, sig = pubs[i], sigs[i]
            if len(pub) != bls.PUB_KEY_SIZE or len(sig) != bls.SIGNATURE_SIZE:
                continue
            if lib.bls_pubkey_validate(pub, len(pub)) != 1:
                continue
            if lib.bls_sig_validate(sig) != 1:
                continue
            entries.append(i)
        if not entries:
            return bits
        if len(entries) == 1:
            i = entries[0]
            bits[i] = bls.verify(pubs[i], msgs[i], sigs[i])
            return bits

        rs = [secrets.randbits(128) | 1 for _ in entries]
        r_bytes = [r.to_bytes(16, "big") for r in rs]

        # rᵢ·pkᵢ — TPU MSM when trusted, else native scalar mul.  With the
        # bls_g1 breaker open, skip straight to the native library: routing
        # through _scaled_pubkeys would land on the much slower pure-Python
        # host fallback, and the native path is the better degraded tier.
        use_device = self._backend != "cpu" and _bls_device_ok()
        if use_device:
            from cometbft_tpu.ops import supervisor

            if supervisor.enabled():
                from cometbft_tpu.crypto import backend_health

                use_device = (
                    backend_health.registry().breaker("bls_g1").state
                    != backend_health.OPEN
                )
        g1_parts = []
        if use_device:
            pks = [bls.g1_deserialize(pubs[i]) for i in entries]
            for pt in self._scaled_pubkeys(pks, rs, self._backend):
                g1_parts.append(bls.g1_serialize(bls.E1.neg_pt(pt)))
        else:
            for i, rb in zip(entries, r_bytes):
                out = ctypes.create_string_buffer(96)
                if lib.bls_g1_scalar_mul(pubs[i], rb, 16, out) != 0:
                    return self._per_signature(pubs, msgs, sigs, entries, bits)
                g1_parts.append(bls.g1_negate_serialized(out.raw))

        # Σ rᵢ·Sᵢ and H(mᵢ), all native
        scaled_sigs = []
        hashes = []
        for i, rb in zip(entries, r_bytes):
            so = ctypes.create_string_buffer(96)
            if lib.bls_g2_scalar_mul_compressed(sigs[i], rb, 16, so) != 0:
                return self._per_signature(pubs, msgs, sigs, entries, bits)
            scaled_sigs.append(so.raw)
            ho = ctypes.create_string_buffer(96)
            msg = msgs[i]
            if lib.bls_hash_to_g2(msg, len(msg), ho) != 0:
                return self._per_signature(pubs, msgs, sigs, entries, bits)
            hashes.append(ho.raw)
        agg = ctypes.create_string_buffer(96)
        if lib.bls_aggregate_sigs(
            b"".join(scaled_sigs), len(scaled_sigs), agg
        ) != 0:
            return self._per_signature(pubs, msgs, sigs, entries, bits)

        from cometbft_tpu.crypto.bls12381 import G1_GEN, g1_serialize

        g1cat = b"".join(g1_parts) + g1_serialize(G1_GEN)
        g2cat = b"".join(hashes) + agg.raw
        if lib.bls_pairing_product_is_one_serialized(
            g1cat, g2cat, len(entries) + 1
        ) == 1:
            for i in entries:
                bits[i] = True
            return bits
        # attribution fallback: the combination failed, find the culprits
        return self._per_signature(pubs, msgs, sigs, entries, bits)

    @staticmethod
    def _scaled_pubkeys(pks, rs, backend: Optional[str] = None):
        """[rᵢ·pkᵢ] as jacobian host points; TPU kernel when trusted and
        not disabled by the backend kill-switch.  Supervised: the bls_g1
        breaker skips a dead device, the watchdog bounds a wedge, and a
        failure demotes to host arithmetic with the same metrics as the
        ed25519 chain (scalar-mul output feeds a pairing CHECK, so a host
        fallback changes cost, never verdicts)."""
        from cometbft_tpu.crypto import bls12381 as bls

        if backend != "cpu" and _bls_device_ok():
            from cometbft_tpu.ops import supervisor

            def _device():
                from cometbft_tpu.ops import bls_g1 as g1

                affs = [bls.E1.affine(pk) for pk in pks]
                out = g1.batch_scalar_mul(affs, rs, nbits=128)
                return [
                    bls.E1.infinity() if a is None else (a[0], a[1], 1)
                    for a in out
                ]

            if not supervisor.enabled():
                try:
                    return _device()
                except Exception:
                    logging.getLogger("cometbft_tpu.crypto").exception(
                        "TPU BLS G1 path raised - host fallback"
                    )
            else:
                from cometbft_tpu.crypto import backend_health

                def _validate(out):
                    if len(out) != len(pks):
                        raise backend_health.BackendOutputError(
                            f"bls_g1 returned {len(out)} points for "
                            f"{len(pks)} inputs"
                        )

                out = supervisor.supervised_device_call(
                    "bls_g1", _device, _validate, fallback_units=len(pks)
                )
                if out is not None:
                    return out
        return [bls.E1.mul_scalar(pk, r) for pk, r in zip(pks, rs)]


def supports_batch_verifier(pub_key) -> bool:
    """Reference: crypto/batch/batch.go:21 — ed25519 there; bls12_381 joins
    via the aggregate path (key_bls12381.go:160-188); secp256k1 is the
    TPU-era extension (BASELINE config #4; no batch in the reference)."""
    return getattr(pub_key, "type_", None) in (
        ck.ED25519_KEY_TYPE,
        ck.BLS12381_KEY_TYPE,
        ck.SECP256K1_KEY_TYPE,
    )


def create_batch_verifier(pub_key, backend: Optional[str] = None) -> BatchVerifier:
    """Reference: crypto/batch/batch.go:10."""
    if not supports_batch_verifier(pub_key):
        raise ValueError(f"key type does not support batch verification: {pub_key}")
    key_type = getattr(pub_key, "type_", None)
    if key_type in (ck.BLS12381_KEY_TYPE, ck.SECP256K1_KEY_TYPE):
        env = os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND")
        if (backend is None or backend == "auto") and env and env != "auto":
            backend = env
        if key_type == ck.SECP256K1_KEY_TYPE:
            return Secp256k1BatchVerifier(backend=backend)
        return BlsBatchVerifier(backend=backend)
    if backend is None or backend == "auto":
        backend = default_backend()
    if backend == "tpu":
        return TpuBatchVerifier()
    if backend == "cpu":
        return CpuBatchVerifier()
    raise ValueError(f"unknown crypto backend: {backend}")
