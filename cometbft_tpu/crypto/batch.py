"""The pluggable batch-verification seam — where the TPU plugs in.

Reference: crypto/batch/batch.go:10-27 and crypto.BatchVerifier
(crypto/crypto.go:44-52).  ``create_batch_verifier(pub_key)`` hands back a
backend-selected verifier; everything above this seam (VoteSet, commit
verification, the light client) is backend-agnostic, exactly as in the
reference design.

Backends:
  * ``tpu``  — batched JAX kernel (cometbft_tpu.ops.verify): decompression,
    ladder and cofactored check on the accelerator; per-signature accept
    bits come back in one shot.
  * ``cpu``  — two-tier host verification (C-speed strict path + ZIP-215
    python fallback), used as oracle and when no accelerator is present.

Unlike the reference (which needs a second pass to attribute failures when a
random-linear-combination batch fails, types/validation.go:308-317), both
backends report per-signature validity directly.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from cometbft_tpu.crypto import keys as ck

_DEFAULT_BACKEND: Optional[str] = None
_LOCK = threading.Lock()


def _tpu_self_check() -> bool:
    """Startup safety net: verify a known-good + known-bad signature pair on
    the accelerator before trusting it for consensus.  A kernel regression
    (round 2 shipped one) otherwise makes the node reject every valid commit
    on TPU hardware.  Returns True iff the backend is trustworthy."""
    try:
        from cometbft_tpu.crypto import ed25519_ref as ref
        from cometbft_tpu.ops import verify as _ops_verify

        seed = b"\x42" * 32
        pub = ref.pubkey_from_seed(seed)
        msg = b"cometbft-tpu backend self-check"
        sig = ref.sign(seed, msg)
        bad = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        bits = _ops_verify.verify_batch([pub, pub], [msg, msg], [sig, bad])
        ok = bool(bits[0]) and not bool(bits[1])
        if not ok:
            logging.getLogger("cometbft_tpu.crypto").error(
                "TPU crypto backend FAILED its known-answer self-check "
                "(valid=%s, tampered=%s) — falling back to the CPU verify "
                "path; consensus is safe but orders of magnitude slower",
                bool(bits[0]),
                bool(bits[1]),
            )
        return ok
    except Exception:
        logging.getLogger("cometbft_tpu.crypto").exception(
            "TPU crypto backend self-check raised — falling back to the "
            "CPU verify path"
        )
        return False


def default_backend() -> str:
    """'tpu' when an accelerator is visible to JAX *and* it passes a
    known-answer self-check, else 'cpu'.  Overridable via config
    (config.crypto.backend) or COMETBFT_TPU_CRYPTO_BACKEND."""
    global _DEFAULT_BACKEND
    env = os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND")
    if env and env != "auto":
        return env
    with _LOCK:
        if _DEFAULT_BACKEND is None:
            try:
                import jax

                platform = jax.devices()[0].platform
                if platform == "cpu":
                    _DEFAULT_BACKEND = "cpu"
                else:
                    _DEFAULT_BACKEND = "tpu" if _tpu_self_check() else "cpu"
            except Exception:
                _DEFAULT_BACKEND = "cpu"
        return _DEFAULT_BACKEND


def set_default_backend(backend: Optional[str]) -> None:
    global _DEFAULT_BACKEND
    _DEFAULT_BACKEND = backend


class BatchVerifier:
    """Collects (pubkey, msg, sig) triples; verify() returns the overall
    result plus per-signature validity bits."""

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        raise NotImplementedError

    def verify(self) -> tuple[bool, list[bool]]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class _CollectingVerifier(BatchVerifier):
    def __init__(self):
        self.pubs: list[bytes] = []
        self.msgs: list[bytes] = []
        self.sigs: list[bytes] = []

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        data = pub_key.bytes() if hasattr(pub_key, "bytes") else bytes(pub_key)
        self.pubs.append(data)
        self.msgs.append(msg)
        self.sigs.append(sig)

    def __len__(self) -> int:
        return len(self.pubs)


class CpuBatchVerifier(_CollectingVerifier):
    def verify(self) -> tuple[bool, list[bool]]:
        bits = [
            ck.Ed25519PubKey(p).verify_signature(m, s)
            if len(p) == 32
            else False
            for p, m, s in zip(self.pubs, self.msgs, self.sigs)
        ]
        return all(bits) and len(bits) > 0, bits


class TpuBatchVerifier(_CollectingVerifier):
    def verify(self) -> tuple[bool, list[bool]]:
        if not self.pubs:
            return False, []
        from cometbft_tpu.ops import verify as _ops_verify

        bits = _ops_verify.verify_batch(self.pubs, self.msgs, self.sigs)
        bits = [bool(b) for b in bits]
        return all(bits), bits


def supports_batch_verifier(pub_key) -> bool:
    """Reference: crypto/batch/batch.go:21."""
    return getattr(pub_key, "type_", None) == ck.ED25519_KEY_TYPE


def create_batch_verifier(pub_key, backend: Optional[str] = None) -> BatchVerifier:
    """Reference: crypto/batch/batch.go:10."""
    if not supports_batch_verifier(pub_key):
        raise ValueError(f"key type does not support batch verification: {pub_key}")
    if backend is None or backend == "auto":
        backend = default_backend()
    if backend == "tpu":
        return TpuBatchVerifier()
    if backend == "cpu":
        return CpuBatchVerifier()
    raise ValueError(f"unknown crypto backend: {backend}")
