"""Consensus-wide signature verification cache.

A bounded, thread-safe LRU mapping SHA-256(pub ‖ msg ‖ sig) -> bool.  Every
vote signature is verified at gossip time (``vote_set.add_vote``); the same
signature is re-verified when the commit built from those votes is checked
at apply time (``state/execution.validate_block`` -> ``verify_commit``),
when blocksync re-checks a served commit, and when an extended commit is
validated.  Caching the verdict makes those re-verifications near-free and
lets the batch verifiers ship only cache MISSES to the device.

Key safety (docs/verify-stream.md):
  * the key digests the FULL (pub, msg, sig) triple with length framing, so
    two distinct triples can never alias short of a SHA-256 collision;
  * signature verification is a pure function of the triple — in particular
    an *invalid* triple is invalid forever, so negative caching is safe;
  * a wrong *prediction* (e.g. blocksync prefetching against a stale
    validator set) caches a verdict for a triple that is simply never
    queried — it can waste a slot, never corrupt an answer;
  * verdicts are implementation-independent, so it does not matter WHICH
    verifier produced a cached bit: every ed25519 path is ZIP-215 — the
    device kernel by construction, and the host single-sig path because
    ``Ed25519PubKey.verify_signature`` falls back to ``verify_zip215``
    whenever the strict library rejects (strict acceptance implies ZIP-215
    acceptance) — while the secp256k1/BLS device paths are gated by
    known-answer self-checks and differential-tested against their host
    oracles.  A node must never mix verifiers that genuinely disagree;
    that invariant predates this cache (batch vs single verification
    already selected per call site) and is what the self-checks enforce.

Kill-switch: ``COMETBFT_TPU_SIGCACHE=0`` disables lookups AND inserts,
restoring the uncached behavior exactly.  ``COMETBFT_TPU_SIGCACHE_SIZE``
bounds the entry count (default 65536; ~48 B of digest+flag per entry plus
dict overhead keeps the default well under 10 MB).
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional

DEFAULT_CAPACITY = 65536


def _key(pub: bytes, msg: bytes, sig: bytes) -> bytes:
    h = hashlib.sha256()
    # length framing: (pub, msg, sig) concatenations can otherwise alias
    # across entries with variable-length msgs
    h.update(len(pub).to_bytes(4, "little"))
    h.update(pub)
    h.update(len(msg).to_bytes(4, "little"))
    h.update(msg)
    h.update(sig)
    return h.digest()


class SigCache:
    """LRU over verification verdicts; all methods are thread-safe."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, bool]" = OrderedDict()
        self._hits = 0
        self._misses = 0

    @staticmethod
    def enabled() -> bool:
        return os.environ.get("COMETBFT_TPU_SIGCACHE", "1") != "0"

    def get(self, pub: bytes, msg: bytes, sig: bytes) -> Optional[bool]:
        """Cached verdict or None.  Disabled cache always misses (without
        counting: the stats then honestly read as all-miss-no-traffic)."""
        if not self.enabled():
            return None
        return self._get(_key(pub, msg, sig))

    def _get(self, k: bytes) -> Optional[bool]:
        """Lookup past the kill-switch check — batch callers
        (``partition_misses``) hoist ``enabled()`` to once per batch; a
        10k-signature commit must not pay an os.environ read per entry."""
        with self._lock:
            v = self._entries.get(k)
            if v is None:
                self._misses += 1
                return None
            self._entries.move_to_end(k)
            self._hits += 1
            return v

    def put(self, pub: bytes, msg: bytes, sig: bytes, ok: bool) -> None:
        if not self.enabled():
            return
        self._put(_key(pub, msg, sig), ok)

    def _put(self, k: bytes, ok: bool) -> None:
        with self._lock:
            self._entries[k] = bool(ok)
            self._entries.move_to_end(k)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._hits = 0
            self._misses = 0

    def stats(self) -> dict:
        with self._lock:
            hits, misses = self._hits, self._misses
            size = len(self._entries)
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "size": size,
            "capacity": self.capacity,
            "hit_rate": (hits / total) if total else 0.0,
        }


_CACHE: Optional[SigCache] = None
_CACHE_LOCK = threading.Lock()


def get_cache() -> SigCache:
    """The process-wide cache (consensus, blocksync, light client and the
    batch verifiers all share one — that sharing IS the optimization)."""
    global _CACHE
    if _CACHE is None:
        with _CACHE_LOCK:
            if _CACHE is None:
                cap = int(
                    os.environ.get(
                        "COMETBFT_TPU_SIGCACHE_SIZE", str(DEFAULT_CAPACITY)
                    )
                )
                _CACHE = SigCache(cap)
    return _CACHE


def reset_cache() -> None:
    """Drop the process-wide cache (tests; also re-reads the size env)."""
    global _CACHE
    with _CACHE_LOCK:
        _CACHE = None


def partition_misses(
    pubs,
    msgs,
    sigs,
    pub_sizes: tuple = (32,),
    sig_sizes: tuple = (64,),
):
    """THE cache/structural prefilter, shared by every consumer (batch
    verifiers, blocksync window prefetch, light-client chain sync) so the
    size rules and get/put protocol cannot diverge.

    Returns (bits, miss_indices): ``bits[i]`` is the resolved verdict —
    False for structurally impossible pub/sig lengths (they must never
    occupy backend lanes), the cached verdict on a hit — or None for the
    entries listed in ``miss_indices``, which the caller verifies and
    feeds to ``writeback``.  Empty ``pub_sizes``/``sig_sizes`` disable
    that structural filter."""
    cache = get_cache()
    enabled = cache.enabled()  # hoisted: one env read per batch, not per sig
    bits: list = [None] * len(pubs)
    miss: list = []
    for i, (p, m, s) in enumerate(zip(pubs, msgs, sigs)):
        if (pub_sizes and len(p) not in pub_sizes) or (
            sig_sizes and len(s) not in sig_sizes
        ):
            bits[i] = False
            continue
        hit = cache._get(_key(p, m, s)) if enabled else None
        if hit is not None:
            bits[i] = hit
            continue
        miss.append(i)
    return bits, miss


def writeback(pubs, msgs, sigs, bits, miss_indices, results) -> None:
    """Resolve ``partition_misses``'s holes: record each fresh verdict in
    ``bits`` and in the cache (``results`` aligns with ``miss_indices``).

    Only DEFINITIVE verdicts are cached: a ``None`` result marks an entry
    the backend could not judge (an infrastructure failure — see
    docs/backend-supervisor.md).  Caching ``False`` for it would negative-
    cache a possibly-valid signature forever, so the hole is left in
    ``bits`` for the caller to surface as an error, never as a verdict."""
    cache = get_cache()
    enabled = cache.enabled()  # hoisted: one env read per batch, not per sig
    for i, r in zip(miss_indices, results):
        if r is None:
            continue
        r = bool(r)
        bits[i] = r
        if enabled:
            cache._put(_key(pubs[i], msgs[i], sigs[i]), r)


def verify_with_cache(pub_key, msg: bytes, sig: bytes) -> bool:
    """Single-signature verification through the cache: the drop-in for
    ``pub_key.verify_signature(msg, sig)`` on consensus paths (vote,
    proposal, vote-extension checks)."""
    pub = pub_key.bytes() if hasattr(pub_key, "bytes") else bytes(pub_key)
    cache = get_cache()
    hit = cache.get(pub, msg, sig)
    if hit is not None:
        return hit
    ok = bool(pub_key.verify_signature(msg, sig))
    cache.put(pub, msg, sig, ok)
    return ok
