"""Pure-Python Ed25519 with ZIP-215 verification semantics.

This is the *correctness oracle* for the TPU (JAX/Pallas) batch verifier, and
the implementation used for signing (validators sign one vote at a time — the
hot path is verification, not signing).

Semantics mirror what the reference gets from curve25519-voi with ZIP-215
options (reference: crypto/ed25519/ed25519.go:170-222):

  * Point decompression accepts non-canonical y encodings (y >= p) and
    small/mixed-order points.  The only rejection is a non-square x^2
    candidate.
  * The scalar ``s`` of a signature must be canonical (s < L).
  * The verification equation is cofactored:  [8][s]B == [8]R + [8][h]A.

Everything here is plain Python big-int arithmetic — slow but transparent,
used for tests, key generation, vote signing and as a differential oracle for
the JAX kernels in ``cometbft_tpu.ops``.
"""

from __future__ import annotations

import hashlib
import os
import threading as _threading
from collections import OrderedDict
from functools import lru_cache as _lru_cache
from typing import Optional, Tuple

# ---------------------------------------------------------------------------
# Field / curve constants (Curve25519 / edwards25519, RFC 8032 section 5.1)
# ---------------------------------------------------------------------------

P = 2**255 - 19
# Curve constant d = -121665/121666 mod p.
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
# Group order of the prime-order subgroup.
L = 2**252 + 27742317777372353535851937790883648493
# sqrt(-1) mod p (= 2^((p-1)/4)).
SQRT_M1 = pow(2, (P - 1) // 4, P)

Point = Tuple[int, int, int, int]  # extended homogeneous (X, Y, Z, T), T=XY/Z

IDENTITY: Point = (0, 1, 1, 0)


def _fe_sqrt_ratio(u: int, v: int) -> Tuple[bool, int]:
    """Return (ok, x) with x = sqrt(u/v) when u/v is square mod p.

    Uses the (p+3)/8 exponent trick: x = u*v^3 * (u*v^7)^((p-5)/8); if
    v*x^2 == -u the root is x*sqrt(-1); otherwise u/v was not a square.
    """
    v3 = (v * v % P) * v % P
    v7 = (v3 * v3 % P) * v % P
    x = (u * v3 % P) * pow(u * v7 % P, (P - 5) // 8, P) % P
    vx2 = v * x % P * x % P
    if vx2 == u % P:
        return True, x
    if vx2 == (-u) % P:
        return True, x * SQRT_M1 % P
    return False, 0


# ---------------------------------------------------------------------------
# Point arithmetic (extended twisted Edwards coordinates, RFC 8032 5.1.4)
# ---------------------------------------------------------------------------

def pt_add(p1: Point, p2: Point) -> Point:
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = T1 * D2 % P * T2 % P
    Dv = Z1 * 2 % P * Z2 % P
    E = (B - A) % P
    F = (Dv - C) % P
    G = (Dv + C) % P
    H = (B + A) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_double(p1: Point) -> Point:
    X1, Y1, Z1, _ = p1
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 % P * Z1 % P
    H = (A + B) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A - B) % P
    F = (C + G) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_neg(p1: Point) -> Point:
    X, Y, Z, T = p1
    return ((-X) % P, Y, Z, (-T) % P)


def pt_mul(k: int, p1: Point) -> Point:
    acc = IDENTITY
    while k > 0:
        if k & 1:
            acc = pt_add(acc, p1)
        p1 = pt_double(p1)
        k >>= 1
    return acc


# ---------------------------------------------------------------------------
# Comb-table scalar multiplication
#
# A comb table for point P holds, for every 4-bit window i of the scalar,
# the multiples [j * 16^i * P for j in 1..15].  A scalar-mul is then just
# one table lookup + point addition per non-zero nibble (<= 64 additions,
# no doublings), ~8x cheaper than the double-and-add ladder.  Tables are
# built once per point: at import for the base point, LRU-cached per
# public key for verification — validators verify the same handful of
# keys thousands of times, which is the hot path this exists for.
# ---------------------------------------------------------------------------

_COMB_WINDOWS = 64  # 64 x 4-bit nibbles covers any scalar < 2^256


def _build_comb(p1: Point) -> tuple:
    rows = []
    base = p1
    for _ in range(_COMB_WINDOWS):
        row = [None, base]
        for j in range(2, 16):
            row.append(pt_add(row[j - 1], base))
        rows.append(tuple(row))
        for _ in range(4):
            base = pt_double(base)
    return tuple(rows)


def _comb_mul(comb: tuple, k: int) -> Point:
    acc = IDENTITY
    i = 0
    while k > 0:
        nib = k & 15
        if nib:
            acc = pt_add(acc, comb[i][nib])
        k >>= 4
        i += 1
    return acc


def pt_equal(p1: Point, p2: Point) -> bool:
    """Projective equality: X1*Z2 == X2*Z1 and Y1*Z2 == Y2*Z1."""
    X1, Y1, Z1, _ = p1
    X2, Y2, Z2, _ = p2
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def pt_is_identity(p1: Point) -> bool:
    X, Y, Z, _ = p1
    return X % P == 0 and (Y - Z) % P == 0


# Base point B: y = 4/5 mod p, x recovered with even sign.
_by = 4 * pow(5, P - 2, P) % P
_ok, _bx = _fe_sqrt_ratio((_by * _by - 1) % P, (D * _by * _by + 1) % P)
assert _ok
if _bx & 1:  # RFC 8032: base point has x with sign bit 0
    _bx = P - _bx
BASE: Point = (_bx, _by, 1, _bx * _by % P)

_BASE_COMB = _build_comb(BASE)


def pt_mul_base(k: int) -> Point:
    """k*B through the precomputed base-point comb (sign / verify hot path)."""
    return _comb_mul(_BASE_COMB, k)


# ---------------------------------------------------------------------------
# Encoding / decoding
# ---------------------------------------------------------------------------

def pt_compress(p1: Point) -> bytes:
    X, Y, Z, _ = p1
    zi = pow(Z, P - 2, P)
    x = X * zi % P
    y = Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def pt_decompress_zip215(b: bytes) -> Optional[Point]:
    """ZIP-215 decompression: non-canonical y (>= p) and small-order points
    are accepted; the only failure mode is a non-square x^2 candidate."""
    if len(b) != 32:
        return None
    enc = int.from_bytes(b, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)  # NOT reduced-checked: y >= p is accepted
    y %= P
    u = (y * y - 1) % P
    v = (D * y % P * y + 1) % P
    ok, x = _fe_sqrt_ratio(u, v)
    if not ok:
        return None
    if x & 1:
        x = P - x  # normalize to the even ("nonnegative") root
    if sign:
        x = (P - x) % P  # x == 0 stays 0: non-canonical sign bit accepted
    return (x, y, 1, x * y % P)


def sc_reduce(b: bytes) -> int:
    return int.from_bytes(b, "little") % L


# ---------------------------------------------------------------------------
# Keys / sign / verify
# ---------------------------------------------------------------------------

def _expand_seed(seed: bytes) -> Tuple[int, bytes]:
    if len(seed) != 32:
        raise ValueError(f"ed25519 seed must be 32 bytes, got {len(seed)}")
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def pubkey_from_seed(seed: bytes) -> bytes:
    a, _ = _expand_seed(seed)
    return pt_compress(pt_mul_base(a))


def generate_seed() -> bytes:
    return os.urandom(32)


@_lru_cache(maxsize=64)
def _expanded_with_pub(seed: bytes) -> Tuple[int, bytes, bytes]:
    """(a, prefix, compressed pub) for a seed — one comb-mul, cached so a
    validator signing thousands of votes derives its pubkey once."""
    a, prefix = _expand_seed(seed)
    return a, prefix, pt_compress(pt_mul_base(a))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 Ed25519 signing."""
    a, prefix, pub = _expanded_with_pub(seed)
    r = sc_reduce(hashlib.sha512(prefix + msg).digest())
    R = pt_compress(pt_mul_base(r))
    k = sc_reduce(hashlib.sha512(R + pub + msg).digest())
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


# Comb tables per compressed public key, built on SECOND sight: a comb
# build costs ~3 ladder muls, so a one-shot key (fuzzed garbage, an
# ephemeral peer) sticks to the plain ladder while any repeated key — a
# validator verifying thousands of votes — gets the ~8x comb.  Keyed on
# the encoding, not the point: ZIP-215 accepts non-canonical encodings,
# and two encodings of one point simply build equal tables.  Failed
# decompressions are never cached, so garbage cannot evict real keys.
_PUB_COMB_CACHE: "OrderedDict[bytes, tuple]" = OrderedDict()
_PUB_COMB_MAX = 256
_PUB_SEEN: "OrderedDict[bytes, int]" = OrderedDict()
_PUB_SEEN_MAX = 1024
# verify_zip215 runs concurrently on reactor/consensus/p2p threads; LRU
# bookkeeping (get + move_to_end vs evicting insert) must be atomic or a
# hit can race an eviction into a KeyError out of signature verification
_COMB_LOCK = _threading.Lock()


def _comb_caches_clear() -> None:
    with _COMB_LOCK:
        _PUB_COMB_CACHE.clear()
        _PUB_SEEN.clear()


def _pub_comb(pub: bytes) -> Optional[tuple]:
    """Comb table for a compressed public key, or None (first sight or
    decompress failure) — the caller falls back to the ladder."""
    with _COMB_LOCK:
        comb = _PUB_COMB_CACHE.get(pub)
        if comb is not None:
            _PUB_COMB_CACHE.move_to_end(pub)
            return comb
        seen = _PUB_SEEN.get(pub, 0) + 1
        if seen < 2:
            _PUB_SEEN[pub] = seen
            _PUB_SEEN.move_to_end(pub)
            if len(_PUB_SEEN) > _PUB_SEEN_MAX:
                _PUB_SEEN.popitem(last=False)
            return None
    A = pt_decompress_zip215(pub)
    if A is None:
        return None
    comb = _build_comb(A)  # outside the lock: ~1100 point ops
    with _COMB_LOCK:
        _PUB_SEEN.pop(pub, None)
        _PUB_COMB_CACHE[pub] = comb
        if len(_PUB_COMB_CACHE) > _PUB_COMB_MAX:
            _PUB_COMB_CACHE.popitem(last=False)
    return comb


def verify_zip215(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Cofactored single verification with ZIP-215 semantics."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    comb_a = _pub_comb(pub)
    if comb_a is None:
        A = pt_decompress_zip215(pub)
        if A is None:
            return False
    R = pt_decompress_zip215(sig[:32])
    if R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:  # s must be canonical
        return False
    h = sc_reduce(hashlib.sha512(sig[:32] + pub + msg).digest())
    hA = _comb_mul(comb_a, h) if comb_a is not None else pt_mul(h, A)
    # Q = s*B - h*A - R ; accept iff [8]Q == identity.
    Q = pt_add(pt_add(pt_mul_base(s), pt_neg(hA)), pt_neg(R))
    for _ in range(3):
        Q = pt_double(Q)
    return pt_is_identity(Q)


def batch_inputs_valid(pub: bytes, sig: bytes) -> bool:
    """Cheap structural checks shared by batch paths."""
    return len(pub) == 32 and len(sig) == 64
