"""Merkle proof-operator chains: multi-tree proof composition for ABCI
queries.

Reference: crypto/merkle/proof_op.go (ProofOperator/ProofOperators/
ProofRuntime), proof_value.go (the "simple:v" value op), and
proof_key_path.go (the /App/IBC/x:0102 key-path encoding).  An ABCI app
proves a key under its own store tree, whose root is itself a leaf of a
higher tree; the runtime walks the chain, consuming one key-path segment
per keyed operator, and checks the final root.

Wire format: ProofOp/ProofOps/ValueOp exactly as
proto/cometbft/crypto/v1/proof.proto, via libs/protoenc.
"""

from __future__ import annotations

import urllib.parse
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from cometbft_tpu.crypto import merkle, tmhash
from cometbft_tpu.libs import protoenc as pe

PROOF_OP_VALUE = "simple:v"

KEY_ENCODING_URL = 0
KEY_ENCODING_HEX = 1


class ProofError(ValueError):
    """Invalid proof, key path, or operator chain."""


# ---------------------------------------------------------------------------
# Key paths (proof_key_path.go): "/App/x:010203" — URL or hex segments.
# ---------------------------------------------------------------------------


class KeyPath:
    def __init__(self) -> None:
        self._parts: list[str] = []

    def append_key(self, key: bytes, enc: int = KEY_ENCODING_URL) -> "KeyPath":
        if enc == KEY_ENCODING_URL:
            self._parts.append(urllib.parse.quote(key.decode("utf-8"), safe=""))
        elif enc == KEY_ENCODING_HEX:
            self._parts.append("x:" + key.hex())
        else:
            raise ProofError(f"unknown key encoding {enc}")
        return self

    def __str__(self) -> str:
        return "/" + "/".join(self._parts)


def key_path_to_keys(path: str) -> list[bytes]:
    """Decode "/seg/seg/x:hex" into raw keys (proof_key_path.go:89-113)."""
    if not path or not path.startswith("/"):
        raise ProofError(f"key path {path!r} must start with '/'")
    out = []
    for part in path[1:].split("/"):
        if part.startswith("x:"):
            hexpart = part[2:]
            try:
                out.append(bytes.fromhex(hexpart))
            except ValueError as e:
                raise ProofError(f"bad hex segment {part!r}: {e}") from e
        else:
            out.append(urllib.parse.unquote(part).encode("utf-8"))
    return out


# ---------------------------------------------------------------------------
# Wire types (proto/cometbft/crypto/v1/proof.proto)
# ---------------------------------------------------------------------------


@dataclass
class ProofOp:
    type: str
    key: bytes
    data: bytes

    def encode(self) -> bytes:
        return (
            pe.t_string(1, self.type) + pe.t_bytes(2, self.key)
            + pe.t_bytes(3, self.data)
        )

    @staticmethod
    def decode(raw: bytes) -> "ProofOp":
        f = pe.fields_dict(raw)
        return ProofOp(
            type=(f.get(1, [b""])[0]).decode("utf-8"),
            key=f.get(2, [b""])[0],
            data=f.get(3, [b""])[0],
        )


@dataclass
class ProofOps:
    ops: list = field(default_factory=list)

    def encode(self) -> bytes:
        return b"".join(pe.t_message(1, op.encode(), always=True) for op in self.ops)

    @staticmethod
    def decode(raw: bytes) -> "ProofOps":
        f = pe.fields_dict(raw)
        return ProofOps(ops=[ProofOp.decode(x) for x in f.get(1, [])])


def _encode_proof(p: merkle.Proof) -> bytes:
    out = pe.t_varint(1, p.total) + pe.t_varint(2, p.index)
    out += pe.t_bytes(3, p.leaf_hash)
    for a in p.aunts:
        out += pe.t_bytes(4, a)
    return out


def _decode_proof(raw: bytes) -> merkle.Proof:
    f = pe.fields_dict(raw)
    return merkle.Proof(
        total=pe.to_int64(f.get(1, [0])[0]),
        index=pe.to_int64(f.get(2, [0])[0]),
        leaf_hash=f.get(3, [b""])[0],
        aunts=list(f.get(4, [])),
    )


# ---------------------------------------------------------------------------
# ValueOp (proof_value.go): proves value under key in a SimpleMap tree.
# ---------------------------------------------------------------------------


def _encode_byte_slice(b: bytes) -> bytes:
    return pe.uvarint(len(b)) + b


@dataclass
class ValueOp:
    key: bytes
    proof: merkle.Proof

    def get_key(self) -> bytes:
        return self.key

    def run(self, args: Sequence[bytes]) -> list[bytes]:
        """value -> SimpleMap root (proof_value.go:88-115): the leaf is
        leafHash(len-prefixed key || len-prefixed sha256(value))."""
        if len(args) != 1:
            raise ProofError(f"value op expects 1 arg, got {len(args)}")
        vhash = tmhash.sum256(args[0])
        kv = _encode_byte_slice(self.key) + _encode_byte_slice(vhash)
        kvhash = merkle._leaf_hash(kv)
        if kvhash != self.proof.leaf_hash:
            raise ProofError(
                f"leaf {kvhash.hex()} != proof leaf {self.proof.leaf_hash.hex()}"
            )
        root = merkle._compute_root(
            self.proof.leaf_hash, self.proof.index, self.proof.total,
            list(self.proof.aunts),
        )
        if root is None:
            raise ProofError("proof does not compute a root")
        return [root]

    def proof_op(self) -> ProofOp:
        data = pe.t_bytes(1, self.key) + pe.t_message(
            2, _encode_proof(self.proof), always=True
        )
        return ProofOp(type=PROOF_OP_VALUE, key=self.key, data=data)


def value_op_decoder(pop: ProofOp) -> ValueOp:
    if pop.type != PROOF_OP_VALUE:
        raise ProofError(f"unexpected op type {pop.type!r}")
    f = pe.fields_dict(pop.data)
    proof_raw = f.get(2, [b""])[0]
    return ValueOp(key=pop.key, proof=_decode_proof(proof_raw))


# ---------------------------------------------------------------------------
# ProofOperators / ProofRuntime (proof_op.go:36-118, 151-157)
# ---------------------------------------------------------------------------


class ProofOperators(list):
    """Chain of operators applied in order; keyed operators consume
    key-path segments from the END of the path (innermost tree first)."""

    def verify_value(self, root: bytes, keypath: str, value: bytes) -> None:
        self.verify(root, keypath, [value])

    def verify(self, root: bytes, keypath: str, args: Sequence[bytes]) -> None:
        if len(self) == 0:
            raise ProofError("no proof operators")
        keys = key_path_to_keys(keypath)
        for i, op in enumerate(self):
            key = op.get_key()
            if key:
                if not keys:
                    raise ProofError(
                        f"key path exhausted but op #{i} wants {key!r}"
                    )
                if keys[-1] != key:
                    raise ProofError(
                        f"key mismatch on op #{i}: path has {keys[-1]!r}, "
                        f"op has {key!r}"
                    )
                keys.pop()
            args = op.run(args)
        if not args:
            raise ProofError("proof operators produced no root")
        if args[0] != root:
            raise ProofError(f"computed root {args[0].hex()}, want {root.hex()}")
        if keys:
            raise ProofError("merkle: keypath not consumed")


class ProofRuntime:
    def __init__(self) -> None:
        self._decoders: dict[str, Callable[[ProofOp], object]] = {}

    def register_op_decoder(self, typ: str, dec) -> None:
        if typ in self._decoders:
            raise ProofError(f"already registered for type {typ!r}")
        self._decoders[typ] = dec

    def decode(self, pop: ProofOp):
        dec = self._decoders.get(pop.type)
        if dec is None:
            raise ProofError(f"unrecognized proof type {pop.type!r}")
        return dec(pop)

    def decode_proof(self, proof: ProofOps) -> ProofOperators:
        return ProofOperators(self.decode(pop) for pop in proof.ops)

    def verify_value(
        self, proof: ProofOps, root: bytes, keypath: str, value: bytes
    ) -> None:
        self.decode_proof(proof).verify(root, keypath, [value])

    def verify_absence(
        self, proof: ProofOps, root: bytes, keypath: str
    ) -> None:
        """Verify a proof of non-existence (empty args; proof_op.go:137).

        The arg list must be EMPTY, not ``[b""]``: an existence proof of an
        empty stored value verifies against ``[b""]``, which would let it
        masquerade as an absence proof (inverted safety semantics)."""
        self.decode_proof(proof).verify(root, keypath, [])


def default_proof_runtime() -> ProofRuntime:
    """Knows value proofs only, like the reference (proof_op.go:151-157)."""
    prt = ProofRuntime()
    prt.register_op_decoder(PROOF_OP_VALUE, value_op_decoder)
    return prt


# ---------------------------------------------------------------------------
# SimpleMap-style helper: build a keyed tree + per-key ValueOps, the shape
# ABCI apps return from Query(prove=true) (reference merkle.SimpleProofsFromMap)
# ---------------------------------------------------------------------------


def proofs_from_map(kvs: dict) -> tuple[bytes, dict]:
    """root hash + {key: ValueOp} for a map of key -> value, with leaves
    len-prefixed(key)||len-prefixed(sha256(value)) in sorted-key order."""
    items = sorted(kvs.items())
    leaves = [
        _encode_byte_slice(k) + _encode_byte_slice(tmhash.sum256(v))
        for k, v in items
    ]
    root, proofs = merkle.proofs_from_byte_slices(leaves)
    return root, {
        k: ValueOp(key=k, proof=pf) for (k, _), pf in zip(items, proofs)
    }
