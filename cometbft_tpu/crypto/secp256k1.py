"""secp256k1 ECDSA keys (reference: crypto/secp256k1/secp256k1.go).

Bitcoin-style addressing: RIPEMD160(SHA256(33-byte compressed pubkey)).
Signatures are 64-byte R||S with low-S normalization (the reference's
btcec serialization).  Backed by the `cryptography` library's C
implementation; no batch verification (matches the reference: secp256k1
has no BatchVerifier, crypto/batch falls back to sequential).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
)

SECP256K1_KEY_TYPE = "secp256k1"

# curve order (for low-S normalization)
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def _address(pub33: bytes) -> bytes:
    sha = hashlib.sha256(pub33).digest()
    rip = hashlib.new("ripemd160")
    rip.update(sha)
    return rip.digest()


@dataclass(frozen=True)
class Secp256k1PubKey:
    data: bytes  # 33-byte compressed SEC1 point

    type_ = SECP256K1_KEY_TYPE

    def __post_init__(self):
        if len(self.data) != 33:
            raise ValueError("secp256k1 pubkey must be 33 bytes (compressed)")

    def address(self) -> bytes:
        addr = self.__dict__.get("_addr")
        if addr is None:
            addr = _address(self.data)
            self.__dict__["_addr"] = addr
        return addr

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != 64:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if r == 0 or s == 0 or s > _N // 2:  # reject non-low-S (reference)
            return False
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256K1(), self.data
            )
            pub.verify(
                encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256())
            )
            return True
        except (InvalidSignature, ValueError):
            return False

    def bytes(self) -> bytes:
        return self.data


@dataclass(frozen=True)
class Secp256k1PrivKey:
    secret: bytes  # 32-byte scalar

    type_ = SECP256K1_KEY_TYPE

    @staticmethod
    def generate() -> "Secp256k1PrivKey":
        key = ec.generate_private_key(ec.SECP256K1())
        raw = key.private_numbers().private_value.to_bytes(32, "big")
        return Secp256k1PrivKey(raw)

    @staticmethod
    def from_secret(secret: bytes) -> "Secp256k1PrivKey":
        return Secp256k1PrivKey(secret)

    def _key(self) -> ec.EllipticCurvePrivateKey:
        return ec.derive_private_key(
            int.from_bytes(self.secret, "big"), ec.SECP256K1()
        )

    def pub_key(self) -> Secp256k1PubKey:
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        pub = self._key().public_key().public_bytes(
            Encoding.X962, PublicFormat.CompressedPoint
        )
        return Secp256k1PubKey(pub)

    def sign(self, msg: bytes) -> bytes:
        der = self._key().sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        if s > _N // 2:
            s = _N - s  # low-S normalization
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def bytes(self) -> bytes:
        return self.secret
