"""secp256k1 ECDSA keys (reference: crypto/secp256k1/secp256k1.go).

Bitcoin-style addressing: RIPEMD160(SHA256(33-byte compressed pubkey)).
Signatures are 64-byte R||S with low-S normalization (the reference's
btcec serialization).  Backed by the `cryptography` library's C
implementation; no batch verification (matches the reference: secp256k1
has no BatchVerifier, crypto/batch falls back to sequential).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac_mod
import os as _os
from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
        encode_dss_signature,
    )
except ImportError:  # no C library: pure-Python affine ECDSA below
    ec = None

SECP256K1_KEY_TYPE = "secp256k1"

# curve order (for low-S normalization)
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141

# field prime and generator for the pure-Python fallback
_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
_G = (
    0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)


def _pt_add(p1, p2):
    """Affine point addition (None is the identity)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % _P == 0:
            return None
        lam = (3 * x1 * x1) * pow(2 * y1, -1, _P) % _P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, _P) % _P
    x3 = (lam * lam - x1 - x2) % _P
    return (x3, (lam * (x1 - x3) - y1) % _P)


def _pt_mul(k: int, p):
    acc = None
    while k:
        if k & 1:
            acc = _pt_add(acc, p)
        p = _pt_add(p, p)
        k >>= 1
    return acc


def _pt_compress(p) -> bytes:
    x, y = p
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


def _pt_decompress(pub33: bytes):
    if len(pub33) != 33 or pub33[0] not in (2, 3):
        return None
    x = int.from_bytes(pub33[1:], "big")
    if x >= _P:
        return None
    y2 = (pow(x, 3, _P) + 7) % _P
    y = pow(y2, (_P + 1) // 4, _P)  # p ≡ 3 (mod 4)
    if y * y % _P != y2:
        return None
    if (y & 1) != (pub33[0] & 1):
        y = _P - y
    return (x, y)


def _rfc6979_k(secret: bytes, digest: bytes) -> int:
    """Deterministic nonce (RFC 6979, SHA-256) — no RNG to misuse."""
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = _hmac_mod.new(k, v + b"\x00" + secret + digest, hashlib.sha256).digest()
    v = _hmac_mod.new(k, v, hashlib.sha256).digest()
    k = _hmac_mod.new(k, v + b"\x01" + secret + digest, hashlib.sha256).digest()
    v = _hmac_mod.new(k, v, hashlib.sha256).digest()
    while True:
        v = _hmac_mod.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < _N:
            return cand
        k = _hmac_mod.new(k, v + b"\x00", hashlib.sha256).digest()
        v = _hmac_mod.new(k, v, hashlib.sha256).digest()


def _address(pub33: bytes) -> bytes:
    sha = hashlib.sha256(pub33).digest()
    rip = hashlib.new("ripemd160")
    rip.update(sha)
    return rip.digest()


@dataclass(frozen=True)
class Secp256k1PubKey:
    data: bytes  # 33-byte compressed SEC1 point

    type_ = SECP256K1_KEY_TYPE

    def __post_init__(self):
        if len(self.data) != 33:
            raise ValueError("secp256k1 pubkey must be 33 bytes (compressed)")

    def address(self) -> bytes:
        addr = self.__dict__.get("_addr")
        if addr is None:
            addr = _address(self.data)
            self.__dict__["_addr"] = addr
        return addr

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != 64:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if r == 0 or s == 0 or s > _N // 2:  # reject non-low-S (reference)
            return False
        if ec is None:
            return self._verify_pure(msg, r, s)
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256K1(), self.data
            )
            pub.verify(
                encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256())
            )
            return True
        except (InvalidSignature, ValueError):
            return False

    def _verify_pure(self, msg: bytes, r: int, s: int) -> bool:
        if r >= _N or s >= _N:
            return False
        q = _pt_decompress(self.data)
        if q is None:
            return False
        e = int.from_bytes(hashlib.sha256(msg).digest(), "big")
        w = pow(s, -1, _N)
        rp = _pt_add(
            _pt_mul(e * w % _N, _G), _pt_mul(r * w % _N, q)
        )
        return rp is not None and rp[0] % _N == r

    def bytes(self) -> bytes:
        return self.data


@dataclass(frozen=True)
class Secp256k1PrivKey:
    secret: bytes  # 32-byte scalar

    type_ = SECP256K1_KEY_TYPE

    @staticmethod
    def generate() -> "Secp256k1PrivKey":
        if ec is None:
            while True:
                raw = _os.urandom(32)
                if 0 < int.from_bytes(raw, "big") < _N:
                    return Secp256k1PrivKey(raw)
        key = ec.generate_private_key(ec.SECP256K1())
        raw = key.private_numbers().private_value.to_bytes(32, "big")
        return Secp256k1PrivKey(raw)

    @staticmethod
    def from_secret(secret: bytes) -> "Secp256k1PrivKey":
        return Secp256k1PrivKey(secret)

    def _key(self) -> ec.EllipticCurvePrivateKey:
        return ec.derive_private_key(
            int.from_bytes(self.secret, "big"), ec.SECP256K1()
        )

    def pub_key(self) -> Secp256k1PubKey:
        if ec is None:
            point = _pt_mul(int.from_bytes(self.secret, "big"), _G)
            return Secp256k1PubKey(_pt_compress(point))
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        pub = self._key().public_key().public_bytes(
            Encoding.X962, PublicFormat.CompressedPoint
        )
        return Secp256k1PubKey(pub)

    def sign(self, msg: bytes) -> bytes:
        if ec is None:
            return self._sign_pure(msg)
        der = self._key().sign(msg, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        if s > _N // 2:
            s = _N - s  # low-S normalization
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def _sign_pure(self, msg: bytes) -> bytes:
        d = int.from_bytes(self.secret, "big")
        digest = hashlib.sha256(msg).digest()
        e = int.from_bytes(digest, "big")
        k = _rfc6979_k(self.secret, digest)
        while True:
            r = _pt_mul(k, _G)[0] % _N
            s = pow(k, -1, _N) * (e + r * d) % _N
            if r != 0 and s != 0:
                break
            k = (k + 1) % _N or 1  # astronomically unlikely; stay total
        if s > _N // 2:
            s = _N - s  # low-S normalization
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def bytes(self) -> bytes:
        return self.secret
