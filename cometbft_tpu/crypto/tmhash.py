"""SHA-256 helpers (reference: crypto/tmhash/hash.go:22,102)."""

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    """First 20 bytes of SHA-256 — used for addresses."""
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]
