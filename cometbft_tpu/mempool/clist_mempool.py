"""CList mempool with lanes (reference: mempool/clist_mempool.go).

Transactions are validated through the app's mempool connection (CheckTx),
cached in an LRU to dedupe gossip, and stored in per-lane concurrent lists —
lanes are priority classes the app declares in its ``Info`` response
(reference: mempool/lanes.go, ``lane_priorities``/``default_lane``).  Reaping
visits lanes in priority order round-robin (high first); after a block commits,
``update`` removes committed txs and rechecks the remainder.
"""

from __future__ import annotations

import threading

from cometbft_tpu.libs import sync as libsync
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from cometbft_tpu.abci import types as at
from cometbft_tpu.config.config import MempoolConfig
from cometbft_tpu.crypto import tmhash
from cometbft_tpu.libs.clist import CElement, CList
from cometbft_tpu.txingest import stats as ingest_stats


class MempoolError(Exception):
    pass


class TxInCacheError(MempoolError):
    pass


class MempoolFullError(MempoolError):
    def __init__(self, n_txs: int, total_bytes: int):
        super().__init__(f"mempool full: {n_txs} txs, {total_bytes} bytes")


class TxTooLargeError(MempoolError):
    pass


class PreCheckError(MempoolError):
    pass


@dataclass
class MempoolTx:
    """Reference: clist_mempool.go mempoolTx."""

    tx: bytes
    height: int  # height at which validated
    gas_wanted: int = 0
    lane: str = ""
    senders: set[str] = field(default_factory=set)

    @property
    def key(self) -> bytes:
        return tmhash.sum256(self.tx)


class LRUTxCache:
    """Reference: mempool/cache.go LRUTxCache."""

    def __init__(self, size: int):
        self.size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._mtx = threading.Lock()

    def push(self, key: bytes) -> bool:
        """Returns False if already present (and refreshes recency)."""
        with self._mtx:
            if key in self._map:
                self._map.move_to_end(key)
                return False
            if len(self._map) >= self.size > 0:
                self._map.popitem(last=False)
            self._map[key] = None
            return True

    def remove(self, key: bytes) -> None:
        with self._mtx:
            self._map.pop(key, None)

    def has(self, key: bytes) -> bool:
        with self._mtx:
            return key in self._map

    def touch(self, key: bytes) -> bool:
        """True if present, refreshing recency — the dedup probe the
        ingest coalescer runs before taking a queue slot, with the same
        LRU effect a duplicate gets from ``push`` on the per-tx path."""
        with self._mtx:
            if key in self._map:
                self._map.move_to_end(key)
                return True
            return False

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()


class NopTxCache:
    def push(self, key: bytes) -> bool:
        return True

    def remove(self, key: bytes) -> None:
        pass

    def has(self, key: bytes) -> bool:
        return False

    def touch(self, key: bytes) -> bool:
        return False

    def reset(self) -> None:
        pass


DEFAULT_LANE = "default"


class CListMempool:
    """Reference: mempool/clist_mempool.go CListMempool.

    ``proxy_app`` is the mempool ABCI connection.  ``lane_info`` comes from
    the app's Info response; when absent a single default lane is used.
    """

    def __init__(
        self,
        config: MempoolConfig,
        proxy_app,
        height: int = 0,
        lane_priorities: Optional[dict[str, int]] = None,
        default_lane: str = "",
        pre_check: Optional[Callable[[bytes], Optional[str]]] = None,
        envelope_aware: bool = False,
    ):
        self.config = config
        self.proxy_app = proxy_app
        self.height = height
        self.pre_check = pre_check
        # True when the app advertises InfoResponse.envelope_sig_verified:
        # batched admission may then pre-verify signed-tx envelopes on the
        # crypto seam and reject forgeries with the app's own canonical
        # codes before any app round trip (docs/tx-ingest.md)
        self.envelope_aware = envelope_aware
        self.cache = (
            LRUTxCache(config.cache_size) if config.cache_size > 0 else NopTxCache()
        )
        if not lane_priorities:
            lane_priorities = {DEFAULT_LANE: 1}
            default_lane = DEFAULT_LANE
        if default_lane not in lane_priorities:
            raise MempoolError(f"default lane {default_lane!r} not in priorities")
        self.default_lane = default_lane
        # high priority first
        self.sorted_lanes = sorted(
            lane_priorities, key=lambda l: (-lane_priorities[l], l)
        )
        self.lanes: dict[str, CList] = {l: CList() for l in lane_priorities}
        self._tx_map: dict[bytes, CElement] = {}
        self._mtx = libsync.rlock("mempool")  # held across Update (reference Lock())
        self._total_bytes = 0
        self._notified_available = False
        self._txs_available: Optional[threading.Event] = None
        self._recheck_cursor: Optional[int] = None

    # -- introspection ----------------------------------------------------

    def size(self) -> int:
        return len(self._tx_map)

    def size_bytes(self) -> int:
        return self._total_bytes

    def is_empty(self) -> bool:
        return not self._tx_map

    def contains(self, tx_key: bytes) -> bool:
        return tx_key in self._tx_map

    def get_tx_by_hash(self, tx_hash: bytes):
        """The queued tx bytes with this hash, or None (reference:
        mempool/clist_mempool.go GetTxByHash via the unconfirmed_tx RPC,
        rpc/core/mempool.go:189-197)."""
        el = self._tx_map.get(tx_hash)
        return el.value.tx if el is not None else None

    def enable_txs_available(self) -> None:
        self._txs_available = threading.Event()

    def txs_available(self) -> Optional[threading.Event]:
        return self._txs_available

    def flush(self) -> None:
        """Remove everything (reference: Flush)."""
        with self._mtx:
            for lane in self.lanes.values():
                el = lane.front()
                while el is not None:
                    lane.remove(el)
                    el = el.next()
            self._tx_map.clear()
            self.cache.reset()
            self._total_bytes = 0

    # -- CheckTx ingress --------------------------------------------------

    @staticmethod
    def tx_key(tx: bytes) -> bytes:
        return tmhash.sum256(tx)

    def note_duplicate(self, key: bytes, sender: str) -> None:
        """Record a gossip duplicate's sender so we don't gossip back
        (reference :365) — shared by the per-tx path, batched admission
        and the coalescer's pre-queue dedup."""
        el = self._tx_map.get(key)
        if el is not None and sender:
            el.value.senders.add(sender)

    def check_tx(self, tx: bytes, sender: str = "") -> at.CheckTxResponse:
        """Validate and maybe add a tx (reference: clist_mempool.go:333).

        Synchronous here — the async pipelining of the reference's socket
        client is handled inside the ABCI client; mempool semantics (cache,
        duplicate-sender tracking, full checks) are identical.
        """
        if len(tx) > self.config.max_tx_bytes:
            ingest_stats.record_error("too_large")
            raise TxTooLargeError(
                f"tx {len(tx)}B > max {self.config.max_tx_bytes}B"
            )
        if self.pre_check is not None:
            err = self.pre_check(tx)
            if err:
                ingest_stats.record_error("pre_check")
                raise PreCheckError(err)

        key = tmhash.sum256(tx)
        if not self.cache.push(key):
            self.note_duplicate(key, sender)
            ingest_stats.record_cache(True)
            ingest_stats.record_error("duplicate")
            raise TxInCacheError()
        ingest_stats.record_cache(False)

        if (
            self.size() + 1 > self.config.size
            or self._total_bytes + len(tx) > self.config.max_txs_bytes
        ):
            self.cache.remove(key)
            ingest_stats.record_error("full")
            raise MempoolFullError(self.size(), self._total_bytes)

        res = self.proxy_app.check_tx(at.CheckTxRequest(tx=tx))
        self._handle_check_tx_response(tx, key, sender, res)
        return res

    def check_tx_batch(
        self,
        txs: Sequence[bytes],
        senders: Optional[Sequence[str]] = None,
        keys: Optional[Sequence[bytes]] = None,
    ) -> list:
        """Batched admission (docs/tx-ingest.md): run the per-tx gauntlet
        (size, pre-check, cache dedup) in request order, pre-verify
        signed-tx envelopes on the crypto seam when the app is
        envelope-aware (forgeries rejected with the app's canonical codes,
        no app round trip), then admit every survivor through ONE batched
        ``check_txs`` call.  Returns one entry per tx: the
        ``CheckTxResponse``, or the ``MempoolError`` instance the per-tx
        path would have raised.  Final mempool contents, tx order, codes
        and cache state are identical to sequential ``check_tx`` calls —
        tests/test_txingest.py pins this differentially.

        ``keys`` optionally carries precomputed ``tx_key`` hashes (the
        coalescer already hashed every tx for its pre-queue dedup probe)
        so the hot gossip path hashes each tx once, not twice."""
        from cometbft_tpu.txingest import envelope as ev

        n = len(txs)
        senders = list(senders) if senders is not None else [""] * n
        if len(senders) != n:
            raise ValueError(
                f"check_tx_batch: {len(senders)} senders for {n} txs"
            )
        if keys is not None and len(keys) != n:
            raise ValueError(
                f"check_tx_batch: {len(keys)} keys for {n} txs"
            )
        pre_keys = keys
        results: list = [None] * n
        keys: "list[Optional[bytes]]" = [None] * n
        live: "list[int]" = []
        for i, (tx, sender) in enumerate(zip(txs, senders)):
            if len(tx) > self.config.max_tx_bytes:
                ingest_stats.record_error("too_large")
                results[i] = TxTooLargeError(
                    f"tx {len(tx)}B > max {self.config.max_tx_bytes}B"
                )
                continue
            if self.pre_check is not None:
                err = self.pre_check(tx)
                if err:
                    ingest_stats.record_error("pre_check")
                    results[i] = PreCheckError(err)
                    continue
            key = pre_keys[i] if pre_keys is not None else tmhash.sum256(tx)
            if not self.cache.push(key):
                # also dedups duplicates WITHIN the batch: the first
                # occurrence owns the cache slot, later ones land here
                # (the apply loop re-probes — see below — in case the
                # first occurrence is rejected and releases the slot)
                self.note_duplicate(key, sender)
                ingest_stats.record_cache(True)
                ingest_stats.record_error("duplicate")
                keys[i] = key
                results[i] = TxInCacheError()
                continue
            ingest_stats.record_cache(False)
            keys[i] = key
            live.append(i)

        if self.envelope_aware and live:
            # one bulk-class pass through the verify seam for the whole
            # burst; a shed inside verify_envelopes degrades to per-item
            # sync host verify — never a dropped tx verdict
            envs: "list" = [None] * n
            for i in live:
                if ev.is_envelope(txs[i]):
                    try:
                        envs[i] = ev.decode(txs[i])
                    except ev.EnvelopeError as e:
                        results[i] = ev.reject_bad_envelope(str(e))
            verdicts = ev.verify_envelopes(envs)
            n_sigs = sum(1 for e in envs if e is not None)
            ingest_stats.record_sig_precheck(n_sigs)
            for i in live:
                if envs[i] is not None and not verdicts[i]:
                    results[i] = ev.reject_bad_signature()
            live = [i for i in live if results[i] is None]

        if live:
            reqs = [at.CheckTxRequest(tx=txs[i]) for i in live]
            resps = self._app_check_txs(reqs)
            ingest_stats.record_app_batch(len(reqs))
            for i, res in zip(live, resps):
                results[i] = res

        # apply in request order: the full check and the add/cache
        # bookkeeping see exactly the mempool state sequential per-tx
        # admission would have seen
        for i in range(n):
            res = results[i]
            if isinstance(res, TxInCacheError):
                # the dedup probe ran before any verdict existed; if the
                # occurrence that owned the cache slot was since rejected
                # (rejection releases the slot unless
                # keep_invalid_txs_in_cache), sequential admission would
                # have re-checked this tx — do that now, per-tx
                if not self.cache.push(keys[i]):
                    continue  # genuine duplicate, error stands
                res = self.proxy_app.check_tx(at.CheckTxRequest(tx=txs[i]))
                results[i] = res
            if not isinstance(res, at.CheckTxResponse):
                continue  # admission error; cache handled above
            if (
                self.size() + 1 > self.config.size
                or self._total_bytes + len(txs[i]) > self.config.max_txs_bytes
            ):
                self.cache.remove(keys[i])
                ingest_stats.record_error("full")
                results[i] = MempoolFullError(self.size(), self._total_bytes)
                continue
            self._handle_check_tx_response(txs[i], keys[i], senders[i], res)
        return results

    def _app_check_txs(
        self, reqs: "list[at.CheckTxRequest]"
    ) -> "list[at.CheckTxResponse]":
        """One batched round trip when the proxy supports it (all
        ``abci.client.Client``s do), else the per-tx loop."""
        ck = getattr(self.proxy_app, "check_txs", None)
        if ck is None:
            return [self.proxy_app.check_tx(r) for r in reqs]
        return ck(reqs)

    def _handle_check_tx_response(
        self, tx: bytes, key: bytes, sender: str, res: at.CheckTxResponse
    ) -> None:
        """Reference: clist_mempool.go:393 handleCheckTxResponse."""
        if not res.ok:
            ingest_stats.record_reject(res.code)
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(key)
            return
        with self._mtx:
            if key in self._tx_map:
                return
            lane = res.lane_id or self.default_lane
            if lane not in self.lanes:
                lane = self.default_lane
            mtx = MempoolTx(
                tx=tx, height=self.height, gas_wanted=res.gas_wanted, lane=lane
            )
            if sender:
                mtx.senders.add(sender)
            el = self.lanes[lane].push_back(mtx)
            self._tx_map[key] = el
            self._total_bytes += len(tx)
        ingest_stats.record_admitted()
        self._notify_txs_available()

    def _notify_txs_available(self) -> None:
        if self._txs_available is not None and not self._notified_available:
            self._notified_available = True
            self._txs_available.set()

    # -- iteration / reaping ----------------------------------------------

    def _iter_lane_elems(self):
        """Round-robin lanes in priority order, one tx per lane per pass
        (reference: mempool/iterators.go BlockingIterator ordering)."""
        cursors = {l: self.lanes[l].front() for l in self.sorted_lanes}
        while True:
            progressed = False
            for lane in self.sorted_lanes:
                el = cursors[lane]
                while el is not None and el.removed:
                    el = el.next()
                if el is not None:
                    cursors[lane] = el.next()
                    progressed = True
                    yield el
            if not progressed:
                return

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        """Reference: clist_mempool.go:676 ReapMaxBytesMaxGas."""
        with self._mtx:
            txs: list[bytes] = []
            total_bytes = 0
            total_gas = 0
            for el in self._iter_lane_elems():
                mtx: MempoolTx = el.value
                new_bytes = total_bytes + len(mtx.tx)
                if max_bytes > -1 and new_bytes > max_bytes:
                    break
                new_gas = total_gas + mtx.gas_wanted
                if max_gas > -1 and new_gas > max_gas:
                    break
                total_bytes, total_gas = new_bytes, new_gas
                txs.append(mtx.tx)
            return txs

    def reap_max_txs(self, n: int) -> list[bytes]:
        with self._mtx:
            out = []
            for el in self._iter_lane_elems():
                if n > -1 and len(out) >= n:
                    break
                out.append(el.value.tx)
            return out

    # -- post-commit update -----------------------------------------------

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def update(
        self,
        height: int,
        txs: Sequence[bytes],
        tx_results: Sequence[at.ExecTxResult],
    ) -> None:
        """Remove committed txs; recheck the rest (reference: :753 Update).
        Caller must hold the lock (consensus does, via blockExec.Commit)."""
        self.height = height
        self._notified_available = False
        if self._txs_available is not None:
            self._txs_available.clear()

        for tx, res in zip(txs, tx_results):
            key = tmhash.sum256(tx)
            if res.ok:
                self.cache.push(key)  # committed: keep in cache forever-ish
            elif not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(key)
            el = self._tx_map.pop(key, None)
            if el is not None:
                self.lanes[el.value.lane].remove(el)
                self._total_bytes -= len(tx)

        if self._tx_map and self.config.recheck:
            self._recheck_txs()
        if self._tx_map:
            self._notify_txs_available()

    def _recheck_txs(self) -> None:
        """Re-run CheckTx on all remaining txs (reference: :828 recheckTxs).

        With tx ingestion enabled the whole remaining mempool rides ONE
        batched ``check_txs`` round trip — and, behind an envelope-aware
        app, one fused signature pass (all cache hits in the common case)
        — instead of the serial per-tx loop.  ``COMETBFT_TPU_TXINGEST=0``
        restores the loop; verdicts are identical either way because the
        batch is semantically a sequence of independent checks."""
        from cometbft_tpu.txingest.coalescer import ingest_enabled

        items = list(self._tx_map.items())
        reqs = [
            at.CheckTxRequest(tx=el.value.tx, type_=at.CHECK_TX_TYPE_RECHECK)
            for _, el in items
        ]
        if ingest_enabled() and len(items) > 1:
            resps = self._app_check_txs(reqs)
            ingest_stats.record_app_batch(len(reqs))
            ingest_stats.record_recheck(len(reqs))
        else:
            resps = [self.proxy_app.check_tx(r) for r in reqs]
        for (key, el), res in zip(items, resps):
            mtx: MempoolTx = el.value
            if not res.ok:
                ingest_stats.record_reject(res.code)
                self._tx_map.pop(key, None)
                self.lanes[mtx.lane].remove(el)
                self._total_bytes -= len(mtx.tx)
                if not self.config.keep_invalid_txs_in_cache:
                    self.cache.remove(key)


class NopMempool:
    """Reference: mempool/nop_mempool.go — app manages txs itself."""

    def __init__(self):
        self._txs_available = None

    def check_tx(self, tx: bytes, sender: str = ""):
        raise MempoolError("tx rejected: nop mempool does not accept txs")

    def size(self) -> int:
        return 0

    def size_bytes(self) -> int:
        return 0

    def is_empty(self) -> bool:
        return True

    def contains(self, tx_key: bytes) -> bool:
        return False

    def get_tx_by_hash(self, tx_hash: bytes):
        return None

    def enable_txs_available(self) -> None:
        pass

    def txs_available(self):
        return None

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        return []

    def reap_max_txs(self, n: int) -> list[bytes]:
        return []

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def update(self, height, txs, tx_results) -> None:
        pass

    def flush(self) -> None:
        pass
