"""Mempool gossip reactor (reference: mempool/reactor.go).

Channel 0x30 (reference: mempool/mempool.go:14 MempoolChannel).  One
broadcast thread per peer walks the mempool's lanes and sends every tx the
peer hasn't already sent us (reference: reactor.go:213
broadcastTxRoutine's send-loop with the senders check).
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.abci import types as at
from cometbft_tpu.libs import log as liblog
from cometbft_tpu.mempool.clist_mempool import (
    MempoolError,
    MempoolFullError,
    PreCheckError,
    TxInCacheError,
    TxTooLargeError,
)
from cometbft_tpu.p2p.conn import ChannelDescriptor
from cometbft_tpu.p2p.reactor import Reactor

MEMPOOL_CHANNEL = 0x30
_BROADCAST_SLEEP = 0.02


class MempoolReactor(Reactor):
    """Reference: mempool/reactor.go Reactor.

    ``ingest`` (a ``txingest.IngestCoalescer``) routes incoming gossip
    through batched admission when active; without it — or with
    ``COMETBFT_TPU_TXINGEST=0`` — every tx takes the per-tx ``check_tx``
    path exactly as before.  Either way the reactor now counts (and logs,
    at debug) tx-cache dedup hits and CheckTx rejections per peer instead
    of silently swallowing them: ``peer_ingest_stats`` feeds sim
    assertions and the ``cometbft_mempool_*`` metrics."""

    def __init__(self, config, mempool, logger=None, ingest=None):
        super().__init__("MempoolReactor")
        self.config = config
        self.mempool = mempool
        self.ingest = ingest
        if ingest is not None and ingest.on_result is None:
            ingest.on_result = self._note_flush_result
        self.logger = logger or liblog.nop_logger()
        self._peer_routines: dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self._peer_stats: dict[str, dict[str, int]] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                MEMPOOL_CHANNEL,
                priority=5,
                send_queue_capacity=100,
                recv_message_capacity=self.config.max_tx_bytes + 64
                if hasattr(self.config, "max_tx_bytes")
                else 1024 * 1024,
            )
        ]

    def add_peer(self, peer) -> None:
        if not getattr(self.config, "broadcast", True):
            return
        stop = threading.Event()
        with self._lock:
            self._peer_routines[peer.id] = stop
        threading.Thread(
            target=self._broadcast_tx_routine,
            args=(peer, stop),
            name="mempool-broadcast",
            daemon=True,
        ).start()

    def remove_peer(self, peer, reason) -> None:
        with self._lock:
            stop = self._peer_routines.pop(peer.id, None)
        if stop is not None:
            stop.set()

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        """An incoming tx: CheckTx with the peer recorded as sender —
        batched through the ingest coalescer when active.  Dupes / full /
        failed pre-check stay non-fatal, but are now counted per peer."""
        try:
            if self.ingest is not None:
                res = self.ingest.submit(msg_bytes, sender=peer.id)
            else:
                res = self.mempool.check_tx(msg_bytes, sender=peer.id)
        except MempoolError as e:
            self._note_sync_error(peer.id, e)
            return
        if res is not None:  # None = queued; verdict arrives at flush time
            self._note_response(peer.id, res)

    # -- per-peer ingest accounting ---------------------------------------

    def peer_ingest_stats(self) -> "dict[str, dict[str, int]]":
        with self._lock:
            return {p: dict(s) for p, s in self._peer_stats.items()}

    def _bump(self, peer_id: str, kind: str) -> None:
        with self._lock:
            stats = self._peer_stats.setdefault(
                peer_id, {"accepted": 0, "dedup": 0, "rejected": 0, "error": 0}
            )
            stats[kind] += 1

    def _note_response(self, peer_id: str, res: at.CheckTxResponse) -> None:
        if res.ok:
            self._bump(peer_id, "accepted")
        else:
            self._bump(peer_id, "rejected")
            self.logger.debug(
                "tx rejected by CheckTx",
                peer=peer_id,
                code=res.code,
                codespace=res.codespace,
                log=res.log,
            )

    def _note_sync_error(self, peer_id: str, err: MempoolError) -> None:
        if isinstance(err, TxInCacheError):
            self._bump(peer_id, "dedup")
            self.logger.debug("tx dedup (cache hit)", peer=peer_id)
        else:
            self._bump(peer_id, "error")
            kind = {
                MempoolFullError: "mempool full",
                TxTooLargeError: "tx too large",
                PreCheckError: "pre-check failed",
            }.get(type(err), "mempool error")
            self.logger.debug("tx not admitted", peer=peer_id, reason=kind)

    def _note_flush_result(self, peer_id: str, res) -> None:
        """Flush-time outcome from the coalescer (response or the
        MempoolError the per-tx path would have raised)."""
        if isinstance(res, at.CheckTxResponse):
            self._note_response(peer_id, res)
        elif isinstance(res, MempoolError):
            self._note_sync_error(peer_id, res)

    def _broadcast_tx_routine(self, peer, stop: threading.Event) -> None:
        """Reference: reactor.go:213 broadcastTxRoutine — iterate the lanes
        forever, skipping txs the peer sent us."""
        sent: set[bytes] = set()
        while self.is_running and peer.is_running and not stop.is_set():
            advanced = False
            with self.mempool._mtx:
                entries = [
                    (el.value.key, el.value.tx, set(el.value.senders))
                    for el in self.mempool._iter_lane_elems()
                ]
            live = set()
            for key, tx, senders in entries:
                live.add(key)
                if key in sent or peer.id in senders:
                    continue
                if peer.try_send(MEMPOOL_CHANNEL, tx):
                    sent.add(key)
                    advanced = True
            # drop bookkeeping for txs no longer in the pool
            if len(sent) > 10000:
                sent &= live
            if not advanced:
                time.sleep(_BROADCAST_SLEEP)
