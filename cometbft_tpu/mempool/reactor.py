"""Mempool gossip reactor (reference: mempool/reactor.go).

Channel 0x30 (reference: mempool/mempool.go:14 MempoolChannel).  One
broadcast thread per peer walks the mempool's lanes and sends every tx the
peer hasn't already sent us (reference: reactor.go:213
broadcastTxRoutine's send-loop with the senders check).
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.libs import log as liblog
from cometbft_tpu.mempool.clist_mempool import MempoolError
from cometbft_tpu.p2p.conn import ChannelDescriptor
from cometbft_tpu.p2p.reactor import Reactor

MEMPOOL_CHANNEL = 0x30
_BROADCAST_SLEEP = 0.02


class MempoolReactor(Reactor):
    """Reference: mempool/reactor.go Reactor."""

    def __init__(self, config, mempool, logger=None):
        super().__init__("MempoolReactor")
        self.config = config
        self.mempool = mempool
        self.logger = logger or liblog.nop_logger()
        self._peer_routines: dict[str, threading.Event] = {}
        self._lock = threading.Lock()

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                MEMPOOL_CHANNEL,
                priority=5,
                send_queue_capacity=100,
                recv_message_capacity=self.config.max_tx_bytes + 64
                if hasattr(self.config, "max_tx_bytes")
                else 1024 * 1024,
            )
        ]

    def add_peer(self, peer) -> None:
        if not getattr(self.config, "broadcast", True):
            return
        stop = threading.Event()
        with self._lock:
            self._peer_routines[peer.id] = stop
        threading.Thread(
            target=self._broadcast_tx_routine,
            args=(peer, stop),
            name="mempool-broadcast",
            daemon=True,
        ).start()

    def remove_peer(self, peer, reason) -> None:
        with self._lock:
            stop = self._peer_routines.pop(peer.id, None)
        if stop is not None:
            stop.set()

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        """An incoming tx: CheckTx with the peer recorded as sender."""
        try:
            self.mempool.check_tx(msg_bytes, sender=peer.id)
        except MempoolError:
            pass  # dupes / full / failed pre-check are non-fatal

    def _broadcast_tx_routine(self, peer, stop: threading.Event) -> None:
        """Reference: reactor.go:213 broadcastTxRoutine — iterate the lanes
        forever, skipping txs the peer sent us."""
        sent: set[bytes] = set()
        while self.is_running and peer.is_running and not stop.is_set():
            advanced = False
            with self.mempool._mtx:
                entries = [
                    (el.value.key, el.value.tx, set(el.value.senders))
                    for el in self.mempool._iter_lane_elems()
                ]
            live = set()
            for key, tx, senders in entries:
                live.add(key)
                if key in sent or peer.id in senders:
                    continue
                if peer.try_send(MEMPOOL_CHANNEL, tx):
                    sent.add(key)
                    advanced = True
            # drop bookkeeping for txs no longer in the pool
            if len(sent) > 10000:
                sent &= live
            if not advanced:
                time.sleep(_BROADCAST_SLEEP)
