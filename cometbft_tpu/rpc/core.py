"""JSON-RPC core routes (reference: rpc/core/routes.go:12-57).

``Environment`` holds handles into the running node; each public method is
one RPC route returning JSON-serializable dicts in the reference's wire
shapes (hashes hex, bytes base64, ints as strings where the reference uses
int64-as-string JSON).
"""

from __future__ import annotations

import base64
import time
from typing import Optional

from cometbft_tpu.abci import types as at
from cometbft_tpu.crypto import tmhash
from cometbft_tpu.libs import storage_stats
from cometbft_tpu.libs.pubsub import Query
from cometbft_tpu.mempool.clist_mempool import MempoolError, TxInCacheError
from cometbft_tpu.state.execution import fbr_from_json
from cometbft_tpu.types import events as tev
from cometbft_tpu.version import CMT_SEMVER, BLOCK_PROTOCOL, P2P_PROTOCOL


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _hex(b: bytes) -> str:
    return b.hex().upper()


def _ts_json(ts) -> str:
    t = time.gmtime(ts.seconds)
    return time.strftime("%Y-%m-%dT%H:%M:%S", t) + f".{ts.nanos:09d}Z"


def _block_id_json(bid) -> dict:
    return {
        "hash": _hex(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": _hex(bid.part_set_header.hash),
        },
    }


def _header_json(h) -> dict:
    return {
        "version": {"block": str(h.version.block), "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": _ts_json(h.time),
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": _hex(h.last_commit_hash),
        "data_hash": _hex(h.data_hash),
        "validators_hash": _hex(h.validators_hash),
        "next_validators_hash": _hex(h.next_validators_hash),
        "consensus_hash": _hex(h.consensus_hash),
        "app_hash": _hex(h.app_hash),
        "last_results_hash": _hex(h.last_results_hash),
        "evidence_hash": _hex(h.evidence_hash),
        "proposer_address": _hex(h.proposer_address),
    }


def _commit_json(c) -> dict:
    return {
        "height": str(c.height),
        "round": c.round_,
        "block_id": _block_id_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": cs.block_id_flag,
                "validator_address": _hex(cs.validator_address),
                "timestamp": _ts_json(cs.timestamp),
                "signature": _b64(cs.signature) if cs.signature else None,
            }
            for cs in c.signatures
        ],
    }


def _evidence_json(ev) -> dict:
    from cometbft_tpu.types import codec as _codec

    return {
        "type": ev.TYPE,
        "height": str(ev.height),
        "time": _ts_json(ev.time),
        "total_voting_power": str(ev.total_voting_power),
        "bytes": _b64(_codec.encode_evidence(ev)),
    }


class QuotedString(str):
    """A URI argument that arrived double-quoted: a raw string literal,
    never hex/base64 (reference: rpc/jsonrpc/server/uri.go)."""


def _bytes_arg(v) -> bytes:
    """Decode a bytes-typed RPC argument with the reference's conventions
    (rpc/jsonrpc/server/uri.go): URI quoted string -> raw bytes of the
    string, 0x/hex -> hex decode, otherwise base64 (JSON-RPC body form)."""
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    if v is None or v == "":
        return b""
    if isinstance(v, QuotedString):
        return str(v).encode()
    if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
        return v[1:-1].encode()
    h = v[2:] if v.startswith("0x") else v
    try:
        return bytes.fromhex(h)
    except ValueError:
        pass
    return base64.b64decode(v)


def _block_json(block) -> dict:
    return {
        "header": _header_json(block.header),
        "data": {"txs": [_b64(tx) for tx in block.data.txs]},
        "evidence": {"evidence": [_evidence_json(ev) for ev in block.evidence]},
        "last_commit": _commit_json(block.last_commit),
    }


def _tx_result_json(r: at.ExecTxResult) -> dict:
    return {
        "code": r.code,
        "data": _b64(r.data),
        "log": r.log,
        "info": r.info,
        "gas_wanted": str(r.gas_wanted),
        "gas_used": str(r.gas_used),
        "events": [
            {
                "type": e.type_,
                "attributes": [
                    {"key": a.key, "value": a.value, "index": a.index}
                    for a in e.attributes
                ],
            }
            for e in r.events
        ],
        "codespace": r.codespace,
    }


class Environment:
    """Reference: rpc/core/env.go Environment."""

    def __init__(self, node):
        self.node = node

    # -- info routes -------------------------------------------------------

    def health(self) -> dict:
        # A fail-stop storage fatal means a persistent surface halted this
        # node — liveness probes must see it (the HTTP server maps this
        # error to 503 on the health route).
        totals = storage_stats.snapshot()["totals"]
        if totals["fatal"]:
            raise RPCError(
                -32000,
                "node unhealthy: fail-stop storage fault",
                data=f"fatals={totals['fatals']}",
            )
        return {}

    def status(self) -> dict:
        node = self.node
        height = node.block_store.height()
        meta = node.block_store.load_block_meta(height) if height else None
        pv_addr = node.priv_validator.pub_key().address()
        state = node.consensus.state
        found = state.validators.get_by_address(pv_addr)
        val_info = {
            "address": _hex(pv_addr),
            "pub_key": {
                "type": "tendermint/PubKeyEd25519",
                "value": _b64(node.priv_validator.pub_key().bytes()),
            },
            "voting_power": str(found[1].voting_power if found else 0),
        }
        return {
            "node_info": {
                "id": node.node_key.node_id,
                "listen_addr": node.config.p2p.laddr,
                "network": node.genesis_doc.chain_id,
                "version": CMT_SEMVER,
                "protocol_version": {
                    "p2p": str(P2P_PROTOCOL),
                    "block": str(BLOCK_PROTOCOL),
                },
                "moniker": node.config.base.moniker,
            },
            "sync_info": {
                "latest_block_hash": _hex(meta.block_id.hash) if meta else "",
                "latest_app_hash": _hex(state.app_hash),
                "latest_block_height": str(height),
                "latest_block_time": _ts_json(meta.header.time)
                if meta
                else _ts_json(node.genesis_doc.genesis_time),
                "earliest_block_height": str(node.block_store.base()),
                "catching_up": False,
            },
            "validator_info": val_info,
        }

    def net_info(self) -> dict:
        sw = getattr(self.node, "switch", None)
        peers = []
        if sw is not None:
            for p in sw.peers_list():
                peers.append(
                    {
                        "node_info": {"id": p.node_id()},
                        "is_outbound": p.is_outbound,
                        "remote_ip": p.remote_ip(),
                    }
                )
        return {
            "listening": sw is not None,
            "listeners": [self.node.config.p2p.laddr],
            "n_peers": str(len(peers)),
            "peers": peers,
        }

    def genesis(self) -> dict:
        import json as _json

        return {"genesis": _json.loads(self.node.genesis_doc.to_json())}

    def genesis_chunked(self, chunk: int = 0) -> dict:
        data = self.node.genesis_doc.to_json().encode()
        size = 16 * 1024 * 1024
        chunks = [data[i : i + size] for i in range(0, len(data), size)] or [b""]
        if not 0 <= chunk < len(chunks):
            raise RPCError(-32603, f"chunk {chunk} out of range [0,{len(chunks)})")
        return {
            "chunk": str(chunk),
            "total": str(len(chunks)),
            "data": _b64(chunks[chunk]),
        }

    # -- block routes ------------------------------------------------------

    def _height_or_latest(self, height: Optional[int]) -> int:
        latest = self.node.block_store.height()
        if height is None or height <= 0:
            return latest
        if height < self.node.block_store.base() or height > latest:
            raise RPCError(-32603, f"height {height} not available")
        return height

    def block(self, height: Optional[int] = None) -> dict:
        h = self._height_or_latest(height)
        block = self.node.block_store.load_block(h)
        meta = self.node.block_store.load_block_meta(h)
        if block is None:
            raise RPCError(-32603, f"block {h} not found")
        return {
            "block_id": _block_id_json(meta.block_id),
            "block": _block_json(block),
        }

    def block_by_hash(self, hash_: str) -> dict:
        raw = _bytes_arg(hash_)
        block = self.node.block_store.load_block_by_hash(raw)
        if block is None:
            raise RPCError(-32603, "block not found")
        return self.block(block.header.height)

    def header(self, height: Optional[int] = None) -> dict:
        h = self._height_or_latest(height)
        meta = self.node.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"header {h} not found")
        return {"header": _header_json(meta.header)}

    def header_by_hash(self, hash_: str) -> dict:
        """Reference: rpc/core/blocks.go HeaderByHash:106-117 — an unknown
        hash returns a null header, not an error."""
        raw = _bytes_arg(hash_)
        meta = self.node.block_store.load_block_meta_by_hash(raw)
        if meta is None:
            return {"header": None}
        return {"header": _header_json(meta.header)}

    def commit(self, height: Optional[int] = None) -> dict:
        h = self._height_or_latest(height)
        meta = self.node.block_store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"block {h} not found")
        # The canonical commit for h is stored when block h+1 is saved; for
        # the head block fall back to the seen commit (reference:
        # rpc/core/blocks.go Commit — canonical=false in that case).
        commit = self.node.block_store.load_block_commit(h)
        canonical = commit is not None
        if commit is None:
            commit = self.node.block_store.load_seen_commit(h)
        if commit is None:
            raise RPCError(-32603, f"commit {h} not found")
        return {
            "signed_header": {
                "header": _header_json(meta.header),
                "commit": _commit_json(commit),
            },
            "canonical": canonical,
        }

    def block_results(self, height: Optional[int] = None) -> dict:
        h = self._height_or_latest(height)
        raw = self.node.state_store.load_finalize_block_response(h)
        if raw is None:
            raise RPCError(-32603, f"no results for height {h}")
        res = fbr_from_json(raw)
        return {
            "height": str(h),
            "txs_results": [_tx_result_json(r) for r in res.tx_results],
            "finalize_block_events": [
                {"type": e.type_, "attributes": [
                    {"key": a.key, "value": a.value, "index": a.index}
                    for a in e.attributes]}
                for e in res.events
            ],
            "validator_updates": [
                {"pub_key_type": v.pub_key_type, "power": str(v.power)}
                for v in res.validator_updates
            ],
            "app_hash": _hex(res.app_hash),
        }

    def blockchain(self, min_height: int = 0, max_height: int = 0) -> dict:
        store = self.node.block_store
        latest = store.height()
        if max_height <= 0:
            max_height = latest
        max_height = min(max_height, latest)
        if min_height <= 0:
            min_height = max(1, max_height - 19)
        min_height = max(min_height, store.base())
        metas = []
        for h in range(max_height, min_height - 1, -1):
            m = store.load_block_meta(h)
            if m is not None:
                metas.append(
                    {
                        "block_id": _block_id_json(m.block_id),
                        "block_size": str(m.block_size),
                        "header": _header_json(m.header),
                        "num_txs": str(m.num_txs),
                    }
                )
        return {"last_height": str(latest), "block_metas": metas}

    def validators(
        self,
        height: Optional[int] = None,
        page: int = 1,
        per_page: int = 30,
    ) -> dict:
        h = self._height_or_latest(height)
        vals = self.node.state_store.load_validators(h)
        if vals is None:
            vals = self.node.consensus.state.validators
        items = [
            {
                "address": _hex(v.address),
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": _b64(v.pub_key.bytes()),
                },
                "voting_power": str(v.voting_power),
                "proposer_priority": str(v.proposer_priority),
            }
            for v in vals.validators
        ]
        per_page = max(1, min(per_page, 100))
        start = (max(page, 1) - 1) * per_page
        return {
            "block_height": str(h),
            "validators": items[start : start + per_page],
            "count": str(len(items[start : start + per_page])),
            "total": str(len(items)),
        }

    def consensus_params(self, height: Optional[int] = None) -> dict:
        from cometbft_tpu.state.state import _params_to_json

        h = self._height_or_latest(height)
        params = self.node.state_store.load_consensus_params(h)
        if params is None:
            params = self.node.consensus.state.consensus_params
        return {
            "block_height": str(h),
            "consensus_params": _params_to_json(params),
        }

    def consensus_state(self) -> dict:
        rs = self.node.consensus.get_round_state()
        return {
            "round_state": {
                "height/round/step": f"{rs.height}/{rs.round_}/{rs.step}",
                "height": str(rs.height),
                "round": rs.round_,
                "step": rs.step_name(),
                "proposal_block_hash": _hex(rs.proposal_block.hash())
                if rs.proposal_block
                else "",
                "locked_block_hash": _hex(rs.locked_block.hash())
                if rs.locked_block
                else "",
                "valid_block_hash": _hex(rs.valid_block.hash())
                if rs.valid_block
                else "",
            }
        }

    def dump_consensus_state(self) -> dict:
        rs = self.node.consensus.get_round_state()
        out = self.consensus_state()
        votes = []
        if rs.votes is not None:
            for r in range(rs.round_ + 1):
                pv = rs.votes.prevotes(r)
                pc = rs.votes.precommits(r)
                votes.append(
                    {
                        "round": r,
                        "prevotes_bit_array": str(pv.bit_array()) if pv else "",
                        "precommits_bit_array": str(pc.bit_array()) if pc else "",
                    }
                )
        out["round_state"]["height_vote_set"] = votes
        return out

    # -- ABCI routes -------------------------------------------------------

    def abci_info(self) -> dict:
        res = self.node.proxy_app.query.info()
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "app_version": str(res.app_version),
                "last_block_height": str(res.last_block_height),
                "last_block_app_hash": _b64(res.last_block_app_hash),
            }
        }

    def abci_query(
        self,
        path: str = "",
        data: str = "",
        height: int = 0,
        prove: bool = False,
    ) -> dict:
        raw = _bytes_arg(data)
        res = self.node.proxy_app.query.query(
            at.QueryRequest(data=raw, path=path, height=height, prove=prove)
        )
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "info": res.info,
                "index": str(res.index),
                "key": _b64(res.key),
                "value": _b64(res.value),
                "height": str(res.height),
                "codespace": res.codespace,
            }
        }

    # -- mempool routes ----------------------------------------------------

    def _check_tx_to_mempool(self, tx: bytes) -> at.CheckTxResponse:
        try:
            return self.node.mempool.check_tx(tx)
        except TxInCacheError:
            raise RPCError(-32603, "tx already exists in cache")
        except MempoolError as e:
            raise RPCError(-32603, f"mempool error: {e}")

    def broadcast_tx_async(self, tx: str) -> dict:
        raw = _bytes_arg(tx)
        import threading

        threading.Thread(
            target=lambda: self._try_check(raw), daemon=True
        ).start()
        return {"code": 0, "data": "", "log": "", "hash": _hex(tmhash.sum256(raw))}

    def _try_check(self, raw: bytes) -> None:
        try:
            self.node.mempool.check_tx(raw)
        except MempoolError:
            pass

    def broadcast_tx_sync(self, tx: str) -> dict:
        raw = _bytes_arg(tx)
        res = self._check_tx_to_mempool(raw)
        return {
            "code": res.code,
            "data": _b64(res.data),
            "log": res.log,
            "codespace": res.codespace,
            "hash": _hex(tmhash.sum256(raw)),
        }

    def broadcast_tx_commit(self, tx: str) -> dict:
        """CheckTx then wait for the tx to be committed (reference:
        rpc/core/mempool.go BroadcastTxCommit)."""
        raw = _bytes_arg(tx)
        tx_hash = tmhash.sum256(raw)
        q = Query.parse(
            f"{tev.EVENT_TYPE_KEY}='{tev.EVENT_TX}' AND "
            f"{tev.TX_HASH_KEY}='{_hex(tx_hash)}'"
        )
        import uuid

        subscriber = f"tx-commit-{uuid.uuid4().hex[:12]}"
        sub = self.node.event_bus.subscribe(subscriber, q, capacity=1)
        try:
            check_res = self._check_tx_to_mempool(raw)
            if not check_res.ok:
                return {
                    "check_tx": _tx_result_json(
                        at.ExecTxResult(code=check_res.code, log=check_res.log)
                    ),
                    "tx_result": _tx_result_json(at.ExecTxResult()),
                    "hash": _hex(tx_hash),
                    "height": "0",
                }
            timeout = self.node.config.rpc.timeout_broadcast_tx_commit_ms / 1000
            msg = sub.next(timeout=timeout)
            if msg is None:
                raise RPCError(-32603, "timed out waiting for tx to be included")
            ev: tev.EventDataTx = msg.data
            return {
                "check_tx": _tx_result_json(
                    at.ExecTxResult(code=check_res.code, log=check_res.log)
                ),
                "tx_result": _tx_result_json(ev.result),
                "hash": _hex(tx_hash),
                "height": str(ev.height),
            }
        finally:
            self.node.event_bus.unsubscribe_all(subscriber)

    def unconfirmed_txs(self, limit: int = 30) -> dict:
        txs = self.node.mempool.reap_max_txs(max(1, min(limit, 100)))
        return {
            "n_txs": str(len(txs)),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.size_bytes()),
            "txs": [_b64(tx) for tx in txs],
        }

    def num_unconfirmed_txs(self) -> dict:
        return {
            "n_txs": str(self.node.mempool.size()),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.size_bytes()),
        }

    def unconfirmed_tx(self, hash_: str) -> dict:
        """A single queued tx by hash (reference: rpc/core/mempool.go
        UnconfirmedTx:189-197 — error only on an empty hash; an unknown
        hash returns a null tx)."""
        raw = _bytes_arg(hash_)
        if not raw:
            raise RPCError(-32602, "transaction hash cannot be empty")
        tx = self.node.mempool.get_tx_by_hash(raw)
        return {"tx": _b64(tx) if tx is not None else None}

    # -- unsafe routes (served only when config rpc.unsafe is set; the
    #    reference adds these via AddUnsafeRoutes, routes.go:59-64) -------

    @staticmethod
    def _addr_list(value, what: str) -> list:
        """Normalize an address-list param (JSON array, or a
        comma-separated string from the URI form) and validate every
        address up front — the reference returns ErrInvalidPeerAddr
        rather than dialing a partial list (rpc/core/net.go:50-86)."""
        from cometbft_tpu.p2p.node_info import NetAddress

        if isinstance(value, str):
            value = [a for a in value.split(",") if a]
        if not isinstance(value, list) or not value:
            raise RPCError(-32602, f"no {what} provided")
        for a in value:
            if not isinstance(a, str):
                raise RPCError(-32602, f"{what} must be strings: {a!r}")
            try:
                NetAddress.parse(a)
            except Exception as e:  # noqa: BLE001
                raise RPCError(-32602, f"invalid {what} address {a!r}: {e}")
        return value

    def dial_seeds(self, seeds=None) -> dict:
        """Reference: rpc/core/net.go UnsafeDialSeeds:50-59."""
        addrs = self._addr_list(seeds, "seeds")
        sw = getattr(self.node, "switch", None)
        if sw is None:
            raise RPCError(-32603, "p2p switch unavailable")
        sw.dial_peers_async(addrs, persistent=False)
        return {"log": "Dialing seeds in progress. See /net_info for details"}

    def dial_peers(
        self,
        peers=None,
        persistent: bool = False,
        unconditional: bool = False,
        private: bool = False,
    ) -> dict:
        """Reference: rpc/core/net.go UnsafeDialPeers:61-86 (the
        unconditional/private markers are accepted for wire parity; this
        switch tracks persistence only)."""
        addrs = self._addr_list(peers, "peers")
        sw = getattr(self.node, "switch", None)
        if sw is None:
            raise RPCError(-32603, "p2p switch unavailable")
        sw.dial_peers_async(addrs, persistent=bool(persistent))
        return {"log": "Dialing peers in progress. See /net_info for details"}

    def unsafe_flush_mempool(self) -> dict:
        """Reference: rpc/core/dev.go UnsafeFlushMempool:8-12."""
        self.node.mempool.flush()
        return {}

    def check_tx(self, tx: str) -> dict:
        raw = _bytes_arg(tx)
        res = self.node.proxy_app.mempool.check_tx(at.CheckTxRequest(tx=raw))
        return {"code": res.code, "log": res.log, "gas_wanted": str(res.gas_wanted)}

    # -- tx lookup (via indexer when present) ------------------------------

    def _tx_loader(self, height: int):
        """Block-store tx loader for the proof plane (None = unknown
        height — pruned or not yet committed)."""
        blk = self.node.block_store.load_block(int(height))
        if blk is None:
            return None
        return list(blk.data.txs)

    def _tx_proof_json(self, result) -> Optional[dict]:
        """ResultTx.Proof JSON (reference: rpc/core/tx.go Tx +
        types.TxProof): the inclusion proof of ``result.tx`` against the
        committed block's ``data_hash``.  Coalesced through the proof
        server when active; serial otherwise — byte-identical."""
        from cometbft_tpu import proofserve

        got = proofserve.prove_tx(
            self._tx_loader, result.height, result.index
        )
        if got is None:
            return None
        root, proof = got
        return {
            "root_hash": _hex(root),
            "data": _b64(result.tx),
            "proof": {
                "total": str(proof.total),
                "index": str(proof.index),
                "leaf_hash": _b64(proof.leaf_hash),
                "aunts": [_b64(a) for a in proof.aunts],
            },
        }

    def tx(self, hash_: str, prove: bool = False) -> dict:
        indexer = getattr(self.node, "tx_indexer", None)
        if indexer is None:
            raise RPCError(-32603, "transaction indexing is disabled")
        raw_hash = _bytes_arg(hash_)
        result = indexer.get(raw_hash)
        if result is None:
            raise RPCError(-32603, f"tx {hash_} not found")
        doc = result.to_json()
        if prove:
            doc["proof"] = self._tx_proof_json(result)
        return doc

    def tx_search(
        self,
        query: str,
        prove: bool = False,
        page: int = 1,
        per_page: int = 30,
        order_by: str = "asc",
    ) -> dict:
        indexer = getattr(self.node, "tx_indexer", None)
        if indexer is None:
            raise RPCError(-32603, "transaction indexing is disabled")
        results = indexer.search(Query.parse(query))
        if order_by == "desc":
            results = list(reversed(results))
        per_page = max(1, min(per_page, 100))
        start = (max(page, 1) - 1) * per_page
        window = results[start : start + per_page]
        txs = []
        for r in window:
            doc = r.to_json()
            if prove:
                doc["proof"] = self._tx_proof_json(r)
            txs.append(doc)
        return {
            "txs": txs,
            "total_count": str(len(results)),
        }

    def block_search(
        self, query: str, page: int = 1, per_page: int = 30, order_by: str = "asc"
    ) -> dict:
        indexer = getattr(self.node, "block_indexer", None)
        if indexer is None:
            raise RPCError(-32603, "block indexing is disabled")
        heights = indexer.search(Query.parse(query))
        if order_by == "desc":
            heights = list(reversed(heights))
        per_page = max(1, min(per_page, 100))
        start = (max(page, 1) - 1) * per_page
        out = []
        for h in heights[start : start + per_page]:
            out.append(self.block(h))
        return {"blocks": out, "total_count": str(len(heights))}

    # -- observability (docs/observability.md) -----------------------------

    def debug_verify_trace(self, spans: int = 256, rounds: int = 8) -> dict:
        """One JSON document snapshotting the verify pipeline: flight-
        recorder ring tail + per-stage latency summary + the last-K merged
        consensus-round timelines (per-step p50/p99, quorum-arrival times,
        commit-to-proposal trace linkage) + health (breaker states,
        signature-cache hit rates, scheduler queue, warm-boot progress).
        Served as ``/debug/verify_trace`` (GET) and the
        ``debug_verify_trace`` JSON-RPC method; the ``cometbft-tpu
        trace`` CLI renders it.  Every read is jax-free by design — this
        endpoint must work exactly when the node is sickest."""
        from cometbft_tpu.libs import tracing

        doc = tracing.trace_document(
            max_spans=max(0, min(int(spans), 4096)),
            rounds=max(0, min(int(rounds), 256)),
        )
        node = self.node
        ctx: dict = {}
        try:
            ctx["latest_block_height"] = str(node.block_store.height())
        except Exception:  # noqa: BLE001 — health must render regardless
            pass
        try:
            ctx["consensus_height"] = str(node.consensus.rs.height)
        except Exception:  # noqa: BLE001
            pass
        doc["node"] = ctx
        return doc

    def debug_postmortem(self) -> dict:
        """The node's crash forensics: the previous run's black-box
        postmortem digest (decoded at boot — ``unclean_shutdown`` means
        the last process died without writing its clean-close sentinel)
        plus the live journal's counters.  Served as ``/debug/postmortem``
        (GET) and the ``debug_postmortem`` JSON-RPC method; jax-free like
        every forensic surface (docs/observability.md)."""
        from cometbft_tpu.libs import blackbox

        boot = getattr(self.node, "boot_postmortem", None)
        doc: dict = {
            "blackbox": "on" if blackbox.enabled() else "off",
            "unclean_shutdown": bool(boot and boot.get("unclean_shutdown")),
            "boot": boot or {},
        }
        stats = blackbox.journal_stats()
        if stats is not None:
            doc["journal"] = stats
        return doc

    def broadcast_evidence(self, evidence) -> dict:
        """Reference: rpc/core/evidence.go BroadcastEvidence.  ``evidence``
        is the proto-encoded evidence (base64/hex/quoted per _bytes_arg)."""
        from cometbft_tpu.types import codec as _codec
        from cometbft_tpu.types.evidence import EvidenceError

        pool = getattr(self.node, "evidence_pool", None)
        if pool is None:
            raise RPCError(-32603, "evidence pool is disabled")
        try:
            ev = _codec.decode_evidence(_bytes_arg(evidence))
        except (ValueError, KeyError) as e:
            raise RPCError(-32602, f"undecodable evidence: {e}") from e
        try:
            pool.add_evidence(ev)
        except EvidenceError as e:
            raise RPCError(-32603, f"evidence rejected: {e}") from e
        return {"hash": _hex(ev.hash())}


# route name -> method name (reference: rpc/core/routes.go)
ROUTES = {
    "health": "health",
    "status": "status",
    "net_info": "net_info",
    "genesis": "genesis",
    "genesis_chunked": "genesis_chunked",
    "blockchain": "blockchain",
    "block": "block",
    "block_by_hash": "block_by_hash",
    "block_results": "block_results",
    "header": "header",
    "header_by_hash": "header_by_hash",
    "commit": "commit",
    "validators": "validators",
    "consensus_params": "consensus_params",
    "consensus_state": "consensus_state",
    "dump_consensus_state": "dump_consensus_state",
    "abci_info": "abci_info",
    "abci_query": "abci_query",
    "broadcast_tx_async": "broadcast_tx_async",
    "broadcast_tx_sync": "broadcast_tx_sync",
    "broadcast_tx_commit": "broadcast_tx_commit",
    "unconfirmed_txs": "unconfirmed_txs",
    "num_unconfirmed_txs": "num_unconfirmed_txs",
    "unconfirmed_tx": "unconfirmed_tx",
    "check_tx": "check_tx",
    "tx": "tx",
    "tx_search": "tx_search",
    "block_search": "block_search",
    "broadcast_evidence": "broadcast_evidence",
    # verify-pipeline flight recorder (docs/observability.md); the slash
    # alias serves the conventional GET /debug/verify_trace path
    "debug_verify_trace": "debug_verify_trace",
    "debug/verify_trace": "debug_verify_trace",
    # black-box crash forensics (boot postmortem digest + live journal)
    "debug_postmortem": "debug_postmortem",
    "debug/postmortem": "debug_postmortem",
}

# Served only when config rpc.unsafe is true (reference AddUnsafeRoutes,
# rpc/core/routes.go:59-64); the server refuses them otherwise.
UNSAFE_ROUTES = {
    "dial_seeds": "dial_seeds",
    "dial_peers": "dial_peers",
    "unsafe_flush_mempool": "unsafe_flush_mempool",
}

# JSON-RPC params that should be ints
_INT_PARAMS = {
    "height",
    "min_height",
    "max_height",
    "page",
    "per_page",
    "limit",
    "chunk",
    "spans",
}
_BOOL_PARAMS = {"prove", "persistent", "unconditional", "private"}


def coerce_params(params: dict) -> dict:
    out = {}
    for k, v in (params or {}).items():
        key = "hash_" if k == "hash" else k
        if key in _INT_PARAMS and isinstance(v, str):
            out[key] = int(v)
        elif key in _BOOL_PARAMS and isinstance(v, str):
            out[key] = v.lower() == "true"
        else:
            out[key] = v
    return out
