"""gRPC services (reference: rpc/grpc/server/services/).

Four services on the public endpoint — version, block, block-results —
plus the privileged pruning service (the data-companion API, reference:
rpc/grpc/server/services/pruningservice).  Implemented with grpc's
generic handlers over JSON payloads: same service/method names as the
reference's proto packages, JSON instead of binary proto on the wire
(this framework's RPC schema is self-defined; see libs/protoenc).
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Optional

from cometbft_tpu.libs import log as liblog
from cometbft_tpu.version import BLOCK_PROTOCOL, CMT_SEMVER, P2P_PROTOCOL

_VERSION_SVC = "cometbft.services.version.v1.VersionService"
_BLOCK_SVC = "cometbft.services.block.v1.BlockService"
_BLOCK_RESULTS_SVC = "cometbft.services.block_results.v1.BlockResultsService"
_PRUNING_SVC = "cometbft.services.pruning.v1.PruningService"


def _json_ser(obj) -> bytes:
    return json.dumps(obj).encode()


def _json_deser(raw: bytes):
    return json.loads(raw.decode()) if raw else {}


class GRPCServer:
    """Reference: rpc/grpc/server/server.go Serve + ServePrivileged."""

    def __init__(self, node, laddr: str, privileged: bool = False, logger=None):
        import grpc

        self.node = node
        self.privileged = privileged
        self.logger = logger or liblog.nop_logger()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        gcfg = node.config.grpc
        handlers = []
        if privileged:
            if gcfg.pruning_service_enabled:
                handlers.append(self._pruning_service(grpc))
        else:
            if gcfg.version_service_enabled:
                handlers.append(self._version_service(grpc))
            if gcfg.block_service_enabled:
                handlers.append(self._block_service(grpc))
            if gcfg.block_results_service_enabled:
                handlers.append(self._block_results_service(grpc))
        for h in handlers:
            self._server.add_generic_rpc_handlers((h,))
        addr = laddr.replace("tcp://", "")
        self.bound_port = self._server.add_insecure_port(addr)

    # -- services ----------------------------------------------------------

    def _unary(self, grpc, fn):
        return grpc.unary_unary_rpc_method_handler(
            fn, request_deserializer=_json_deser, response_serializer=_json_ser
        )

    def _version_service(self, grpc):
        def get_version(request, context):
            return {
                "node": CMT_SEMVER,
                "abci": "2.2.0",
                "p2p": str(P2P_PROTOCOL),
                "block": str(BLOCK_PROTOCOL),
            }

        return grpc.method_handlers_generic_handler(
            _VERSION_SVC, {"GetVersion": self._unary(grpc, get_version)}
        )

    def _block_service(self, grpc):
        from cometbft_tpu.rpc.core import _block_json, _block_id_json

        def get_block(request, context):
            h = int(request.get("height", 0)) or self.node.block_store.height()
            block = self.node.block_store.load_block(h)
            meta = self.node.block_store.load_block_meta(h)
            if block is None or meta is None:
                context.abort(grpc.StatusCode.NOT_FOUND, f"block {h} not found")
            return {
                "block_id": _block_id_json(meta.block_id),
                "block": _block_json(block),
            }

        def get_latest_height(request, context):
            # single-shot variant of the reference's streaming endpoint
            return {"height": str(self.node.block_store.height())}

        return grpc.method_handlers_generic_handler(
            _BLOCK_SVC,
            {
                "GetByHeight": self._unary(grpc, get_block),
                "GetLatestHeight": self._unary(grpc, get_latest_height),
            },
        )

    def _block_results_service(self, grpc):
        from cometbft_tpu.rpc.core import Environment

        def get_block_results(request, context):
            env = Environment(self.node)
            h = int(request.get("height", 0)) or None
            try:
                return env.block_results(h)
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))

        return grpc.method_handlers_generic_handler(
            _BLOCK_RESULTS_SVC,
            {"GetBlockResults": self._unary(grpc, get_block_results)},
        )

    def _pruning_service(self, grpc):
        """Data-companion retain heights (reference: pruningservice)."""

        def set_block_retain_height(request, context):
            h = int(request.get("height", 0))
            self.node.block_exec._retain.companion_retain = h
            return {}

        def get_block_retain_height(request, context):
            r = self.node.block_exec._retain
            return {
                "app_retain_height": str(r.app_retain),
                "pruning_service_retain_height": str(r.companion_retain),
            }

        return grpc.method_handlers_generic_handler(
            _PRUNING_SVC,
            {
                "SetBlockRetainHeight": self._unary(grpc, set_block_retain_height),
                "GetBlockRetainHeight": self._unary(grpc, get_block_retain_height),
            },
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)


def make_client_channel(target: str):
    """A channel whose calls use the same JSON codec (for tests/tools)."""
    import grpc

    return grpc.insecure_channel(target.replace("tcp://", ""))


def grpc_call(channel, service: str, method: str, request: dict) -> dict:
    callable_ = channel.unary_unary(
        f"/{service}/{method}",
        request_serializer=_json_ser,
        response_deserializer=_json_deser,
    )
    return callable_(request)
