"""gRPC services with the reference's protobuf wire format.

Four services (reference: rpc/grpc/server/services/): version, block,
block-results on the public endpoint, plus the privileged pruning service
(the data-companion API).  Requests and responses are the real protobuf
messages from proto/cometbft/services/* — any client built against the
reference's .proto files (grpcurl, Go/Rust data companions) can connect.

Method handlers are registered through grpc's generic-handler API with
protobuf (de)serializers; service code generation is not required.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import cometbft_tpu.proto_gen  # noqa: F401 — sys.path hook for cometbft.*

from cometbft.services.block.v1 import block_pb2 as block_svc_pb
from cometbft.services.block_results.v1 import (
    block_results_pb2 as block_results_svc_pb,
)
from cometbft.services.pruning.v1 import pruning_pb2 as pruning_pb
from cometbft.services.version.v1 import version_pb2 as version_pb

from cometbft_tpu.libs import log as liblog
from cometbft_tpu.rpc import pb_convert as conv
from cometbft_tpu.version import (
    ABCI_SEMVER,
    BLOCK_PROTOCOL,
    CMT_SEMVER,
    P2P_PROTOCOL,
)

_VERSION_SVC = "cometbft.services.version.v1.VersionService"
_BLOCK_SVC = "cometbft.services.block.v1.BlockService"
_BLOCK_RESULTS_SVC = "cometbft.services.block_results.v1.BlockResultsService"
_PRUNING_SVC = "cometbft.services.pruning.v1.PruningService"


class GRPCServer:
    """Reference: rpc/grpc/server/server.go Serve + ServePrivileged."""

    def __init__(self, node, laddr: str, privileged: bool = False, logger=None):
        import grpc

        self.node = node
        self.privileged = privileged
        self.logger = logger or liblog.nop_logger()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        gcfg = node.config.grpc
        handlers = []
        if privileged:
            if gcfg.pruning_service_enabled:
                handlers.append(self._pruning_service(grpc))
        else:
            if gcfg.version_service_enabled:
                handlers.append(self._version_service(grpc))
            if gcfg.block_service_enabled:
                handlers.append(self._block_service(grpc))
            if gcfg.block_results_service_enabled:
                handlers.append(self._block_results_service(grpc))
        for h in handlers:
            self._server.add_generic_rpc_handlers((h,))
        addr = laddr.replace("tcp://", "")
        self.bound_port = self._server.add_insecure_port(addr)

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _unary(grpc, fn, req_cls, resp_cls):
        return grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )

    @staticmethod
    def _stream(grpc, fn, req_cls, resp_cls):
        return grpc.unary_stream_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )

    # -- services -----------------------------------------------------------

    def _version_service(self, grpc):
        def get_version(request, context):
            return version_pb.GetVersionResponse(
                node=CMT_SEMVER,
                abci=ABCI_SEMVER,
                p2p=P2P_PROTOCOL,
                block=BLOCK_PROTOCOL,
            )

        return grpc.method_handlers_generic_handler(
            _VERSION_SVC,
            {
                "GetVersion": self._unary(
                    grpc,
                    get_version,
                    version_pb.GetVersionRequest,
                    version_pb.GetVersionResponse,
                )
            },
        )

    def _block_service(self, grpc):
        def get_by_height(request, context):
            h = request.height or self.node.block_store.height()
            block = self.node.block_store.load_block(h)
            meta = self.node.block_store.load_block_meta(h)
            if block is None or meta is None:
                context.abort(grpc.StatusCode.NOT_FOUND, f"block {h} not found")
            resp = block_svc_pb.GetByHeightResponse()
            resp.block_id.CopyFrom(conv.block_id_pb(meta.block_id))
            resp.block.CopyFrom(conv.block_pb(block))
            return resp

        def get_latest_height(request, context):
            # Stream of height updates (reference: blockservice
            # GetLatestHeight subscribes to the event bus).  Emit the
            # current height, then follow new blocks until the client
            # disconnects.
            import queue as _queue

            from cometbft_tpu.libs.pubsub import Query

            yield block_svc_pb.GetLatestHeightResponse(
                height=self.node.block_store.height()
            )
            bus = getattr(self.node, "event_bus", None)
            if bus is None:
                return
            sub_id = "grpc-latest-height-%d" % id(context)
            try:
                sub = bus.subscribe(
                    sub_id, Query.parse("tm.event='NewBlock'"), capacity=128
                )
            except Exception:
                return
            try:
                while context.is_active() and not sub.canceled.is_set():
                    try:
                        sub.out.get(timeout=1.0)
                    except _queue.Empty:
                        continue
                    yield block_svc_pb.GetLatestHeightResponse(
                        height=self.node.block_store.height()
                    )
            finally:
                try:
                    bus.unsubscribe_all(sub_id)
                except Exception:
                    pass

        return grpc.method_handlers_generic_handler(
            _BLOCK_SVC,
            {
                "GetByHeight": self._unary(
                    grpc,
                    get_by_height,
                    block_svc_pb.GetByHeightRequest,
                    block_svc_pb.GetByHeightResponse,
                ),
                "GetLatestHeight": self._stream(
                    grpc,
                    get_latest_height,
                    block_svc_pb.GetLatestHeightRequest,
                    block_svc_pb.GetLatestHeightResponse,
                ),
            },
        )

    def _block_results_service(self, grpc):
        from cometbft_tpu.state.execution import fbr_from_json

        def get_block_results(request, context):
            h = request.height or self.node.block_store.height()
            raw = self.node.state_store.load_finalize_block_response(h)
            if raw is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND, f"no results for height {h}"
                )
            res = fbr_from_json(raw)
            resp = block_results_svc_pb.GetBlockResultsResponse(
                height=h, app_hash=res.app_hash
            )
            for r in res.tx_results:
                resp.tx_results.add().CopyFrom(conv.exec_tx_result_pb(r))
            for e in res.events:
                resp.finalize_block_events.add().CopyFrom(conv.event_pb(e))
            for v in res.validator_updates:
                resp.validator_updates.add().CopyFrom(
                    conv.validator_update_pb(v)
                )
            conv.params_to_pb(
                resp.consensus_param_updates, res.consensus_param_updates
            )
            return resp

        return grpc.method_handlers_generic_handler(
            _BLOCK_RESULTS_SVC,
            {
                "GetBlockResults": self._unary(
                    grpc,
                    get_block_results,
                    block_results_svc_pb.GetBlockResultsRequest,
                    block_results_svc_pb.GetBlockResultsResponse,
                )
            },
        )

    def _pruning_service(self, grpc):
        """Data-companion retain heights (reference: pruningservice).
        Every setter persists the heights so a restart cannot drop a
        companion's hold on data it has not yet ingested."""
        node = self.node

        def _persist():
            try:
                node.state_store.save_retain_heights(node.block_exec._retain)
            except Exception:  # noqa: BLE001 — persistence is best-effort
                self.logger.error("failed to persist retain heights")

        def set_block(request, context):
            node.block_exec._retain.companion_retain = request.height
            _persist()
            return pruning_pb.SetBlockRetainHeightResponse()

        def get_block(request, context):
            r = node.block_exec._retain
            return pruning_pb.GetBlockRetainHeightResponse(
                app_retain_height=r.app_retain,
                pruning_service_retain_height=r.companion_retain,
            )

        def set_block_results(request, context):
            node.block_exec._retain.companion_results_retain = request.height
            _persist()
            return pruning_pb.SetBlockResultsRetainHeightResponse()

        def get_block_results(request, context):
            r = node.block_exec._retain
            return pruning_pb.GetBlockResultsRetainHeightResponse(
                pruning_service_retain_height=getattr(
                    r, "companion_results_retain", 0
                )
            )

        def set_tx_indexer(request, context):
            node.block_exec._retain.tx_index_retain = request.height
            _persist()
            return pruning_pb.SetTxIndexerRetainHeightResponse()

        def get_tx_indexer(request, context):
            return pruning_pb.GetTxIndexerRetainHeightResponse(
                height=getattr(node.block_exec._retain, "tx_index_retain", 0)
            )

        def set_block_indexer(request, context):
            node.block_exec._retain.block_index_retain = request.height
            _persist()
            return pruning_pb.SetBlockIndexerRetainHeightResponse()

        def get_block_indexer(request, context):
            return pruning_pb.GetBlockIndexerRetainHeightResponse(
                height=getattr(
                    node.block_exec._retain, "block_index_retain", 0
                )
            )

        def u(fn, name):
            return self._unary(
                grpc,
                fn,
                getattr(pruning_pb, name + "Request"),
                getattr(pruning_pb, name + "Response"),
            )

        return grpc.method_handlers_generic_handler(
            _PRUNING_SVC,
            {
                "SetBlockRetainHeight": u(set_block, "SetBlockRetainHeight"),
                "GetBlockRetainHeight": u(get_block, "GetBlockRetainHeight"),
                "SetBlockResultsRetainHeight": u(
                    set_block_results, "SetBlockResultsRetainHeight"
                ),
                "GetBlockResultsRetainHeight": u(
                    get_block_results, "GetBlockResultsRetainHeight"
                ),
                "SetTxIndexerRetainHeight": u(
                    set_tx_indexer, "SetTxIndexerRetainHeight"
                ),
                "GetTxIndexerRetainHeight": u(
                    get_tx_indexer, "GetTxIndexerRetainHeight"
                ),
                "SetBlockIndexerRetainHeight": u(
                    set_block_indexer, "SetBlockIndexerRetainHeight"
                ),
                "GetBlockIndexerRetainHeight": u(
                    get_block_indexer, "GetBlockIndexerRetainHeight"
                ),
            },
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)


def make_client_channel(target: str):
    import grpc

    return grpc.insecure_channel(target.replace("tcp://", ""))


def grpc_unary(channel, service: str, method: str, request, resp_cls):
    """One protobuf unary call (client side of the generic handlers)."""
    callable_ = channel.unary_unary(
        f"/{service}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )
    return callable_(request)
