"""Internal types -> wire protobuf messages (cometbft.* packages).

The framework's own storage/gossip encoding is self-defined
(libs/protoenc); these converters produce the *reference-compatible*
protobuf messages that external data companions and gRPC clients expect
(reference: rpc/grpc/server/services/*/service.go response construction).
"""

from __future__ import annotations

import cometbft_tpu.proto_gen  # noqa: F401 — sys.path hook for cometbft.*

from cometbft.abci.v1 import types_pb2 as abci_pb
from cometbft.types.v1 import block_pb2, evidence_pb2, types_pb2


def _ts(pb_ts, t) -> None:
    pb_ts.seconds = t.seconds
    pb_ts.nanos = t.nanos


def block_id_pb(bid) -> types_pb2.BlockID:
    out = types_pb2.BlockID(hash=bid.hash)
    out.part_set_header.total = bid.part_set_header.total
    out.part_set_header.hash = bid.part_set_header.hash
    return out


def header_pb(h) -> types_pb2.Header:
    out = types_pb2.Header(
        chain_id=h.chain_id,
        height=h.height,
        last_commit_hash=h.last_commit_hash,
        data_hash=h.data_hash,
        validators_hash=h.validators_hash,
        next_validators_hash=h.next_validators_hash,
        consensus_hash=h.consensus_hash,
        app_hash=h.app_hash,
        last_results_hash=h.last_results_hash,
        evidence_hash=h.evidence_hash,
        proposer_address=h.proposer_address,
    )
    out.version.block = h.version.block
    out.version.app = h.version.app
    _ts(out.time, h.time)
    out.last_block_id.CopyFrom(block_id_pb(h.last_block_id))
    return out


def vote_pb(v) -> types_pb2.Vote:
    out = types_pb2.Vote(
        type=v.type_,
        height=v.height,
        round=v.round_,
        validator_address=v.validator_address,
        validator_index=v.validator_index,
        signature=v.signature,
        extension=v.extension,
        extension_signature=v.extension_signature,
    )
    out.block_id.CopyFrom(block_id_pb(v.block_id))
    _ts(out.timestamp, v.timestamp)
    return out


def commit_pb(c) -> types_pb2.Commit:
    out = types_pb2.Commit(height=c.height, round=c.round_)
    out.block_id.CopyFrom(block_id_pb(c.block_id))
    for sig in c.signatures:
        s = out.signatures.add()
        s.block_id_flag = sig.block_id_flag
        s.validator_address = sig.validator_address
        s.signature = sig.signature
        _ts(s.timestamp, sig.timestamp)
    return out


def _validator_pb(v) -> types_pb2.Validator:
    return types_pb2.Validator(
        address=v.address,
        voting_power=v.voting_power,
        proposer_priority=getattr(v, "proposer_priority", 0),
        pub_key_bytes=v.pub_key.bytes(),
        pub_key_type=v.pub_key.type_,
    )


def evidence_pb(ev) -> evidence_pb2.Evidence:
    out = evidence_pb2.Evidence()
    if ev.TYPE == "duplicate_vote":
        dv = out.duplicate_vote_evidence
        dv.vote_a.CopyFrom(vote_pb(ev.vote_a))
        dv.vote_b.CopyFrom(vote_pb(ev.vote_b))
        dv.total_voting_power = ev.total_voting_power
        dv.validator_power = ev.validator_power
        _ts(dv.timestamp, ev.timestamp)
    else:  # light_client_attack
        la = out.light_client_attack_evidence
        lb = ev.conflicting_block
        la.conflicting_block.signed_header.header.CopyFrom(
            header_pb(lb.signed_header.header)
        )
        la.conflicting_block.signed_header.commit.CopyFrom(
            commit_pb(lb.signed_header.commit)
        )
        vs = la.conflicting_block.validator_set
        for v in lb.validator_set.validators:
            vs.validators.add().CopyFrom(_validator_pb(v))
        if lb.validator_set.validators:
            vs.proposer.CopyFrom(
                _validator_pb(lb.validator_set.get_proposer())
            )
        vs.total_voting_power = lb.validator_set.total_voting_power()
        la.common_height = ev.common_height
        for v in ev.byzantine_validators:
            la.byzantine_validators.add().CopyFrom(_validator_pb(v))
        la.total_voting_power = ev.total_voting_power
        _ts(la.timestamp, ev.timestamp)
    return out


def block_pb(b) -> block_pb2.Block:
    out = block_pb2.Block()
    out.header.CopyFrom(header_pb(b.header))
    out.data.txs.extend(b.data.txs)
    for ev in b.evidence:
        out.evidence.evidence.add().CopyFrom(evidence_pb(ev))
    if b.last_commit is not None:
        out.last_commit.CopyFrom(commit_pb(b.last_commit))
    return out


def event_pb(e) -> abci_pb.Event:
    out = abci_pb.Event(type=e.type_)
    for a in e.attributes:
        out.attributes.add(key=a.key, value=a.value, index=a.index)
    return out


def exec_tx_result_pb(r) -> abci_pb.ExecTxResult:
    out = abci_pb.ExecTxResult(
        code=r.code,
        data=r.data,
        log=r.log,
        info=r.info,
        gas_wanted=r.gas_wanted,
        gas_used=r.gas_used,
        codespace=r.codespace,
    )
    for e in r.events:
        out.events.add().CopyFrom(event_pb(e))
    return out


def validator_update_pb(v) -> abci_pb.ValidatorUpdate:
    return abci_pb.ValidatorUpdate(
        power=v.power,
        pub_key_bytes=v.pub_key_bytes,
        pub_key_type=v.pub_key_type,
    )


_NS = 1_000_000_000


def params_to_pb(target, params) -> None:
    """Internal consensus-params dict -> cometbft.types.v1.ConsensusParams
    (in place on ``target``)."""
    if not params:
        return
    block = params.get("block", {})
    if block:
        target.block.max_bytes = int(block.get("max_bytes", 0))
        target.block.max_gas = int(block.get("max_gas", 0))
    ev = params.get("evidence", {})
    if ev:
        target.evidence.max_age_num_blocks = int(
            ev.get("max_age_num_blocks", 0)
        )
        dur_ns = int(ev.get("max_age_duration", 0))
        target.evidence.max_age_duration.seconds = dur_ns // _NS
        target.evidence.max_age_duration.nanos = dur_ns % _NS
        target.evidence.max_bytes = int(ev.get("max_bytes", 0))
    val = params.get("validator", {})
    if val:
        target.validator.pub_key_types.extend(val.get("pub_key_types", []))
    feat = params.get("feature", {})
    if feat:
        if "vote_extensions_enable_height" in feat:
            target.feature.vote_extensions_enable_height.value = int(
                feat["vote_extensions_enable_height"]
            )
        if "pbts_enable_height" in feat:
            target.feature.pbts_enable_height.value = int(
                feat["pbts_enable_height"]
            )


def params_from_pb(msg):
    """cometbft.types.v1.ConsensusParams -> internal dict (None if empty)."""
    if msg is None or not msg.ByteSize():
        return None
    out: dict = {}
    if msg.HasField("block"):
        out["block"] = {
            "max_bytes": msg.block.max_bytes,
            "max_gas": msg.block.max_gas,
        }
    if msg.HasField("evidence"):
        out["evidence"] = {
            "max_age_num_blocks": msg.evidence.max_age_num_blocks,
            "max_age_duration": msg.evidence.max_age_duration.seconds * _NS
            + msg.evidence.max_age_duration.nanos,
            "max_bytes": msg.evidence.max_bytes,
        }
    if msg.HasField("validator"):
        out["validator"] = {
            "pub_key_types": list(msg.validator.pub_key_types)
        }
    if msg.HasField("feature"):
        feat = {}
        if msg.feature.HasField("vote_extensions_enable_height"):
            feat["vote_extensions_enable_height"] = (
                msg.feature.vote_extensions_enable_height.value
            )
        if msg.feature.HasField("pbts_enable_height"):
            feat["pbts_enable_height"] = msg.feature.pbts_enable_height.value
        if feat:
            out["feature"] = feat
    return out or None
