"""JSON-RPC server: HTTP (POST body + GET URI params) and WebSocket
subscriptions.

Reference: rpc/jsonrpc/server — routes resolve against ``core.ROUTES``;
``/websocket`` upgrades to RFC-6455 and supports ``subscribe`` /
``unsubscribe`` / ``unsubscribe_all`` backed by the node's EventBus, pushing
each matching event as a JSON-RPC notification with the subscription query
echoed (reference: rpc/core/events.go + jsonrpc/server/ws_handler.go).
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

from cometbft_tpu.libs import log as liblog
from cometbft_tpu.libs.pubsub import Query, QueryError
from cometbft_tpu.rpc import core as rpccore

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def _rpc_response(id_, result=None, error=None) -> bytes:
    doc = {"jsonrpc": "2.0", "id": id_}
    if error is not None:
        doc["error"] = error
    else:
        doc["result"] = result
    return json.dumps(doc).encode()


def _route_status(method, payload: bytes) -> int:
    """HTTP status for a JSON-RPC reply.  Everything is 200 except the
    health route, whose errors (fail-stop storage fault) must surface as
    a 503 so liveness probes fail without parsing JSON-RPC envelopes."""
    if method != "health":
        return 200
    try:
        doc = json.loads(payload)
    except ValueError:
        return 200
    return 503 if isinstance(doc, dict) and "error" in doc else 200


def _event_to_json(msg) -> dict:
    """Render a pubsub Message (typed event data) for WS delivery."""
    from cometbft_tpu.types import events as tev

    data = msg.data
    ev_type = msg.tags.get(tev.EVENT_TYPE_KEY, ["?"])[0]
    value: dict = {}
    if isinstance(data, tev.EventDataNewBlock):
        value = {"block": rpccore._block_json(data.block)}
    elif isinstance(data, tev.EventDataNewBlockHeader):
        value = {"header": rpccore._header_json(data.header)}
    elif isinstance(data, tev.EventDataTx):
        value = {
            "TxResult": {
                "height": str(data.height),
                "index": data.index,
                "tx": base64.b64encode(data.tx).decode(),
                "result": rpccore._tx_result_json(data.result),
            }
        }
    elif isinstance(data, tev.EventDataRoundState):
        value = {"height": str(data.height), "round": data.round_, "step": data.step}
    elif isinstance(data, tev.EventDataVote):
        v = data.vote
        value = {
            "vote": {
                "type": v.type_,
                "height": str(v.height),
                "round": v.round_,
                "validator_index": v.validator_index,
            }
        }
    return {
        "type": f"tendermint/event/{ev_type}",
        "value": value,
        "events": msg.tags,
    }


class RPCServer:
    def __init__(self, rpc_config, env: rpccore.Environment, event_bus, logger=None):
        self.config = rpc_config
        self.env = env
        self.event_bus = event_bus
        self.logger = logger or liblog.nop_logger()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.bound_port: Optional[int] = None

    def start(self) -> None:
        addr = self.config.laddr
        hostport = addr[len("tcp://") :] if addr.startswith("tcp://") else addr
        host, port = hostport.rsplit(":", 1)
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # silence default stderr spam
                server.logger.debug("http " + fmt % args)

            def _send_json(self, payload: bytes, status: int = 200):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                cors = server.config.cors_allowed_origins
                if cors:
                    self.send_header("Access-Control-Allow-Origin", cors[0])
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path in ("/websocket", "/v1/websocket"):
                    server._handle_websocket(self)
                    return
                name = url.path.lstrip("/")
                if not name:
                    routes = "\n".join(sorted(rpccore.ROUTES))
                    self._send_json(
                        json.dumps({"available_endpoints": sorted(rpccore.ROUTES)}).encode()
                    )
                    return
                params = dict(parse_qsl(url.query))
                # URI string params arrive quoted: strip the quotes but keep
                # the fact that they were quoted (bytes args decode raw)
                params = {
                    k: rpccore.QuotedString(v[1:-1])
                    if len(v) >= 2 and v[0] == '"' and v[-1] == '"'
                    else v
                    for k, v in params.items()
                }
                self._dispatch(name, params, id_=-1)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                if length > server.config.max_body_bytes:
                    self._send_json(
                        _rpc_response(
                            None, error={"code": -32600, "message": "body too large"}
                        ),
                        413,
                    )
                    return
                body = self.rfile.read(length)
                try:
                    req = json.loads(body)
                except (json.JSONDecodeError, UnicodeDecodeError, ValueError,
                        RecursionError):
                    # non-UTF8 bytes, malformed JSON and parser bombs all
                    # get the spec parse-error reply, never a broken conn
                    self._send_json(
                        _rpc_response(
                            None, error={"code": -32700, "message": "parse error"}
                        )
                    )
                    return
                if isinstance(req, list):  # batch
                    parts = [server._call_route_json(r) for r in req[: server.config.max_request_batch_size]]
                    self._send_json(b"[" + b",".join(parts) + b"]")
                    return
                payload = server._call_route_json(req)
                method = req.get("method") if isinstance(req, dict) else None
                self._send_json(payload, _route_status(method, payload))

            def _dispatch(self, name: str, params: dict, id_):
                payload = server._call_route_json(
                    {"method": name, "params": params, "id": id_}
                )
                self._send_json(payload, _route_status(name, payload))

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.bound_port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        self.logger.info("RPC server listening", addr=f"{host}:{self.bound_port}")

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()

    # -- route dispatch ----------------------------------------------------

    def _call_route_json(self, req: dict) -> bytes:
        if not isinstance(req, dict):
            return _rpc_response(
                None, error={"code": -32600, "message": "invalid request"}
            )
        id_ = req.get("id", -1)
        method = req.get("method", "")
        if not isinstance(method, str):
            return _rpc_response(
                id_, error={"code": -32600, "message": "method must be a string"}
            )
        params = req.get("params")
        if params is None:
            params = {}
        if not isinstance(params, (dict, list)):
            return _rpc_response(
                id_, error={"code": -32602, "message": "params must be an object"}
            )
        if isinstance(params, list):
            return _rpc_response(
                id_,
                error={
                    "code": -32602,
                    "message": "positional params not supported; use named params",
                },
            )
        fn_name = rpccore.ROUTES.get(method)
        if fn_name is None:
            fn_name = rpccore.UNSAFE_ROUTES.get(method)
            if fn_name is not None and not getattr(
                self.config, "unsafe", False
            ):
                return _rpc_response(
                    id_,
                    error={
                        "code": -32601,
                        "message": f"method {method!r} requires rpc.unsafe",
                    },
                )
        if fn_name is None:
            return _rpc_response(
                id_, error={"code": -32601, "message": f"method {method!r} not found"}
            )
        try:
            kwargs = rpccore.coerce_params(params)
            result = getattr(self.env, fn_name)(**kwargs)
            return _rpc_response(id_, result=result)
        except rpccore.RPCError as e:
            return _rpc_response(
                id_, error={"code": e.code, "message": e.message, "data": e.data}
            )
        except TypeError as e:
            return _rpc_response(
                id_, error={"code": -32602, "message": f"invalid params: {e}"}
            )
        except Exception as e:  # noqa: BLE001
            self.logger.error("rpc handler error", method=method, err=repr(e))
            return _rpc_response(
                id_, error={"code": -32603, "message": f"internal error: {e}"}
            )

    # -- WebSocket ---------------------------------------------------------

    def _handle_websocket(self, handler: BaseHTTPRequestHandler) -> None:
        key = handler.headers.get("Sec-WebSocket-Key")
        if not key:
            handler.send_response(400)
            handler.end_headers()
            return
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()
        ).decode()
        handler.send_response(101, "Switching Protocols")
        handler.send_header("Upgrade", "websocket")
        handler.send_header("Connection", "Upgrade")
        handler.send_header("Sec-WebSocket-Accept", accept)
        handler.end_headers()
        conn = handler.connection
        conn.settimeout(None)
        _WSConn(self, conn).run()


def _ws_send(conn: socket.socket, payload: bytes, opcode: int = 1) -> None:
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([n])
    elif n < 1 << 16:
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    conn.sendall(header + payload)


_WS_MAX_FRAME = 16 * 1024 * 1024  # cap attacker-declared frame lengths


def _ws_recv(conn: socket.socket) -> Optional[tuple[int, bytes]]:
    def read_exact(k: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < k:
            chunk = conn.recv(k - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    hdr = read_exact(2)
    if hdr is None:
        return None
    opcode = hdr[0] & 0x0F
    masked = hdr[1] & 0x80
    n = hdr[1] & 0x7F
    if n == 126:
        ext = read_exact(2)
        if ext is None:
            return None
        n = struct.unpack(">H", ext)[0]
    elif n == 127:
        ext = read_exact(8)
        if ext is None:
            return None
        n = struct.unpack(">Q", ext)[0]
    if n > _WS_MAX_FRAME:
        return None  # oversized frame: drop the connection
    mask = b"\x00" * 4
    if masked:
        mask = read_exact(4)
        if mask is None:
            return None
    payload = read_exact(n) if n else b""
    if payload is None:
        return None
    if masked:
        payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    return opcode, payload


class _WSConn:
    """One WebSocket client: JSON-RPC over frames + event push."""

    _counter = 0
    _counter_lock = threading.Lock()

    def __init__(self, server: RPCServer, conn: socket.socket):
        self.server = server
        self.conn = conn
        with _WSConn._counter_lock:
            _WSConn._counter += 1
            self.subscriber = f"ws-{_WSConn._counter}"
        self._send_lock = threading.Lock()
        self._pushers: list[threading.Thread] = []
        self._closed = threading.Event()

    def _send(self, payload: bytes, opcode: int = 1) -> None:
        with self._send_lock:
            _ws_send(self.conn, payload, opcode)

    def run(self) -> None:
        try:
            while not self._closed.is_set():
                frame = _ws_recv(self.conn)
                if frame is None:
                    break
                opcode, payload = frame
                if opcode == 8:  # close
                    self._send(b"", opcode=8)
                    break
                if opcode == 9:  # ping
                    self._send(payload, opcode=10)
                    continue
                if opcode not in (1, 2):
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    self._send(
                        _rpc_response(
                            None, error={"code": -32700, "message": "parse error"}
                        )
                    )
                    continue
                self._handle_rpc(req)
        except OSError:
            pass
        finally:
            self._closed.set()
            self.server.event_bus.unsubscribe_all(self.subscriber)
            try:
                self.conn.close()
            except OSError:
                pass

    def _handle_rpc(self, req: dict) -> None:
        method = req.get("method", "")
        id_ = req.get("id", -1)
        params = req.get("params") or {}
        if method == "subscribe":
            self._subscribe(id_, params.get("query", ""))
        elif method == "unsubscribe":
            try:
                q = Query.parse(params.get("query", ""))
                self.server.event_bus.unsubscribe(self.subscriber, q)
                self._send(_rpc_response(id_, result={}))
            except (QueryError, ValueError) as e:
                self._send(
                    _rpc_response(id_, error={"code": -32603, "message": str(e)})
                )
        elif method == "unsubscribe_all":
            self.server.event_bus.unsubscribe_all(self.subscriber)
            self._send(_rpc_response(id_, result={}))
        else:
            self._send(self.server._call_route_json(req))

    def _subscribe(self, id_, query_str: str) -> None:
        try:
            q = Query.parse(query_str)
        except QueryError as e:
            self._send(
                _rpc_response(id_, error={"code": -32602, "message": str(e)})
            )
            return
        try:
            sub = self.server.event_bus.subscribe(
                self.subscriber, q, capacity=100
            )
        except ValueError as e:
            self._send(
                _rpc_response(id_, error={"code": -32603, "message": str(e)})
            )
            return
        self._send(_rpc_response(id_, result={}))

        def pusher():
            while not self._closed.is_set() and not sub.canceled.is_set():
                msg = sub.next(timeout=0.2)
                if msg is None:
                    continue
                payload = _rpc_response(
                    id_,
                    result={
                        "query": query_str,
                        "data": _event_to_json(msg),
                        "events": msg.tags,
                    },
                )
                try:
                    self._send(payload)
                except OSError:
                    self._closed.set()
                    return

        t = threading.Thread(target=pusher, daemon=True)
        t.start()
        self._pushers.append(t)
