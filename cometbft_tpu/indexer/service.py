"""Indexer service: subscribes to the event bus and feeds the indexers.

Reference: state/txindex/indexer_service.go — one subscriber draining
NewBlockEvents + Tx events so tx_search/block_search stay current.
"""

from __future__ import annotations

import threading
import time

from cometbft_tpu.libs import log as liblog
from cometbft_tpu.libs.pubsub import Query
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.types import events as tev

_SUBSCRIBER = "IndexerService"


class IndexerService(BaseService):
    """Reference: txindex/indexer_service.go IndexerService."""

    def __init__(self, tx_indexer, block_indexer, event_bus, logger=None):
        super().__init__("IndexerService")
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self.logger = logger or liblog.nop_logger()
        self._thread = None
        self._tx_sub = None
        self._block_sub = None

    def on_start(self) -> None:
        self._tx_sub = self.event_bus.subscribe(
            _SUBSCRIBER,
            Query.parse(f"{tev.EVENT_TYPE_KEY}='{tev.EVENT_TX}'"),
            capacity=1000,
        )
        self._block_sub = self.event_bus.subscribe(
            _SUBSCRIBER,
            Query.parse(f"{tev.EVENT_TYPE_KEY}='{tev.EVENT_NEW_BLOCK_EVENTS}'"),
            capacity=100,
        )
        self._thread = threading.Thread(
            target=self._run, name="indexer", daemon=True
        )
        self._thread.start()

    def on_stop(self) -> None:
        try:
            self.event_bus.unsubscribe_all(_SUBSCRIBER)
        except Exception:  # noqa: BLE001
            pass

    def _run(self) -> None:
        while self.is_running:
            # drain BOTH queues completely each wakeup — blocking on one
            # starves the other and a full queue gets its subscription
            # canceled by the pubsub server
            drained = 0
            while True:
                msg = self._block_sub.next(timeout=0)
                if msg is None:
                    break
                drained += 1
                data: tev.EventDataNewBlockEvents = msg.data
                try:
                    self.block_indexer.index(data.height, data.events)
                except Exception as e:  # noqa: BLE001
                    self.logger.error("block index failed", err=repr(e))
            txs = []
            while True:
                tx_msg = self._tx_sub.next(timeout=0)
                if tx_msg is None:
                    break
                drained += 1
                txs.append(tx_msg.data)
            if txs:
                self._index_txs(txs)
            if not drained:
                time.sleep(0.02)

    def _index_txs(self, batch) -> None:
        """One drain's worth of txs: use the indexer's batch entry point
        when it has one (the psql sink commits once per batch, reference
        psql.go IndexTxEvents takes the whole block's txs) else per-tx.
        A failing batch falls back to per-tx indexing so one bad tx never
        discards the rest of the drain, and per-tx errors are isolated."""
        index_batch = getattr(self.tx_indexer, "index_batch", None)
        if index_batch is not None:
            try:
                index_batch(batch)
                return
            except Exception as e:  # noqa: BLE001
                self.logger.error(
                    "batch tx index failed; retrying per-tx", err=repr(e)
                )
        for d in batch:
            try:
                self.tx_indexer.index(d.height, d.index, d.tx, d.result)
            except Exception as e:  # noqa: BLE001
                self.logger.error("tx index failed", err=repr(e))
