from cometbft_tpu.indexer.kv import KVBlockIndexer, KVTxIndexer, TxResult
from cometbft_tpu.indexer.service import IndexerService

__all__ = ["KVTxIndexer", "KVBlockIndexer", "TxResult", "IndexerService"]
