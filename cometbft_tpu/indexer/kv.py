"""KV event indexers (reference: state/txindex/kv/kv.go and
state/indexer/block/kv/kv.go).

Compound keys make range queries a prefix scan:
  tx primary   ``txh/<hash>``                          -> TxResult record
  tx event     ``txe/<tag>/<value>/<height>/<index>``  -> tx hash
  block event  ``bhe/<tag>/<value>/<height>``          -> b""

Search evaluates a query's conditions as index scans and intersects the
candidate sets (the reference's approach for its compound keyspace).
"""

from __future__ import annotations

import base64
import struct
from dataclasses import dataclass, field
from itertools import islice
from typing import Optional

from cometbft_tpu.abci import types as at
from cometbft_tpu.crypto import tmhash
from cometbft_tpu.libs.pubsub import Query

_TX_PRIMARY = b"txh/"
_TX_EVENT = b"txe/"
_BLOCK_EVENT = b"bhe/"

TX_HASH_TAG = "tx.hash"
TX_HEIGHT_TAG = "tx.height"
BLOCK_HEIGHT_TAG = "block.height"


def migrate_legacy_index(chain_db, index_db, chunk: int = 4096) -> int:
    """Move KV index entries out of the shared chain db into the
    dedicated index db (the surfaces split when the indexer became
    degradable while chain.db stayed fail-stop: docs/storage-robustness.md).
    Pre-split nodes left ``txh/``/``txe/``/``bhe/`` keys in chain.db;
    without this, tx_search/block_search silently lose every pre-split
    height.  Idempotent and crash-resumable: each boot moves whatever
    legacy keys remain (three cheap range probes once drained).  Returns
    the number of rows moved."""
    moved = 0
    for prefix in (_TX_PRIMARY, _TX_EVENT, _BLOCK_EVENT):
        # upper bound: prefix with its last byte incremented — key bodies
        # may contain 0xff (raw tx hashes), so ``prefix + b"\xff"`` would
        # clip the tail of the range
        end = prefix[:-1] + bytes([prefix[-1] + 1])
        # paged stream (snapshot=False) keeps boot memory bounded however
        # large the legacy index; deleting a consumed chunk never
        # disturbs the scan — it only removes keys the cursor is past
        it = chain_db.iterate(prefix, end, snapshot=False)
        while True:
            part = list(islice(it, chunk))
            if not part:
                break
            # copy INTO the index db before deleting from chain.db so a
            # crash between the two leaves duplicates (harmless: same
            # bytes), never lost index entries.  The delete runs under
            # the DEGRADABLE indexer policy even though the file is the
            # fail-stop chain db: the rows are index data, and a failed
            # cleanup must count a drop and resume next boot — not latch
            # the storage-fatal flag on a node that then keeps running
            index_db.write_batch(part, [])
            chain_db.write_batch(
                [], [k for k, _ in part], surface="indexer"
            )
            moved += len(part)
    return moved


@dataclass
class TxResult:
    """Reference: abci/types.TxResult + rpc ResultTx shape."""

    height: int
    index: int
    tx: bytes
    result: at.ExecTxResult

    @property
    def hash(self) -> bytes:
        return tmhash.sum256(self.tx)

    def to_json(self) -> dict:
        return {
            "hash": self.hash.hex().upper(),
            "height": str(self.height),
            "index": self.index,
            "tx_result": {
                "code": self.result.code,
                "data": base64.b64encode(self.result.data).decode(),
                "log": self.result.log,
                "gas_wanted": str(self.result.gas_wanted),
                "gas_used": str(self.result.gas_used),
                "events": [
                    {
                        "type": e.type_,
                        "attributes": [
                            {
                                "key": a.key,
                                "value": a.value,
                                "index": a.index,
                            }
                            for a in e.attributes
                        ],
                    }
                    for e in self.result.events
                ],
                "codespace": self.result.codespace,
            },
            "tx": base64.b64encode(self.tx).decode(),
        }

    def encode(self) -> bytes:
        import json

        ev = [
            {
                "type": e.type_,
                "attributes": [
                    {"key": a.key, "value": a.value, "index": a.index}
                    for a in e.attributes
                ],
            }
            for e in self.result.events
        ]
        doc = {
            "height": self.height,
            "index": self.index,
            "tx": base64.b64encode(self.tx).decode(),
            "result": {
                "code": self.result.code,
                "data": base64.b64encode(self.result.data).decode(),
                "log": self.result.log,
                "gas_wanted": self.result.gas_wanted,
                "gas_used": self.result.gas_used,
                "events": ev,
                "codespace": self.result.codespace,
            },
        }
        return json.dumps(doc, sort_keys=True).encode()

    @staticmethod
    def decode(raw: bytes) -> "TxResult":
        import json

        doc = json.loads(raw.decode())
        r = doc["result"]
        return TxResult(
            height=doc["height"],
            index=doc["index"],
            tx=base64.b64decode(doc["tx"]),
            result=at.ExecTxResult(
                code=r["code"],
                data=base64.b64decode(r["data"]),
                log=r["log"],
                gas_wanted=r["gas_wanted"],
                gas_used=r["gas_used"],
                events=[
                    at.Event(
                        type_=e["type"],
                        attributes=[
                            at.EventAttribute(
                                a["key"], a["value"], a["index"]
                            )
                            for a in e["attributes"]
                        ],
                    )
                    for e in r["events"]
                ],
                codespace=r["codespace"],
            ),
        )


def _event_key(prefix: bytes, tag: str, value: str, height: int, index: int = -1) -> bytes:
    key = (
        prefix
        + tag.encode()
        + b"/"
        + value.encode()
        + b"/"
        + struct.pack(">q", height)
    )
    if index >= 0:
        key += struct.pack(">I", index)
    return key


def _indexed_tags(events) -> list[tuple[str, str]]:
    """(tag, value) pairs for attributes flagged index=True
    (reference: kv.go indexEvents honors the Index flag)."""
    out = []
    for ev in events or []:
        for attr in ev.attributes:
            if attr.index and attr.key:
                out.append((f"{ev.type_}.{attr.key}", attr.value))
    return out


class KVTxIndexer:
    """Reference: state/txindex/kv/kv.go TxIndex."""

    def __init__(self, db):
        self._db = db

    def index(self, height: int, index: int, tx: bytes, result: at.ExecTxResult):
        rec = TxResult(height=height, index=index, tx=tx, result=result)
        h = rec.hash
        sets = [(_TX_PRIMARY + h, rec.encode())]
        # implicit tags
        sets.append(
            (_event_key(_TX_EVENT, TX_HEIGHT_TAG, str(height), height, index), h)
        )
        for tag, value in _indexed_tags(result.events):
            sets.append((_event_key(_TX_EVENT, tag, value, height, index), h))
        self._db.write_batch(sets, [])

    def prune(self, retain_height: int) -> int:
        """Drop all tx index entries below ``retain_height`` (background
        pruner; reference: state/txindex pruning via state/pruner.go).
        Returns the number of transactions un-indexed."""
        event_deletes: list[bytes] = []
        primary_candidates: set[bytes] = set()
        for key, val in self._db.iterate(_TX_EVENT, _TX_EVENT + b"\xff"):
            # key tail: 8-byte big-endian height + 4-byte index
            if len(key) < 12:
                continue
            height = struct.unpack(">q", key[-12:-4])[0]
            if height < retain_height:
                event_deletes.append(key)
                if val:
                    primary_candidates.add(val)
        # Only drop a primary record if its (latest) indexed height is
        # itself below the retain height — the same tx bytes may have been
        # re-committed at a higher height, overwriting the record.
        primary_deletes = []
        for h in sorted(primary_candidates):
            rec = self.get(h)
            if rec is not None and rec.height < retain_height:
                primary_deletes.append(_TX_PRIMARY + h)
        if event_deletes or primary_deletes:
            self._db.write_batch([], event_deletes + primary_deletes)
        return len(primary_deletes)

    def get(self, hash_: bytes) -> Optional[TxResult]:
        raw = self._db.get(_TX_PRIMARY + hash_)
        return TxResult.decode(raw) if raw else None

    def search(self, query: Query) -> list[TxResult]:
        """Intersect per-condition candidate hash sets (reference:
        kv.go Search)."""
        result_set: Optional[set[bytes]] = None
        for cond in query.conditions:
            if cond.tag == TX_HASH_TAG and cond.op == "=":
                h = bytes.fromhex(str(cond.operand))
                cands = {h} if self._db.get(_TX_PRIMARY + h) else set()
            else:
                cands = self._scan_condition(cond)
            result_set = cands if result_set is None else (result_set & cands)
            if not result_set:
                return []
        out = []
        for h in result_set or set():
            rec = self.get(h)
            if rec is not None:
                out.append(rec)
        out.sort(key=lambda r: (r.height, r.index))
        return out

    def _scan_condition(self, cond) -> set[bytes]:
        prefix = _TX_EVENT + cond.tag.encode() + b"/"
        out: set[bytes] = set()
        for key, val in self._db.iterate(prefix, prefix + b"\xff"):
            # key layout: prefix + value + "/" + height(8) + index(4)
            body = key[len(prefix) : -12]
            value = body[:-1].decode(errors="replace")  # strip trailing "/"
            if _match_value(cond, value):
                out.add(bytes(val))
        return out


class KVBlockIndexer:
    """Reference: state/indexer/block/kv/kv.go BlockerIndexer."""

    def __init__(self, db):
        self._db = db

    def index(self, height: int, events) -> None:
        sets = [
            (
                _event_key(_BLOCK_EVENT, BLOCK_HEIGHT_TAG, str(height), height),
                b"",
            )
        ]
        for tag, value in _indexed_tags(events):
            sets.append((_event_key(_BLOCK_EVENT, tag, value, height), b""))
        self._db.write_batch(sets, [])

    def prune(self, retain_height: int) -> int:
        """Drop all block index entries below ``retain_height``."""
        deletes: list[bytes] = []
        for key, _val in self._db.iterate(_BLOCK_EVENT, _BLOCK_EVENT + b"\xff"):
            if len(key) < 8:
                continue
            height = struct.unpack(">q", key[-8:])[0]
            if height < retain_height:
                deletes.append(key)
        if deletes:
            self._db.write_batch([], deletes)
        return len(deletes)

    def search(self, query: Query) -> list[int]:
        result_set: Optional[set[int]] = None
        for cond in query.conditions:
            cands = self._scan_condition(cond)
            result_set = cands if result_set is None else (result_set & cands)
            if not result_set:
                return []
        return sorted(result_set or set())

    def _scan_condition(self, cond) -> set[int]:
        prefix = _BLOCK_EVENT + cond.tag.encode() + b"/"
        out: set[int] = set()
        for key, _val in self._db.iterate(prefix, prefix + b"\xff"):
            body = key[len(prefix) :]
            value = body[:-9].decode(errors="replace")  # strip "/"+height(8)
            height = struct.unpack(">q", body[-8:])[0]
            if _match_value(cond, value):
                out.add(height)
        return out


def _match_value(cond, value: str) -> bool:
    if cond.op == "EXISTS":
        return True
    if cond.op == "=":
        if isinstance(cond.operand, (int, float)):
            try:
                return float(value) == float(cond.operand)
            except ValueError:
                return False
        return value == str(cond.operand)
    if cond.op == "CONTAINS":
        return str(cond.operand) in value
    try:
        fv, fo = float(value), float(cond.operand)
    except (TypeError, ValueError):
        return False
    return (
        (cond.op == "<" and fv < fo)
        or (cond.op == "<=" and fv <= fo)
        or (cond.op == ">" and fv > fo)
        or (cond.op == ">=" and fv >= fo)
    )
