"""PostgreSQL event sink with the reference's exact table layout.

Reference: state/indexer/sink/psql/psql.go + schema.sql — four tables
(blocks, tx_results, events, attributes) and three views
(event_attributes, block_events, tx_events), identical column names and
uniqueness constraints, so external analytics tooling written against the
reference's schema works unchanged.  Insert semantics match: ON CONFLICT
DO NOTHING block dedup, the implicit ``block.height`` / ``tx.hash`` /
``tx.height`` meta-events, only ``index=True`` attributes recorded, and
the stored ``tx_result`` column is the real protobuf wire encoding of
``cometbft.abci.v1.TxResult``.

Beyond the reference (whose sink returns "not supported" for searches and
expects companions to query the DB directly), this sink also *serves*
``tx_search`` / ``block_search`` from the SQL views, so a node configured
with ``indexer = "psql"`` keeps those RPCs working.

Backend drivers: psycopg2 when installed (production PostgreSQL), else a
clearly-labeled sqlite3 emulation used by the test suite — same schema
modulo dialect (BIGSERIAL/TIMESTAMPTZ/BYTEA -> sqlite equivalents); the
SQL the sink issues is identical.
"""

from __future__ import annotations

import random
import threading
from datetime import datetime, timezone
from typing import Optional, Sequence

from cometbft_tpu.indexer.kv import TxResult, _indexed_tags  # noqa: F401

BLOCK_HEIGHT_KEY = "block.height"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"

_SCHEMA_PG = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      BIGSERIAL PRIMARY KEY,
  height     BIGINT NOT NULL,
  chain_id   VARCHAR NOT NULL,
  created_at TIMESTAMPTZ NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid BIGSERIAL PRIMARY KEY,
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  index INTEGER NOT NULL,
  created_at TIMESTAMPTZ NOT NULL,
  tx_hash VARCHAR NOT NULL,
  tx_result BYTEA NOT NULL,
  UNIQUE (block_id, index)
);
CREATE TABLE IF NOT EXISTS events (
  rowid BIGSERIAL PRIMARY KEY,
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_id    BIGINT NULL REFERENCES tx_results(rowid),
  type VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
   event_id      BIGINT NOT NULL REFERENCES events(rowid),
   key           VARCHAR NOT NULL,
   composite_key VARCHAR NOT NULL,
   value         VARCHAR NULL,
   UNIQUE (event_id, key)
);
"""

# sqlite dialect: BIGSERIAL -> INTEGER (alias of rowid), "index" must be
# quoted, BYTEA -> BLOB, TIMESTAMPTZ -> TEXT.  Views are created
# identically in both dialects.
_SCHEMA_SQLITE = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY,
  height     BIGINT NOT NULL,
  chain_id   VARCHAR NOT NULL,
  created_at TEXT NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid INTEGER PRIMARY KEY,
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  "index" INTEGER NOT NULL,
  created_at TEXT NOT NULL,
  tx_hash VARCHAR NOT NULL,
  tx_result BLOB NOT NULL,
  UNIQUE (block_id, "index")
);
CREATE TABLE IF NOT EXISTS events (
  rowid INTEGER PRIMARY KEY,
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_id    BIGINT NULL REFERENCES tx_results(rowid),
  type VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
   event_id      BIGINT NOT NULL REFERENCES events(rowid),
   key           VARCHAR NOT NULL,
   composite_key VARCHAR NOT NULL,
   value         VARCHAR NULL,
   UNIQUE (event_id, key)
);
"""

_VIEWS = """
CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);
CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key, composite_key, value
  FROM blocks JOIN event_attributes ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;
CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, "index", chain_id, type, key, composite_key, value, tx_results.created_at, tx_results.rowid AS tx_rowid
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id)
  WHERE event_attributes.tx_id IS NOT NULL;
"""


def _random_bigserial() -> int:
    return random.getrandbits(62) + 1


class PsqlEventSink:
    """Reference: psql.go EventSink (plus served searches, see module doc)."""

    def __init__(self, conn_str: str, chain_id: str):
        self.chain_id = chain_id
        self._lock = threading.Lock()
        if conn_str.startswith("sqlite://") or conn_str == ":memory:":
            import sqlite3

            path = conn_str.replace("sqlite://", "") or ":memory:"
            self._conn = sqlite3.connect(path, check_same_thread=False)
            self._dialect = "sqlite"
            self._conn.executescript(_SCHEMA_SQLITE + _VIEWS)
        else:
            import psycopg2  # production path; not bundled in test images

            self._conn = psycopg2.connect(conn_str)
            self._dialect = "pg"
            with self._conn, self._conn.cursor() as cur:
                cur.execute(_SCHEMA_PG)
                # postgres has no CREATE VIEW IF NOT EXISTS; use OR REPLACE
                cur.execute(
                    _VIEWS.replace(
                        "CREATE VIEW IF NOT EXISTS", "CREATE OR REPLACE VIEW"
                    )
                )

    # -- SQL helpers --------------------------------------------------------

    def _q(self, sql: str) -> str:
        """Dialect fixups: parameter marker and the reserved ``index``."""
        if self._dialect == "pg":
            return sql.replace("?", "%s")
        return sql

    def _exec(self, sql: str, params: Sequence = ()):  # -> cursor
        cur = self._conn.cursor()
        cur.execute(self._q(sql), tuple(params))
        return cur

    def _commit(self) -> None:
        self._conn.commit()

    # -- indexing (reference: IndexBlockEvents / IndexTxEvents) -------------

    def index_block_events(self, height: int, events) -> None:
        ts = datetime.now(timezone.utc).isoformat()
        with self._lock:
            try:
                cur = self._exec(
                    'SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?',
                    (height, self.chain_id),
                )
                if cur.fetchone() is not None:
                    return  # already indexed; quietly succeed (reference :204)
                block_id = _random_bigserial()
                self._exec(
                    "INSERT INTO blocks (rowid, height, chain_id, created_at)"
                    " VALUES (?, ?, ?, ?)",
                    (block_id, height, self.chain_id, ts),
                )
                self._insert_events(
                    block_id,
                    None,
                    self._with_meta_events(
                        [(BLOCK_HEIGHT_KEY, str(height))], events
                    ),
                )
                self._commit()
            except Exception:
                self._conn.rollback()
                raise

    def index_tx_events(self, txrs: Sequence[TxResult]) -> None:
        ts = datetime.now(timezone.utc).isoformat()
        with self._lock:
            try:
                self._index_tx_events_locked(txrs, ts)
            except Exception:
                # never leave partial inserts in the open transaction for
                # a later unrelated commit to pick up
                self._conn.rollback()
                raise

    def _index_tx_events_locked(self, txrs: Sequence[TxResult], ts) -> None:
        for txr in txrs:
            cur = self._exec(
                "SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?",
                (txr.height, self.chain_id),
            )
            row = cur.fetchone()
            if row is None:
                raise LookupError(
                    f"block {txr.height} not indexed before its txs"
                )
            block_id = row[0]
            cur = self._exec(
                'SELECT 1 FROM tx_results WHERE block_id = ? AND "index" = ?',
                (block_id, txr.index),
            )
            if cur.fetchone() is not None:
                continue  # already indexed
            tx_hash = txr.hash.hex().upper()
            tx_id = _random_bigserial()
            self._exec(
                "INSERT INTO tx_results "
                '(rowid, block_id, "index", created_at, tx_hash, tx_result)'
                " VALUES (?, ?, ?, ?, ?, ?)",
                (
                    tx_id,
                    block_id,
                    txr.index,
                    ts,
                    tx_hash,
                    self._wire_tx_result(txr),
                ),
            )
            self._insert_events(
                block_id,
                tx_id,
                self._with_meta_events(
                    [
                        (TX_HASH_KEY, tx_hash),
                        (TX_HEIGHT_KEY, str(txr.height)),
                    ],
                    txr.result.events,
                ),
            )
        self._commit()

    @staticmethod
    def _wire_tx_result(txr: TxResult) -> bytes:
        """Protobuf wire encoding of cometbft.abci.v1.TxResult (the
        reference stores exactly this in the tx_result column)."""
        import cometbft_tpu.proto_gen  # noqa: F401

        from cometbft.abci.v1 import types_pb2 as abci_pb

        from cometbft_tpu.rpc.pb_convert import exec_tx_result_pb

        msg = abci_pb.TxResult(
            height=txr.height, index=txr.index, tx=txr.tx
        )
        msg.result.CopyFrom(exec_tx_result_pb(txr.result))
        return msg.SerializeToString()

    @staticmethod
    def _with_meta_events(meta: list[tuple[str, str]], events):
        """Prepend the implicit meta-events (reference: makeIndexedEvent)."""
        from cometbft_tpu.abci import types as at

        out = []
        for composite, value in meta:
            typ, _, key = composite.partition(".")
            out.append(
                at.Event(
                    type_=typ,
                    attributes=[
                        at.EventAttribute(key=key, value=value, index=True)
                    ],
                )
            )
        return out + list(events or [])

    def _insert_events(self, block_id: int, tx_id, events) -> None:
        for ev in events:
            if not ev.type_:
                continue  # reference skips empty-type events
            event_id = _random_bigserial()
            self._exec(
                "INSERT INTO events (rowid, block_id, tx_id, type)"
                " VALUES (?, ?, ?, ?)",
                (event_id, block_id, tx_id, ev.type_),
            )
            for attr in ev.attributes:
                if not attr.index:
                    continue  # only indexable attributes (reference :165)
                self._exec(
                    "INSERT INTO attributes "
                    "(event_id, key, composite_key, value) VALUES (?, ?, ?, ?)",
                    (
                        event_id,
                        attr.key,
                        f"{ev.type_}.{attr.key}",
                        attr.value,
                    ),
                )

    # -- serving searches (beyond the reference's sink) ---------------------

    def has_block(self, height: int) -> bool:
        with self._lock:
            cur = self._exec(
                "SELECT 1 FROM blocks WHERE height = ? AND chain_id = ?",
                (height, self.chain_id),
            )
            return cur.fetchone() is not None

    def get_tx_by_hash(self, hash_: bytes) -> Optional[TxResult]:
        with self._lock:
            cur = self._exec(
                "SELECT tx_result FROM tx_results WHERE tx_hash = ?",
                (hash_.hex().upper(),),
            )
            row = cur.fetchone()
        return self._decode_tx_result(row[0]) if row else None

    @staticmethod
    def _decode_tx_result(raw: bytes) -> TxResult:
        import cometbft_tpu.proto_gen  # noqa: F401

        from cometbft.abci.v1 import types_pb2 as abci_pb

        from cometbft_tpu.abci import types as at

        msg = abci_pb.TxResult.FromString(bytes(raw))
        events = [
            at.Event(
                type_=e.type,
                attributes=[
                    at.EventAttribute(key=a.key, value=a.value, index=a.index)
                    for a in e.attributes
                ],
            )
            for e in msg.result.events
        ]
        return TxResult(
            height=msg.height,
            index=msg.index,
            tx=msg.tx,
            result=at.ExecTxResult(
                code=msg.result.code,
                data=msg.result.data,
                log=msg.result.log,
                info=msg.result.info,
                gas_wanted=msg.result.gas_wanted,
                gas_used=msg.result.gas_used,
                events=events,
                codespace=msg.result.codespace,
            ),
        )

    def _condition_sql(self, cond, view: str, id_col: str):
        """One query condition -> (sql, params) yielding matching ids."""
        base = f"SELECT DISTINCT {id_col} FROM {view} WHERE composite_key = ?"
        params: list = [cond.tag]
        op = cond.op
        operand = cond.operand
        if op == "EXISTS":
            return base, params
        if op == "CONTAINS":
            # literal-substring semantics (kv indexer parity): escape LIKE
            # wildcards in the operand
            esc = (
                str(operand)
                .replace("\\", "\\\\")
                .replace("%", "\\%")
                .replace("_", "\\_")
            )
            return (
                base + " AND value LIKE ? ESCAPE '\\'",
                params + [f"%{esc}%"],
            )
        if isinstance(operand, (int, float)):
            cast = (
                "CAST(value AS NUMERIC)"
                if self._dialect == "pg"
                else "CAST(value AS REAL)"
            )
            return base + f" AND {cast} {op} ?", params + [operand]
        if op == "=":
            return base + " AND value = ?", params + [str(operand)]
        return base + f" AND value {op} ?", params + [str(operand)]

    def search_block_events(self, query) -> list[int]:
        """block_search served from the sink's SQL views; returns heights."""
        result: Optional[set[int]] = None
        with self._lock:
            for cond in query.conditions:
                sql, params = self._condition_sql(cond, "block_events", "height")
                rows = {r[0] for r in self._exec(sql, params).fetchall()}
                result = rows if result is None else (result & rows)
                if not result:
                    return []
        return sorted(result or set())

    def search_tx_events(self, query) -> list[TxResult]:
        """tx_search served from the sink's SQL views."""
        result: Optional[set[int]] = None
        with self._lock:
            for cond in query.conditions:
                sql, params = self._condition_sql(cond, "tx_events", "tx_rowid")
                rows = {r[0] for r in self._exec(sql, params).fetchall()}
                result = rows if result is None else (result & rows)
                if not result:
                    return []
            out = []
            for rowid in sorted(result or set()):
                cur = self._exec(
                    "SELECT tx_result FROM tx_results WHERE rowid = ?",
                    (rowid,),
                )
                row = cur.fetchone()
                if row:
                    out.append(self._decode_tx_result(row[0]))
        return out

    # -- adapters: the IndexerService drives kv-style index() calls ---------

    def stop(self) -> None:
        self._conn.close()


class PsqlTxIndexerAdapter:
    """kv-indexer-shaped facade over the sink (IndexerService + rpc)."""

    def __init__(self, sink: PsqlEventSink):
        self.sink = sink

    def index(self, height, index, tx, result) -> None:
        self.sink.index_tx_events(
            [TxResult(height=height, index=index, tx=tx, result=result)]
        )

    def index_batch(self, batch) -> None:
        """One sink call (one block SELECT + one commit) for a whole
        drain of tx events — the shape IndexTxEvents is built for."""
        self.sink.index_tx_events(
            [
                TxResult(height=d.height, index=d.index, tx=d.tx,
                         result=d.result)
                for d in batch
            ]
        )

    def get(self, hash_: bytes):
        return self.sink.get_tx_by_hash(hash_)

    def search(self, query):
        return self.sink.search_tx_events(query)

    def prune(self, retain_height: int) -> int:
        # Reference leaves psql pruning to the operator/companion (the
        # sink is append-only analytics storage).
        return 0


class PsqlBlockIndexerAdapter:
    """kv-block-indexer-shaped facade over the sink."""

    def __init__(self, sink: PsqlEventSink):
        self.sink = sink

    def index(self, height, events) -> None:
        self.sink.index_block_events(height, events)

    def search(self, query):
        return self.sink.search_block_events(query)

    def prune(self, retain_height: int) -> int:
        return 0
