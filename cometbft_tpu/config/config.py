"""Node configuration: one TOML file, sectioned structs.

Mirrors the reference's mega-Config (config/config.go:93-1567) — Base/RPC/
GRPC/P2P/Mempool/StateSync/BlockSync/Consensus/Storage/TxIndex/
Instrumentation — plus a `crypto` section that is new here: it selects the
batch-verification backend (cpu | tpu | auto), the pluggable seam the whole
TPU build hangs off (SURVEY.md §7 step 2).

TOML is read with stdlib tomllib; writing uses a small emitter (the config
surface is flat sections of scalars/lists, which TOML expresses exactly).
"""

from __future__ import annotations

import os
try:
    import tomllib
except ImportError:  # Python < 3.11: tomli is API-compatible
    import tomli as tomllib
from dataclasses import dataclass, field, fields as dc_fields, is_dataclass, asdict
from typing import Optional


def _home(*parts: str) -> str:
    return os.path.join(*parts)


@dataclass
class BaseConfig:
    """Reference: config/config.go BaseConfig."""

    chain_id: str = ""
    home: str = ""
    moniker: str = "anonymous"
    db_backend: str = "sqlite"  # sqlite (embedded default) | memdb
    db_dir: str = "data"
    log_level: str = "info"
    log_format: str = "plain"  # plain | json
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    priv_validator_laddr: str = ""  # remote signer listen address
    node_key_file: str = "config/node_key.json"
    abci: str = "builtin"  # builtin | socket | grpc
    proxy_app: str = "kvstore"  # app name (builtin) or address (socket/grpc)
    filter_peers: bool = False

    def genesis_path(self) -> str:
        return _home(self.home, self.genesis_file)

    def priv_validator_key_path(self) -> str:
        return _home(self.home, self.priv_validator_key_file)

    def priv_validator_state_path(self) -> str:
        return _home(self.home, self.priv_validator_state_file)

    def node_key_path(self) -> str:
        return _home(self.home, self.node_key_file)

    def db_path(self) -> str:
        return _home(self.home, self.db_dir)

    def validate_basic(self) -> Optional[str]:
        if self.log_format not in ("plain", "json"):
            return "unknown log_format (must be 'plain' or 'json')"
        if self.abci not in ("builtin", "socket", "grpc"):
            return "unknown abci mode"
        return None


@dataclass
class RPCConfig:
    """Reference: config/config.go RPCConfig."""

    laddr: str = "tcp://127.0.0.1:26657"
    cors_allowed_origins: list[str] = field(default_factory=list)
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    max_subscriptions_per_client: int = 5
    timeout_broadcast_tx_commit_ms: int = 10_000
    max_request_batch_size: int = 10
    max_body_bytes: int = 1_000_000
    pprof_laddr: str = ""
    # serve the dial_seeds/dial_peers/unsafe_flush_mempool routes
    # (reference config.go RPCConfig.Unsafe + routes.go AddUnsafeRoutes)
    unsafe: bool = False

    def validate_basic(self) -> Optional[str]:
        if self.max_open_connections < 0:
            return "max_open_connections cannot be negative"
        if self.timeout_broadcast_tx_commit_ms < 0:
            return "timeout_broadcast_tx_commit_ms cannot be negative"
        return None


@dataclass
class GRPCConfig:
    """Reference: config/config.go GRPCConfig (versioned services)."""

    laddr: str = ""  # empty = disabled
    block_service_enabled: bool = True
    block_results_service_enabled: bool = True
    version_service_enabled: bool = True
    privileged_laddr: str = ""
    pruning_service_enabled: bool = False

    def validate_basic(self) -> Optional[str]:
        return None


@dataclass
class P2PConfig:
    """Reference: config/config.go P2PConfig."""

    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: list[str] = field(default_factory=list)
    persistent_peers: list[str] = field(default_factory=list)
    addr_book_file: str = "config/addrbook.json"
    addr_book_strict: bool = True
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    unconditional_peer_ids: list[str] = field(default_factory=list)
    persistent_peers_max_dial_period_s: int = 0
    flush_throttle_timeout_ms: int = 10
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    pex: bool = True
    seed_mode: bool = False
    private_peer_ids: list[str] = field(default_factory=list)
    allow_duplicate_ip: bool = False
    handshake_timeout_s: int = 20
    dial_timeout_s: int = 3
    # WAN latency emulation (testnets): this node's zone, the zone-pair RTT
    # matrix (ms), and each peer id's zone.  Empty zone = no emulation.
    # Reference analog: test/e2e/pkg/latency/ zone tables applied via tc.
    zone: str = ""
    zone_rtt_ms: dict = field(default_factory=dict)
    peer_zones: dict = field(default_factory=dict)

    def validate_basic(self) -> Optional[str]:
        if self.max_packet_msg_payload_size <= 0:
            return "max_packet_msg_payload_size must be positive"
        if self.send_rate < 0 or self.recv_rate < 0:
            return "send_rate/recv_rate cannot be negative"
        for a, row in (self.zone_rtt_ms or {}).items():
            if not isinstance(row, dict):
                return f"zone_rtt_ms[{a!r}] must be a table of rtt values"
            for b, v in row.items():
                if not isinstance(v, (int, float)) or v < 0:
                    return f"zone_rtt_ms[{a!r}][{b!r}] must be a nonneg number"
        return None


@dataclass
class MempoolConfig:
    """Reference: config/config.go MempoolConfig."""

    type_: str = "flood"  # flood | nop
    recheck: bool = True
    recheck_timeout_ms: int = 1000
    broadcast: bool = True
    size: int = 5000
    max_txs_bytes: int = 1_073_741_824
    cache_size: int = 10_000
    keep_invalid_txs_in_cache: bool = False
    max_tx_bytes: int = 1_048_576

    def validate_basic(self) -> Optional[str]:
        if self.type_ not in ("flood", "nop"):
            return "unknown mempool type"
        if self.size < 0 or self.cache_size < 0:
            return "mempool size/cache_size cannot be negative"
        return None


@dataclass
class StateSyncConfig:
    """Reference: config/config.go StateSyncConfig."""

    enable: bool = False
    rpc_servers: list[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_s: int = 168 * 3600
    discovery_time_s: int = 15
    temp_dir: str = ""
    chunk_request_timeout_s: int = 10
    chunk_fetchers: int = 4

    def validate_basic(self) -> Optional[str]:
        if self.enable:
            if len(self.rpc_servers) < 2:
                return "state sync requires >=2 rpc_servers"
            if self.trust_height <= 0:
                return "state sync requires trust_height > 0"
            if not self.trust_hash:
                return "state sync requires trust_hash"
        return None


@dataclass
class BlockSyncConfig:
    """Reference: config/config.go BlockSyncConfig."""

    version: str = "v0"

    def validate_basic(self) -> Optional[str]:
        if self.version != "v0":
            return "unknown blocksync version"
        return None


@dataclass
class ConsensusConfig:
    """Reference: config/config.go ConsensusConfig (timeouts in ms)."""

    wal_file: str = "data/cs.wal/wal"
    timeout_propose_ms: int = 3000
    timeout_propose_delta_ms: int = 500
    timeout_vote_ms: int = 1000
    timeout_vote_delta_ms: int = 500
    timeout_commit_ms: int = 1000
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval_ms: int = 0
    peer_gossip_sleep_duration_ms: int = 100
    peer_query_maj23_sleep_duration_ms: int = 2000
    double_sign_check_height: int = 0

    def propose_timeout(self, round_: int) -> float:
        return (
            self.timeout_propose_ms + self.timeout_propose_delta_ms * round_
        ) / 1000.0

    def vote_timeout(self, round_: int) -> float:
        return (self.timeout_vote_ms + self.timeout_vote_delta_ms * round_) / 1000.0

    def commit_timeout(self) -> float:
        return self.timeout_commit_ms / 1000.0

    def validate_basic(self) -> Optional[str]:
        for name in (
            "timeout_propose_ms",
            "timeout_propose_delta_ms",
            "timeout_vote_ms",
            "timeout_vote_delta_ms",
            "timeout_commit_ms",
        ):
            if getattr(self, name) < 0:
                return f"{name} cannot be negative"
        return None


@dataclass
class StorageConfig:
    """Reference: config/config.go StorageConfig."""

    discard_abci_responses: bool = False
    pruning_interval_s: int = 10
    compact: bool = False
    compaction_interval: int = 1000

    def validate_basic(self) -> Optional[str]:
        return None


@dataclass
class TxIndexConfig:
    """Reference: config/config.go TxIndexConfig."""

    indexer: str = "kv"  # kv | null | psql
    psql_conn: str = ""

    def validate_basic(self) -> Optional[str]:
        if self.indexer not in ("kv", "null", "psql"):
            return "unknown indexer"
        if self.indexer == "psql" and not self.psql_conn:
            return "the psql connection settings cannot be empty"
        return None


@dataclass
class InstrumentationConfig:
    """Reference: config/config.go InstrumentationConfig."""

    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    max_open_connections: int = 3
    namespace: str = "cometbft"

    def validate_basic(self) -> Optional[str]:
        return None


@dataclass
class CryptoConfig:
    """TPU-build specific: selects the batch-verification backend behind the
    crypto/batch seam (SURVEY.md §7 design stance)."""

    backend: str = "auto"  # auto | cpu | tpu
    min_batch_size: int = 2
    mesh_shard_threshold: int = 4096  # shard batches larger than this over the mesh

    def validate_basic(self) -> Optional[str]:
        if self.backend not in ("auto", "cpu", "tpu"):
            return "crypto backend must be auto|cpu|tpu"
        return None


_SECTIONS = {
    "rpc": RPCConfig,
    "grpc": GRPCConfig,
    "p2p": P2PConfig,
    "mempool": MempoolConfig,
    "statesync": StateSyncConfig,
    "blocksync": BlockSyncConfig,
    "consensus": ConsensusConfig,
    "storage": StorageConfig,
    "tx_index": TxIndexConfig,
    "instrumentation": InstrumentationConfig,
    "crypto": CryptoConfig,
}


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    grpc: GRPCConfig = field(default_factory=GRPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)
    crypto: CryptoConfig = field(default_factory=CryptoConfig)

    def set_home(self, home: str) -> "Config":
        self.base.home = home
        return self

    def wal_path(self) -> str:
        return _home(self.base.home, self.consensus.wal_file)

    def addr_book_path(self) -> str:
        return _home(self.base.home, self.p2p.addr_book_file)

    def validate_basic(self) -> Optional[str]:
        err = self.base.validate_basic()
        if err:
            return f"base: {err}"
        for name, _ in _SECTIONS.items():
            err = getattr(self, name).validate_basic()
            if err:
                return f"{name}: {err}"
        return None


def default_config() -> Config:
    return Config()


def test_config(home: str = "") -> Config:
    """Fast timeouts for tests (reference: config.TestConfig)."""
    cfg = Config()
    cfg.base.home = home
    cfg.base.chain_id = "test-chain"
    cfg.base.db_backend = "memdb"
    cfg.consensus.timeout_propose_ms = 400
    cfg.consensus.timeout_propose_delta_ms = 100
    cfg.consensus.timeout_vote_ms = 100
    cfg.consensus.timeout_vote_delta_ms = 50
    cfg.consensus.timeout_commit_ms = 20
    cfg.consensus.peer_gossip_sleep_duration_ms = 5
    cfg.crypto.backend = "cpu"
    return cfg


# ---------------------------------------------------------------------------
# TOML round-trip
# ---------------------------------------------------------------------------

def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        s = v.replace("\\", "\\\\").replace('"', '\\"')
        s = "".join(
            c if ord(c) >= 0x20 and c != "\x7f" else f"\\u{ord(c):04x}"
            for c in s
        )
        return '"' + s + '"'
    if isinstance(v, list):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    if isinstance(v, dict):
        # inline table; keys always quoted (zone names, node ids)
        return (
            "{"
            + ", ".join(
                f"{_toml_value(str(k))} = {_toml_value(x)}"
                for k, x in v.items()
            )
            + "}"
        )
    raise TypeError(f"unsupported TOML value: {type(v)}")


def _emit_section(name: str, obj) -> str:
    lines = [f"[{name}]"] if name else []
    for f in dc_fields(obj):
        key = f.name.rstrip("_")
        lines.append(f"{key} = {_toml_value(getattr(obj, f.name))}")
    return "\n".join(lines) + "\n"


def dumps(cfg: Config) -> str:
    out = ["# cometbft_tpu node configuration\n"]
    base = _emit_section("", cfg.base)
    # home is a runtime path, not persisted
    base = "\n".join(
        l for l in base.splitlines() if not l.startswith("home = ")
    )
    out.append(base + "\n")
    for name in _SECTIONS:
        out.append("\n" + _emit_section(name, getattr(cfg, name)))
    return "".join(out)


def _fill(obj, doc: dict):
    for f in dc_fields(obj):
        key = f.name.rstrip("_")
        if key in doc:
            setattr(obj, f.name, doc[key])
    return obj


def loads(text: str) -> Config:
    doc = tomllib.loads(text)
    cfg = Config()
    _fill(cfg.base, {k: v for k, v in doc.items() if not isinstance(v, dict)})
    for name in _SECTIONS:
        if name in doc:
            _fill(getattr(cfg, name), doc[name])
    return cfg


def write_config(cfg: Config, path: Optional[str] = None) -> None:
    path = path or _home(cfg.base.home, "config", "config.toml")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write(dumps(cfg))


def load_config(home: str) -> Config:
    path = _home(home, "config", "config.toml")
    with open(path) as f:
        cfg = loads(f.read())
    cfg.base.home = home
    return cfg
