from cometbft_tpu.config.config import (
    Config,
    default_config,
    test_config,
    load_config,
    write_config,
    dumps,
    loads,
)
