"""Confix: migrate a config file across framework versions.

Reference: internal/confix — loads an existing config.toml (any vintage),
carries every recognized key into a freshly rendered current template,
reports unknown keys, and backs up the original.
"""

from __future__ import annotations

import os
import shutil
from dataclasses import fields, is_dataclass

from cometbft_tpu.config import config as cfgmod


def upgrade(home: str, dry_run: bool = False) -> dict:
    """Upgrade <home>/config/config.toml in place.  Returns a report:
    {carried: [...], unknown: [...], backup: path|None}."""
    path = os.path.join(home, "config", "config.toml")
    if not os.path.exists(path):
        raise FileNotFoundError(path)

    old = cfgmod.load_config(home)  # tolerant: unknown keys are dropped
    current = cfgmod.default_config()

    carried: list[str] = []
    # top-level (base) fields first, then each section
    default_base = cfgmod.BaseConfig()
    for f in fields(cfgmod.BaseConfig):
        old_val = getattr(old.base, f.name)
        if f.name != "home" and old_val != getattr(default_base, f.name):
            setattr(current.base, f.name, old_val)
            carried.append(f.name)
    # copy every known field that differs from the default
    for section_name, section_cls in cfgmod._SECTIONS.items():
        old_sec = getattr(old, section_name)
        new_sec = getattr(current, section_name)
        default_sec = section_cls()
        if not is_dataclass(old_sec):
            continue
        for f in fields(section_cls):
            old_val = getattr(old_sec, f.name)
            if old_val != getattr(default_sec, f.name):
                setattr(new_sec, f.name, old_val)
                carried.append(f"{section_name}.{f.name}")

    unknown = _unknown_keys(path)
    report = {"carried": carried, "unknown": unknown, "backup": None}
    if dry_run:
        return report

    backup = path + ".bak"
    shutil.copyfile(path, backup)
    report["backup"] = backup
    cfgmod.write_config(
        current, os.path.join(home, "config", "config.toml")
    )
    return report


def _unknown_keys(path: str) -> list[str]:
    """TOML keys in the file that the current schema doesn't know."""
    known: dict[str, set[str]] = {
        name: {f.name for f in fields(cls)}
        for name, cls in cfgmod._SECTIONS.items()
    }
    known[""] = {f.name for f in fields(cfgmod.BaseConfig)}
    unknown = []
    section = ""
    with open(path) as fobj:
        for line in fobj:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if line.startswith("[") and line.endswith("]"):
                section = line[1:-1]
                continue
            if "=" in line:
                key = line.split("=", 1)[0].strip()
                sec_known = known.get(section)
                # TOML drops the trailing underscore of keyword-collision
                # fields (type_ -> type)
                if sec_known is not None and not (
                    key in sec_known or key + "_" in sec_known
                ):
                    unknown.append(f"{section + '.' if section else ''}{key}")
    return unknown
