"""Continuous-batching async verification service (docs/verify-scheduler.md).

Callers submit signature checks with a priority class and get futures; a
dispatcher thread coalesces pending items across ALL submitters into one
fused ``ops/verify.verify_segments`` dispatch under the supervisor chain.
``COMETBFT_TPU_VERIFY_SCHED=0`` kills the scheduler and restores the
synchronous per-caller paths bit-for-bit.
"""

from cometbft_tpu.verifysched.service import (  # noqa: F401
    DEFAULT_FLUSH_US,
    DEFAULT_QUEUE_CAP,
    PRIO_BLOCKSYNC,
    PRIO_CONSENSUS,
    PRIO_EVIDENCE,
    PRIO_LIGHT,
    PRIO_MEMPOOL,
    QueueFullError,
    VerifyScheduler,
    backend_trusted,
    current_priority,
    enabled,
    get_scheduler,
    priority_class,
    reset_scheduler,
    scheduler_active,
    verify_cached,
    verify_many_cached,
    verify_now,
    verify_segment_sync,
)
from cometbft_tpu.verifysched import stats  # noqa: F401
