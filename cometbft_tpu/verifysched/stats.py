"""Process-wide counters for the verification scheduler.

Deliberately free of jax imports, exactly like ``ops/dispatch_stats``:
``libs/metrics.NodeMetrics`` reads these through callback gauges and a
/metrics scrape must never be the thing that initializes an accelerator
backend.  ``verifysched/service.py`` writes them (and computes the padded
lane count at flush time, where ``ops.verify`` is already imported, so this
module never has to).

Counters (all guarded by one lock):
  * ``submitted[class]``     — items admitted to the queue, per priority class
  * ``submit_hits[class]``   — submissions resolved from the sigcache without
    ever occupying a queue slot
  * ``shed[class]``          — submissions rejected by admission control
    (never ``consensus``: that class is exempt from shedding by design)
  * ``queue_depth``          — items currently pending (gauge-style)
  * ``flushes[reason]``      — dispatcher flushes by trigger:
    ``deadline`` / ``full`` / ``shutdown``
  * ``flush_items``          — items drained across all flushes
  * ``flush_misses``         — unique cache-missing items shipped to the
    verify seam (<= flush_items: duplicates and fresh cache hits resolve
    on the host)
  * ``flush_lanes``          — bucket-padded device lanes those misses
    occupied (occupancy = flush_misses / flush_lanes)
  * ``dedup_hits``           — duplicate in-flight triples collapsed into a
    single lane at flush time (concurrent gossip of the same vote)
  * ``verdicts[class]`` / ``latency_seconds[class]`` — resolved futures and
    cumulative submit->verdict latency, per priority class

Hot-path latency HISTOGRAMS (docs/observability.md) — real distributions,
not just cumulative sums, rendered on /metrics as histogram series:

  * ``latency_hist[class]``    — submit->verdict, per priority class
    (includes ``record_shed_fallback`` samples: a shed caller's sync
    verify stays in the latency record instead of vanishing from it)
  * ``queue_wait_hist[class]`` — submit->drain wait, per class (recorded
    SEPARATELY from device time: queue pressure and device slowness are
    different regressions)
  * ``device_hist[class]``     — drain->verdict (flush execution) share
  * ``flush_interval_hist``    — time between consecutive flush starts
  * ``shed_fallback[class]``   — sync fallbacks that recorded a sample
"""

from __future__ import annotations

import threading

from cometbft_tpu.libs.histo import Histo

CLASS_NAMES = ("consensus", "evidence_light", "bulk")
FLUSH_REASONS = ("deadline", "full", "shutdown")

_LOCK = threading.Lock()


def _zero() -> dict:
    return {
        "submitted": {c: 0 for c in CLASS_NAMES},
        "submit_hits": {c: 0 for c in CLASS_NAMES},
        "shed": {c: 0 for c in CLASS_NAMES},
        "queue_depth": 0,
        "flushes": {r: 0 for r in FLUSH_REASONS},
        "flush_items": 0,
        "flush_misses": 0,
        "flush_lanes": 0,
        "dedup_hits": 0,
        "verdicts": {c: 0 for c in CLASS_NAMES},
        "latency_seconds": {c: 0.0 for c in CLASS_NAMES},
        "queue_wait_seconds": {c: 0.0 for c in CLASS_NAMES},
        "shed_fallback": {c: 0 for c in CLASS_NAMES},
        "latency_hist": {c: Histo() for c in CLASS_NAMES},
        "queue_wait_hist": {c: Histo() for c in CLASS_NAMES},
        "device_hist": {c: Histo() for c in CLASS_NAMES},
        "flush_interval_hist": Histo(),
        # in-flight pipeline (docs/verify-scheduler.md): flushes currently
        # dispatched but not yet fetched, and the high-water mark
        "inflight_depth": 0,
        "inflight_hwm": 0,
    }


_STATS = _zero()


def _cls(priority: int) -> str:
    return CLASS_NAMES[min(max(int(priority), 0), len(CLASS_NAMES) - 1)]


def record_submit(priority: int) -> None:
    with _LOCK:
        _STATS["submitted"][_cls(priority)] += 1
        _STATS["queue_depth"] += 1


def record_submit_hit(priority: int) -> None:
    with _LOCK:
        _STATS["submit_hits"][_cls(priority)] += 1


def record_shed(priority: int) -> None:
    with _LOCK:
        _STATS["shed"][_cls(priority)] += 1


def record_flush(
    reason: str,
    items: int,
    misses: int,
    lanes: int,
    interval_s: "float | None" = None,
) -> None:
    with _LOCK:
        _STATS["flushes"][reason] = _STATS["flushes"].get(reason, 0) + 1
        _STATS["flush_items"] += int(items)
        _STATS["flush_misses"] += int(misses)
        _STATS["flush_lanes"] += int(lanes)
        _STATS["queue_depth"] = max(0, _STATS["queue_depth"] - int(items))
        if interval_s is not None:
            _STATS["flush_interval_hist"].observe(float(interval_s))


def record_inflight(depth: int) -> None:
    """Current number of dispatched-but-unfetched flushes — written by the
    dispatcher at dispatch and by the completion pool at fetch, rendered
    as the ``cometbft_sched_inflight_depth`` gauge."""
    with _LOCK:
        _STATS["inflight_depth"] = int(depth)
        if depth > _STATS["inflight_hwm"]:
            _STATS["inflight_hwm"] = int(depth)


def record_dedup(n: int) -> None:
    if n:
        with _LOCK:
            _STATS["dedup_hits"] += int(n)


def record_verdict(
    priority: int,
    latency_s: float,
    queue_wait_s: "float | None" = None,
    device_s: "float | None" = None,
) -> None:
    """One resolved future.  ``queue_wait_s`` (submit->drain) and
    ``device_s`` (drain->verdict) are recorded as SEPARATE distributions
    when the dispatcher knows them — a latency regression then names the
    guilty half instead of hiding in the conflated total."""
    with _LOCK:
        c = _cls(priority)
        _STATS["verdicts"][c] += 1
        _STATS["latency_seconds"][c] += float(latency_s)
        _STATS["latency_hist"][c].observe(float(latency_s))
        if queue_wait_s is not None:
            _STATS["queue_wait_seconds"][c] += float(queue_wait_s)
            _STATS["queue_wait_hist"][c].observe(float(queue_wait_s))
        if device_s is not None:
            _STATS["device_hist"][c].observe(float(device_s))


def record_shed_fallback(priority: int, latency_s: float) -> None:
    """A shed (or scheduler-inactive-mid-teardown) caller finished its
    synchronous fallback verify: the sample lands in the SAME
    submit->verdict latency record as scheduled work, so shedding can
    never silently improve the histogram it degraded."""
    with _LOCK:
        c = _cls(priority)
        _STATS["shed_fallback"][c] += 1
        _STATS["latency_seconds"][c] += float(latency_s)
        _STATS["latency_hist"][c].observe(float(latency_s))


def queue_depth() -> int:
    with _LOCK:
        return _STATS["queue_depth"]


def _copy(v):
    if isinstance(v, Histo):
        return v.to_dict()
    if isinstance(v, dict):
        return {k: _copy(x) for k, x in v.items()}
    return v


def snapshot() -> dict:
    """Deep-enough copy for metrics/tests; adds derived aggregates.
    Histograms render as their ``Histo.to_dict`` wire shape."""
    with _LOCK:
        out = {k: _copy(v) for k, v in _STATS.items()}
    out["flush_occupancy"] = (
        out["flush_misses"] / out["flush_lanes"] if out["flush_lanes"] else 0.0
    )
    out["verdicts_total"] = sum(out["verdicts"].values())
    out["latency_seconds_total"] = sum(out["latency_seconds"].values())
    out["shed_total"] = sum(out["shed"].values())
    out["shed_fallback_total"] = sum(out["shed_fallback"].values())
    return out


def reset() -> None:
    global _STATS
    with _LOCK:
        _STATS = _zero()
