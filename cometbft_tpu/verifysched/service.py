"""Continuous-batching asynchronous verification service.

The device kernel only earns its keep when batches are full, yet every
caller on the verify hot path historically assembled its *own* batch and
blocked on its *own* dispatch: gossip-time ``Vote.verify`` paid a
one-signature dispatch (or the host fallback), evidence checks verified
per vote, and concurrent submitters each ate the per-dispatch floor.  This
module is the missing shared engine — the continuous-batching scheduler
shape from inference serving applied to signature verification
(docs/verify-scheduler.md):

  * callers ``submit(pub, msg, sig, priority)`` and get back a Future (or
    bridge whole segments via ``verify_segment_sync``);
  * one dispatcher thread coalesces pending items ACROSS all submitters
    into a single ``ops/verify.verify_segments`` dispatch, flushing when
    the oldest item has waited ``COMETBFT_TPU_SCHED_FLUSH_US`` (~2000) or
    when a padding bucket fills (at which point the dispatch carries zero
    padding waste);
  * the sigcache is consulted before any queue slot or device lane is
    occupied, and duplicate in-flight triples (the same vote gossiped by
    two peers at once) collapse into one lane;
  * everything below the flush runs under the existing ``ops/supervisor``
    chain, so futures ALWAYS complete with definitive verdicts — an
    infrastructure failure degrades pallas -> xla -> host, never becomes a
    False accept bit (tests/test_verifysched.py pins this with
    ``FaultyBackend``).

Priority classes and admission control: ``consensus`` (vote/proposal/
extension checks) > ``evidence_light`` (evidence, light client) > ``bulk``
(blocksync, mempool).  The queue is bounded (``COMETBFT_TPU_SCHED_QUEUE``,
default 8192); overload sheds ONLY non-consensus classes — a shed caller
falls back to its own synchronous verify (it loses the batching win, never
the verdict) — while consensus submissions are always admitted: consensus
traffic is bounded by validator count x rounds, and blocking or dropping a
vote is a liveness hazard no queue bound justifies.

Activation: the scheduler takes the verify path only when
``COMETBFT_TPU_VERIFY_SCHED`` != 0 (default on) AND the accelerator batch
backend is trusted (``crypto.batch.default_backend() == "tpu"`` — the same
gate the fused stream uses).  Otherwise every wrapper here falls through
to the exact pre-scheduler code path, so the kill switch
``COMETBFT_TPU_VERIFY_SCHED=0`` restores prior behavior bit-for-bit.
``verify_now`` is the synchronous escape hatch for callers that cannot
tolerate queueing latency.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Optional, Sequence

from cometbft_tpu.crypto import sigcache
from cometbft_tpu.libs import tracing
from cometbft_tpu.verifysched import stats

logger = logging.getLogger("cometbft_tpu.verifysched")

# Priority classes (lower = more important).  EVIDENCE/LIGHT share a class,
# as do BLOCKSYNC/MEMPOOL — three queues cover the real urgency tiers.
PRIO_CONSENSUS = 0
PRIO_EVIDENCE = 1
PRIO_LIGHT = 1
PRIO_BLOCKSYNC = 2
PRIO_MEMPOOL = 2
N_CLASSES = 3

DEFAULT_FLUSH_US = 2000.0
DEFAULT_QUEUE_CAP = 8192
# largest fusable bucket (mirrors ops.verify._BUCKETS[-1]); a single drain
# never exceeds it — leftovers stay queued for the next flush
MAX_DRAIN = 32768

# ops.verify._BUCKETS mirror for the ``_bucket_target`` fallback path: if
# the ops import itself fails, the width-scaled target must STILL clamp to
# a real padding bucket — a non-bucket target would deliberately wait for
# a strictly worse-padded flush
_FALLBACK_BUCKETS = (32, 64, 128, 256, 512, 1024, 4096, 8192, 10240, 32768)


class QueueFullError(Exception):
    """Admission control rejected a non-consensus submission (backpressure).
    The caller verifies synchronously instead — shedding costs the batching
    win, never the verdict."""


def enabled() -> bool:
    return os.environ.get("COMETBFT_TPU_VERIFY_SCHED", "1") != "0"


def backend_trusted() -> bool:
    """True when the accelerator batch backend is the trusted ``tpu``
    seam — the gate the fused stream, blocksync prefetch, the scheduler
    AND the tx-ingest coalescer all share, so a CPU-backend node (whose
    host library path has no dispatch floor to amortize) keeps its
    synchronous behavior untouched.

    Deliberately NEVER calls ``cbatch.default_backend()``'s auto-probe:
    that would import jax and initialize a backend from gossip-time
    ``Vote.verify`` in processes that otherwise never touch the device
    (every CPU e2e node pays seconds of init on its first vote).  With the
    backend unconfigured and still unresolved, the gate stays closed; it
    opens the moment the batch seam's own first use resolves the backend
    to ``tpu``."""
    from cometbft_tpu.crypto import batch as cbatch

    env = os.environ.get("COMETBFT_TPU_CRYPTO_BACKEND")
    if env and env != "auto":
        return env == "tpu"
    return cbatch._DEFAULT_BACKEND == "tpu"


def scheduler_active() -> bool:
    """True when submissions should take the scheduler path: kill switch
    on AND the batch backend trusted (``backend_trusted``)."""
    return enabled() and backend_trusted()


def pipeline_enabled() -> bool:
    """In-flight pipelining (docs/verify-scheduler.md "In-flight
    pipeline"): the dispatcher ships flush i+1 while flush i is still on
    the device, and one completion thread resolves verdicts in drain
    order.  ``COMETBFT_TPU_SCHED_PIPELINE=0`` restores the single-flight
    dispatcher bit-for-bit."""
    return os.environ.get("COMETBFT_TPU_SCHED_PIPELINE", "1") != "0"


def inflight_target() -> int:
    """Bound on concurrently dispatched flushes: explicit
    ``COMETBFT_TPU_SCHED_INFLIGHT`` wins; the default is the LIVE elastic
    mesh width (each healthy lane carries its own dispatch, and the bound
    follows shrinks/restores automatically — ``healthy_width`` is
    jax-free) with a floor of 2 on a single chip, where the depth buys
    host-prep/device-compute overlap rather than lane parallelism."""
    env = os.environ.get("COMETBFT_TPU_SCHED_INFLIGHT")
    if env:
        try:
            return max(int(env), 1)
        except ValueError:
            pass
    try:
        from cometbft_tpu.parallel import elastic

        w = elastic.healthy_width()
    except Exception:  # noqa: BLE001 — mesh introspection is never
        # load-bearing for the flush loop
        w = 0
    return max(w, 2)


# -- per-thread priority class ----------------------------------------------

_TLS = threading.local()


def current_priority() -> int:
    """The ambient priority class for this thread.  Call sites that reach
    the scheduler through deep shared layers (the ``_CollectingVerifier``
    bridge under ``types/validation``) tag their work with
    ``priority_class`` instead of plumbing an argument through every
    signature-verification API.

    FAIL-CLOSED default: untagged work is BULK (sheddable).  The
    consensus class is shed-exempt and skips the queue bound, so handing
    it out implicitly would let any future untagged caller bypass
    admission control and starve every other class; the three consensus
    sites (vote, proposal, vote-extension) pass ``priority=`` explicitly."""
    return getattr(_TLS, "prio", PRIO_BLOCKSYNC)


@contextlib.contextmanager
def priority_class(priority: int):
    prev = getattr(_TLS, "prio", None)
    _TLS.prio = int(priority)
    try:
        yield
    finally:
        if prev is None:
            del _TLS.prio
        else:
            _TLS.prio = prev


# -- the scheduler -----------------------------------------------------------


class _Item:
    # t0 = submit time, t_drain = when the dispatcher drained it out of
    # the queue: submit->drain is QUEUE WAIT, drain->verdict is DEVICE
    # time — recorded as separate histograms so queue pressure and device
    # slowness are distinguishable regressions (docs/observability.md)
    __slots__ = ("pub", "msg", "sig", "prio", "future", "t0", "t_drain")

    def __init__(self, pub, msg, sig, prio, future, t0):
        self.pub = pub
        self.msg = msg
        self.sig = sig
        self.prio = prio
        self.future = future
        self.t0 = t0
        self.t_drain = t0


class VerifyScheduler:
    """One dispatcher thread over three priority queues.  Thread-safe;
    lazily starts its thread on the first queued submission and drains
    everything (reason ``shutdown``) on ``close()`` — a future handed out
    is always eventually resolved."""

    def __init__(
        self,
        flush_us: Optional[float] = None,
        queue_cap: Optional[int] = None,
    ):
        if flush_us is None:
            try:
                flush_us = float(
                    os.environ.get("COMETBFT_TPU_SCHED_FLUSH_US", "")
                    or DEFAULT_FLUSH_US
                )
            except ValueError:
                flush_us = DEFAULT_FLUSH_US
        if queue_cap is None:
            try:
                queue_cap = int(
                    os.environ.get("COMETBFT_TPU_SCHED_QUEUE", "")
                    or DEFAULT_QUEUE_CAP
                )
            except ValueError:
                queue_cap = DEFAULT_QUEUE_CAP
        self.flush_s = max(flush_us, 0.0) / 1e6
        self.queue_cap = max(int(queue_cap), 1)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: "list[deque[_Item]]" = [
            deque() for _ in range(N_CLASSES)
        ]
        self._count = 0
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._paused = False
        self._full_target: Optional[int] = None
        # flush-interval histo — stamped at DISPATCH SUBMISSION on the
        # dispatcher thread (monotonic there), never from item drain
        # times: with K flushes in flight, drain-time deltas could go
        # negative or interleave
        self._last_flush_t: Optional[float] = None
        # in-flight pipeline state (pipeline_enabled()): FIFO of
        # dispatched-but-unfetched flushes, resolved in drain order by
        # one completion thread; _fcond has its OWN lock so a waiting
        # dispatcher never blocks submitters
        self._flock = threading.Lock()
        self._fcond = threading.Condition(self._flock)
        self._fetch_queue: "deque[tuple]" = deque()
        self._inflight = 0
        self._fetch_thread: Optional[threading.Thread] = None
        self._fetch_stop = False
        self._lane_rr = 0  # round-robin over healthy mesh ordinals

    # -- submission -------------------------------------------------------

    def submit(
        self,
        pub: bytes,
        msg: bytes,
        sig: bytes,
        priority: int = PRIO_CONSENSUS,
        precleared: bool = False,
    ) -> "Future[bool]":
        """Queue one (pub, msg, sig) check; returns a Future resolving to
        the definitive verdict.  A sigcache hit resolves immediately
        without occupying a queue slot (``precleared=True`` skips that
        lookup — for bridges that just partitioned the cache themselves).
        Raises ``QueueFullError`` for non-consensus classes when the queue
        is at capacity; consensus submissions are always admitted."""
        prio = min(max(int(priority), 0), N_CLASSES - 1)
        fut: "Future[bool]" = Future()
        if not precleared:
            hit = sigcache.get_cache().get(pub, msg, sig)
            if hit is not None:
                stats.record_submit_hit(prio)
                fut.set_result(bool(hit))
                return fut
        try:
            with self._cond:
                if self._stopped:
                    raise RuntimeError("verify scheduler is stopped")
                if prio != PRIO_CONSENSUS and self._count >= self.queue_cap:
                    stats.record_shed(prio)
                    raise QueueFullError(
                        f"verify queue at capacity ({self.queue_cap}); "
                        f"shedding class {stats.CLASS_NAMES[prio]}"
                    )
                self._queues[prio].append(
                    _Item(pub, msg, sig, prio, fut, time.perf_counter())
                )
                self._count += 1
                stats.record_submit(prio)
                if self._thread is None or not self._thread.is_alive():
                    # lazily started — and RESTARTED if it ever died (an
                    # exception escaping even the _execute fallback, e.g.
                    # MemoryError): without this, every queued future would
                    # hang forever and take consensus with it.  The new
                    # thread drains whatever the dead one left queued.
                    if self._thread is not None:
                        logger.error(
                            "verify dispatcher thread died; restarting "
                            "(%d items pending)",
                            self._count,
                        )
                    self._thread = threading.Thread(
                        target=self._run, name="verify-sched", daemon=True
                    )
                    self._thread.start()
                self._cond.notify_all()
        except QueueFullError:
            # flight-recorder anomaly (the FIRST shed dumps the ring;
            # later sheds are counted), recorded AFTER the cond is
            # released: the dump's file IO must never block other
            # submitters — least of all shed-exempt consensus votes —
            # behind the scheduler lock
            tracing.record_anomaly(
                "queue_shed",
                cls=stats.CLASS_NAMES[prio],
                queue_cap=self.queue_cap,
            )
            raise
        return fut

    def submit_many(
        self,
        pubs: Sequence[bytes],
        msgs: Sequence[bytes],
        sigs: Sequence[bytes],
        priority: int = PRIO_CONSENSUS,
        precleared: bool = False,
    ) -> "list[Optional[Future]]":
        """Submit a whole segment before waiting on any item, so the
        pieces can coalesce into one flush.  Entries the admission control
        sheds come back as ``None`` — the caller verifies those itself.
        A scheduler stopped mid-segment (teardown race) marks the rest
        ``None`` the same way: already-queued futures still resolve (close
        drains the queue), the remainder degrade to the caller's fallback."""
        out: "list[Optional[Future]]" = []
        for p, m, s in zip(pubs, msgs, sigs):
            try:
                out.append(self.submit(p, m, s, priority, precleared))
            except QueueFullError:
                out.append(None)
            except RuntimeError:
                out.extend([None] * (len(msgs) - len(out)))
                break
        return out

    # -- test/bench hooks -------------------------------------------------

    def pause(self) -> None:
        """Hold flushing (test/bench hook: build a deterministic backlog)."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def pending(self) -> int:
        with self._lock:
            return self._count

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting work, drain the queue (reason ``shutdown``) and
        join the dispatcher.  Every outstanding future resolves.  A
        dispatcher wedged past the join timeout (a stuck device dispatch)
        is surfaced loudly: the caller may be about to restore global
        state (env knobs, device-runner seam, stats) that the straggling
        flush would then run — and record — under."""
        with self._cond:
            self._stopped = True
            self._paused = False
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout_s)
            if t.is_alive():
                logger.warning(
                    "verify scheduler dispatcher still alive %.1fs after "
                    "close() — a wedged flush will finish under whatever "
                    "global state exists when it unwedges",
                    timeout_s,
                )
        # the dispatcher is down (no new enqueues); now drain the
        # completion pool — it exits only once the in-flight FIFO is empty,
        # so every dispatched flush still resolves its futures
        with self._fcond:
            self._fetch_stop = True
            self._fcond.notify_all()
            ft = self._fetch_thread
        if ft is not None:
            ft.join(timeout_s)
            if ft.is_alive():
                logger.warning(
                    "verify scheduler completion thread still alive %.1fs "
                    "after close() — a wedged fetch will finish under "
                    "whatever global state exists when it unwedges",
                    timeout_s,
                )

    # -- dispatcher -------------------------------------------------------

    def _bucket_target(self) -> int:
        """Items that fill the smallest padding bucket for the active
        kernel: flushing there costs zero padding waste, so waiting any
        longer only adds latency.  The base bucket is computed once, off
        the submit path (the ops import pulls in jax); the LIVE elastic
        mesh width scales it per flush — a W-device mesh splits the batch
        W ways, so a full flush is W smallest buckets (one per shard),
        and the target follows shrinks and restores automatically
        (``parallel/elastic.healthy_width`` is jax-free)."""
        if self._full_target is None:
            try:
                from cometbft_tpu.ops import verify as ov

                self._full_target = ov.bucket_size(1, ov._min_bucket())
            except Exception:  # noqa: BLE001 — conservative fallback
                self._full_target = 128
        try:
            from cometbft_tpu.parallel import elastic

            w = elastic.healthy_width()
        except Exception:  # noqa: BLE001 — mesh introspection is never
            # load-bearing for the flush loop
            w = 0
        if w < 2:
            return self._full_target
        # round DOWN to a real padding bucket: the mesh path pads the
        # fused batch to a GLOBAL bucket before sharding, so a non-bucket
        # target (base×3 = 384 → bucket 512) would deliberately wait for
        # a strictly worse-padded flush; the largest bucket ≤ base×W
        # keeps the zero-waste property at lower latency
        scaled = self._full_target * w
        try:
            from cometbft_tpu.ops import verify as ov

            fits = [
                b for b in ov._BUCKETS if self._full_target <= b <= scaled
            ]
            return fits[-1] if fits else self._full_target
        except Exception:  # noqa: BLE001 — clamp against the static
            # bucket mirror: the raw scaled value may not be a bucket
            fits = [
                b
                for b in _FALLBACK_BUCKETS
                if self._full_target <= b <= scaled
            ]
            return fits[-1] if fits else self._full_target

    def _oldest_t0(self) -> Optional[float]:
        heads = [q[0].t0 for q in self._queues if q]
        return min(heads) if heads else None

    def _drain(self) -> "list[_Item]":
        out: "list[_Item]" = []
        now = time.perf_counter()
        for q in self._queues:  # consensus first
            while q and len(out) < MAX_DRAIN:
                it = q.popleft()
                it.t_drain = now
                out.append(it)
        self._count -= len(out)
        return out

    def _run(self) -> None:
        self._bucket_target()  # jax import happens here, unlocked
        # the dispatcher only exists when the trusted backend is active —
        # the exact population warm-boot serves: precompile the bucket x
        # tier matrix in the background so the first flush (and the first
        # post-demotion flush) meets a resident executable
        from cometbft_tpu.ops import warmboot

        warmboot.ensure_started()
        while True:
            # re-read once per flush cycle (not per wakeup: every submit
            # notifies the cond, and the live-width read walks breaker
            # locks) — the target still follows mesh shrinks/restores at
            # flush granularity
            full = self._bucket_target()
            with self._cond:
                while not self._stopped and (
                    self._count == 0 or self._paused
                ):
                    self._cond.wait()
                if self._stopped and self._count == 0:
                    return
                reason = "shutdown"
                if not self._stopped:
                    while True:
                        if self._stopped:
                            break
                        if self._paused:
                            break
                        if self._count >= full:
                            reason = "full"
                            break
                        oldest = self._oldest_t0()
                        if oldest is None:
                            break
                        remain = oldest + self.flush_s - time.perf_counter()
                        if remain <= 0:
                            reason = "deadline"
                            break
                        self._cond.wait(remain)
                    if self._paused and not self._stopped:
                        continue
                    if self._count == 0:
                        continue
                items = self._drain()
            if items:
                self._execute(items, reason)

    # -- flush ------------------------------------------------------------

    def _execute(self, items: "list[_Item]", reason: str) -> None:
        recorded = [False]
        try:
            if pipeline_enabled():
                # in-flight pipeline: dispatch without blocking on the
                # verdicts — enqueueing onto the completion FIFO is the
                # LAST step, so any exception reaching the fallback below
                # means these items were never handed off and the host
                # reference resolve covers all of them
                self._dispatch_flush(items, reason, recorded)
            else:
                self._execute_inner(items, reason, recorded)
        except BaseException as e:  # noqa: BLE001 — futures must ALWAYS
            # resolve: these items left the queue, so the submit-path
            # dispatcher restart can never recover them — an unresolved
            # future here is a permanent consensus hang in result()
            logger.exception(
                "verify flush failed unexpectedly; resolving %d items on "
                "the host reference",
                len(items),
            )
            from cometbft_tpu.crypto import ed25519_ref as ref

            # exactly-once flush accounting: if the inner pass failed
            # before recording, account the drained items here or
            # queue_depth stays inflated forever
            if not recorded[0]:
                stats.record_flush(
                    reason, items=len(items), misses=0, lanes=0
                )
            now = time.perf_counter()
            for it in items:
                if it.future.done():
                    continue
                try:
                    ok = len(it.pub) == 32 and len(it.sig) == 64 and bool(
                        ref.verify_zip215(it.pub, it.msg, it.sig)
                    )
                except Exception:  # noqa: BLE001 — malformed input
                    ok = False
                it.future.set_result(ok)
                stats.record_verdict(it.prio, now - it.t0)
            if not isinstance(e, Exception):
                raise  # SystemExit etc.: die, but only AFTER resolving
                # (the next submit detects the dead thread and restarts)

    def _execute_inner(
        self, items: "list[_Item]", reason: str, recorded: "list[bool]"
    ) -> None:
        n = len(items)
        pubs = [it.pub for it in items]
        msgs = [it.msg for it in items]
        sigs = [it.sig for it in items]

        # flush span (closed BEFORE futures resolve, like the stats below,
        # so a deterministic sim's ring order cannot race its waiters)
        with tracing.span("sched.flush", reason=reason, items=n) as fsp:
            # structural filter (garbage never occupies a device lane) +
            # in-flight dedup: concurrent gossip of the same vote collapses
            # into one lane, both futures share the verdict
            bits: "list[Optional[bool]]" = [None] * n
            uniq: "OrderedDict[bytes, list[int]]" = OrderedDict()
            for i in range(n):
                if len(pubs[i]) != 32 or len(sigs[i]) != 64:
                    bits[i] = False
                    continue
                k = sigcache._key(pubs[i], msgs[i], sigs[i])
                uniq.setdefault(k, []).append(i)
            firsts = [ixs[0] for ixs in uniq.values()]
            stats.record_dedup(sum(len(ixs) - 1 for ixs in uniq.values()))

            lanes = 0
            if firsts:
                from cometbft_tpu.ops import verify as ov

                # one segment per priority class present: verify_segments
                # fuses them into ONE dispatch (recording cross-class
                # fusion in ops/dispatch_stats), splits bits back per class
                by_class: "list[list[int]]" = [[] for _ in range(N_CLASSES)]
                for i in firsts:
                    by_class[items[i].prio].append(i)
                ordered = [i for cls in by_class for i in cls]
                work = [
                    (
                        [pubs[i] for i in cls],
                        [msgs[i] for i in cls],
                        [sigs[i] for i in cls],
                    )
                    for cls in by_class
                    if cls
                ]
                lanes = ov.bucket_size(len(ordered), ov._min_bucket())
                results = ov.verify_segments(work)
                # verdicts keyed by FIRST index of each dedup group (the
                # hash was already paid once in the dedup loop above)
                verdict_by_first = dict(
                    zip(ordered, (bool(b) for seg in results for b in seg))
                )
                # resolve every member of each dedup group + cache
                # writeback.  Inlined rather than sigcache.writeback: that
                # would re-hash every entry, and the dedup loop already
                # holds the keys — on the single dispatcher thread a third
                # SHA-256 per item gates every waiter's latency.
                # Supervised verdicts are always definitive, so caching
                # unconditionally is safe.
                cache = sigcache.get_cache()
                cache_on = cache.enabled()
                for k, ixs in uniq.items():
                    v = verdict_by_first[ixs[0]]
                    for i in ixs:
                        bits[i] = v
                    if cache_on:
                        cache._put(k, v)
            fsp.set(misses=len(firsts), lanes=lanes)

        # record BEFORE resolving: set_result unblocks waiters, and a
        # caller reading stats right after its verdict (the sim's
        # end-of-run capture asserts queue_depth == 0) must not race the
        # dispatcher's bookkeeping; ``recorded`` keeps the _execute
        # fallback from double-counting if a resolve below raises
        interval = self._flush_interval()
        stats.record_flush(
            reason, items=n, misses=len(firsts), lanes=lanes,
            interval_s=interval,
        )
        recorded[0] = True
        now = time.perf_counter()
        for i, it in enumerate(items):
            it.future.set_result(bool(bits[i]))
            stats.record_verdict(
                it.prio,
                now - it.t0,
                queue_wait_s=it.t_drain - it.t0,
                device_s=now - it.t_drain,
            )

    # -- in-flight pipeline (docs/verify-scheduler.md) --------------------

    def _flush_interval(self) -> Optional[float]:
        """Interval between consecutive flushes, stamped NOW on the
        dispatcher thread — monotonic there by construction, so the
        histogram cannot go negative or interleave however many flushes
        are in flight."""
        t = time.perf_counter()
        interval = (
            None if self._last_flush_t is None else t - self._last_flush_t
        )
        self._last_flush_t = t
        return interval

    def _dispatch_flush(
        self, items: "list[_Item]", reason: str, recorded: "list[bool]"
    ) -> None:
        """The pipelined front half of a flush: structural filter +
        dedup + ONE fused dispatch (``ops.verify.dispatch_segments``),
        then hand the in-flight handle to the completion thread and
        return to draining — up to ``inflight_target()`` flushes ride
        the device concurrently, round-robined across healthy mesh
        lanes.  Identical front-half semantics to ``_execute_inner``;
        only WHERE the fetch happens moves."""
        n = len(items)
        pubs = [it.pub for it in items]
        msgs = [it.msg for it in items]
        sigs = [it.sig for it in items]
        interval = self._flush_interval()

        with tracing.span("sched.flush", reason=reason, items=n) as fsp:
            bits: "list[Optional[bool]]" = [None] * n
            uniq: "OrderedDict[bytes, list[int]]" = OrderedDict()
            for i in range(n):
                if len(pubs[i]) != 32 or len(sigs[i]) != 64:
                    bits[i] = False
                    continue
                k = sigcache._key(pubs[i], msgs[i], sigs[i])
                uniq.setdefault(k, []).append(i)
            firsts = [ixs[0] for ixs in uniq.values()]
            stats.record_dedup(sum(len(ixs) - 1 for ixs in uniq.values()))

            lanes = 0
            handle = None
            ordered: "list[int]" = []
            if firsts:
                from cometbft_tpu.ops import verify as ov

                by_class: "list[list[int]]" = [[] for _ in range(N_CLASSES)]
                for i in firsts:
                    by_class[items[i].prio].append(i)
                ordered = [i for cls in by_class for i in cls]
                work = [
                    (
                        [pubs[i] for i in cls],
                        [msgs[i] for i in cls],
                        [sigs[i] for i in cls],
                    )
                    for cls in by_class
                    if cls
                ]
                lanes = ov.bucket_size(len(ordered), ov._min_bucket())
                self._ensure_fetch_thread()
                cap = max(inflight_target(), 1)
                # reserve an in-flight slot BEFORE dispatching — the cap
                # bounds concurrent dispatches, and the wait re-checks the
                # completion thread so a dead one is restarted rather
                # than waited on forever
                with self._fcond:
                    while self._inflight >= cap:
                        if (
                            self._fetch_thread is None
                            or not self._fetch_thread.is_alive()
                        ):
                            break
                        self._fcond.wait(0.1)
                    self._inflight += 1
                    stats.record_inflight(self._inflight)
                self._ensure_fetch_thread()
                lane = None
                try:
                    from cometbft_tpu.parallel import elastic

                    # the probe-ADMITTING membership walk, not the
                    # read-only healthy list: a half-open chip re-earns
                    # its lane via the one-bucket probe here, exactly as
                    # it would under a mesh-wide dispatch.  Below 2 lanes
                    # the mesh rule says single-chip: lane=None falls
                    # into the pallas→xla→host chain, which keeps THOSE
                    # breakers probed and re-promoted too.
                    ords = elastic.admit_ordinals()
                    if len(ords) >= 2:
                        lane = ords[self._lane_rr % len(ords)]
                        self._lane_rr += 1
                except Exception:  # noqa: BLE001 — lane pinning is an
                    # optimization, never load-bearing
                    lane = None
                try:
                    with tracing.span(
                        "sched.dispatch", reason=reason, items=n,
                        lanes=lanes,
                    ):
                        handle = ov.dispatch_segments(work, lane=lane)
                except BaseException:
                    with self._fcond:
                        self._inflight -= 1
                        stats.record_inflight(self._inflight)
                        self._fcond.notify_all()
                    raise
            fsp.set(misses=len(firsts), lanes=lanes)

        stats.record_flush(
            reason, items=n, misses=len(firsts), lanes=lanes,
            interval_s=interval,
        )
        recorded[0] = True
        if handle is None:
            # nothing device-bound (all garbage/empty): resolve inline
            now = time.perf_counter()
            for i, it in enumerate(items):
                it.future.set_result(bool(bits[i]))
                stats.record_verdict(
                    it.prio,
                    now - it.t0,
                    queue_wait_s=it.t_drain - it.t0,
                    device_s=now - it.t_drain,
                )
            return
        with self._fcond:
            self._fetch_queue.append((handle, items, bits, uniq, ordered))
            self._fcond.notify_all()

    def _ensure_fetch_thread(self) -> None:
        """Start — or RESTART, mirroring the dispatcher's own restart
        path — the completion thread.  A dead completion thread with
        flushes still queued would strand their futures forever."""
        with self._fcond:
            if self._fetch_thread is None or not self._fetch_thread.is_alive():
                if self._fetch_thread is not None:
                    logger.error(
                        "verify completion thread died; restarting "
                        "(%d flushes in flight)",
                        len(self._fetch_queue),
                    )
                self._fetch_thread = threading.Thread(
                    target=self._fetch_run,
                    name="verify-sched-fetch",
                    daemon=True,
                )
                self._fetch_thread.start()

    def _fetch_run(self) -> None:
        while True:
            with self._fcond:
                while not self._fetch_queue and not self._fetch_stop:
                    self._fcond.wait()
                if not self._fetch_queue:
                    return  # stop requested and FIFO drained
                pf = self._fetch_queue.popleft()
            try:
                self._resolve_flush(pf)
            finally:
                with self._fcond:
                    self._inflight = max(0, self._inflight - 1)
                    stats.record_inflight(self._inflight)
                    self._fcond.notify_all()

    def _resolve_flush(self, pf: tuple) -> None:
        """The completion half of one pipelined flush: fetch verdicts,
        write the sigcache back, resolve every future.  Runs on the
        completion thread in drain order; cannot leave a future
        unresolved — a fetch that somehow escapes the supervisor's
        degradation chain resolves the flush on the host reference."""
        handle, items, bits, uniq, ordered = pf
        try:
            from cometbft_tpu.ops import verify as ov

            with tracing.span("sched.fetch", items=len(items)):
                results = ov.fetch_segments(handle)
            verdict_by_first = dict(
                zip(ordered, (bool(b) for seg in results for b in seg))
            )
            cache = sigcache.get_cache()
            cache_on = cache.enabled()
            for k, ixs in uniq.items():
                v = verdict_by_first[ixs[0]]
                for i in ixs:
                    bits[i] = v
                if cache_on:
                    cache._put(k, v)
        except BaseException:  # noqa: BLE001 — swallow even SystemExit:
            # the completion thread must outlive one bad flush or every
            # queued flush behind it strands its futures
            logger.exception(
                "pipelined flush fetch failed unexpectedly; resolving %d "
                "items on the host reference",
                len(items),
            )
            from cometbft_tpu.crypto import ed25519_ref as ref

            for i, it in enumerate(items):
                if it.future.done() or bits[i] is not None:
                    continue
                try:
                    bits[i] = len(it.pub) == 32 and len(
                        it.sig
                    ) == 64 and bool(
                        ref.verify_zip215(it.pub, it.msg, it.sig)
                    )
                except Exception:  # noqa: BLE001 — malformed input
                    bits[i] = False
        now = time.perf_counter()
        for i, it in enumerate(items):
            if it.future.done():
                continue
            it.future.set_result(bool(bits[i]))
            stats.record_verdict(
                it.prio,
                now - it.t0,
                queue_wait_s=it.t_drain - it.t0,
                device_s=now - it.t_drain,
            )


# -- process-wide instance ----------------------------------------------------

_SCHED: Optional[VerifyScheduler] = None
_SCHED_LOCK = threading.Lock()


def get_scheduler() -> VerifyScheduler:
    """The process-wide scheduler (consensus, evidence, light and blocksync
    all share one — that sharing IS the optimization)."""
    global _SCHED
    if _SCHED is None:
        with _SCHED_LOCK:
            if _SCHED is None:
                _SCHED = VerifyScheduler()
    return _SCHED


def reset_scheduler() -> None:
    """Drain + drop the process-wide scheduler (tests/sim; also re-reads
    the flush/queue env knobs on next use)."""
    global _SCHED
    with _SCHED_LOCK:
        sched, _SCHED = _SCHED, None
    if sched is not None:
        sched.close()


# -- call-site wrappers -------------------------------------------------------


def _ed25519_pub(pub_key) -> Optional[bytes]:
    from cometbft_tpu.crypto import keys as ck

    if getattr(pub_key, "type_", None) != ck.ED25519_KEY_TYPE:
        return None
    return pub_key.bytes() if hasattr(pub_key, "bytes") else bytes(pub_key)


def verify_now(pub_key, msg: bytes, sig: bytes) -> bool:
    """Synchronous escape hatch: cache-through single verification with no
    queueing — exactly the pre-scheduler path."""
    return sigcache.verify_with_cache(pub_key, msg, sig)


def _shed_fallback_verify(pub_key, msg: bytes, sig: bytes, prio: int) -> bool:
    """The synchronous verify a SHED caller runs: emits a span and a
    submit->verdict histogram sample so shed work stays in the latency
    record instead of vanishing from it (docs/observability.md)."""
    t0 = time.perf_counter()
    with tracing.span(
        "sched.shed_fallback", cls=stats.CLASS_NAMES[_clamp_prio(prio)]
    ):
        ok = verify_now(pub_key, msg, sig)
    stats.record_shed_fallback(prio, time.perf_counter() - t0)
    return ok


def _clamp_prio(priority: int) -> int:
    return min(max(int(priority), 0), N_CLASSES - 1)


def verify_cached(pub_key, msg: bytes, sig: bytes, priority=None) -> bool:
    """THE drop-in for ``sigcache.verify_with_cache`` on scheduler-wired
    call sites (gossip-time ``Vote.verify``, proposal and vote-extension
    checks, evidence).  Scheduler inactive, non-ed25519 key, or shed by
    admission control -> the synchronous path, verdict-identical."""
    prio = current_priority() if priority is None else priority
    if scheduler_active():
        pub = _ed25519_pub(pub_key)
        if pub is not None:
            try:
                return bool(
                    get_scheduler().submit(pub, msg, sig, prio).result()
                )
            except (QueueFullError, RuntimeError):
                # shed, or scheduler torn down under us (reset race):
                # synchronous fallback — spanned + histogram-sampled
                return _shed_fallback_verify(pub_key, msg, sig, prio)
    return verify_now(pub_key, msg, sig)


def verify_many_cached(
    pub_keys, msgs: Sequence[bytes], sigs: Sequence[bytes], priority=None
) -> "list[bool]":
    """Several independent checks submitted before waiting on any, so they
    ride one flush (evidence checks both duplicate-vote signatures this
    way).  Falls back per item on shed / inactive / non-ed25519."""
    prio = current_priority() if priority is None else priority
    out: "list[Optional[bool]]" = [None] * len(msgs)
    futs: "list[Optional[Future]]" = [None] * len(msgs)
    shed_ix: set = set()
    if scheduler_active():
        sched = get_scheduler()
        for i, (pk, m, s) in enumerate(zip(pub_keys, msgs, sigs)):
            pub = _ed25519_pub(pk)
            if pub is None:
                continue
            try:
                futs[i] = sched.submit(pub, m, s, prio)
            except (QueueFullError, RuntimeError):
                futs[i] = None  # shed or torn down: sync fallback below
                shed_ix.add(i)
    for i, (pk, m, s) in enumerate(zip(pub_keys, msgs, sigs)):
        if futs[i] is not None:
            out[i] = bool(futs[i].result())
        elif i in shed_ix:
            out[i] = _shed_fallback_verify(pk, m, s, prio)
        else:
            out[i] = verify_now(pk, m, s)
    return out


def verify_segment_sync(
    pubs: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    priority=None,
) -> "list[bool]":
    """The batch-verifier bridge: submit a pre-partitioned segment of raw
    ed25519 triples (the caller — ``_CollectingVerifier`` — already took
    its cache hits) and wait for all verdicts.  Entries shed by admission
    control are verified in one direct supervised dispatch instead, so the
    call never blocks on queue capacity."""
    prio = current_priority() if priority is None else priority
    futs = get_scheduler().submit_many(
        pubs, msgs, sigs, prio, precleared=True
    )
    shed = [i for i, f in enumerate(futs) if f is None]
    direct: dict = {}
    if shed:
        from cometbft_tpu.ops import verify as ov

        t0 = time.perf_counter()
        with tracing.span(
            "sched.shed_fallback",
            cls=stats.CLASS_NAMES[_clamp_prio(prio)],
            items=len(shed),
        ):
            got = ov.verify_batch(
                [pubs[i] for i in shed],
                [msgs[i] for i in shed],
                [sigs[i] for i in shed],
            )
        dt = time.perf_counter() - t0
        for _ in shed:
            # every shed item experienced the whole direct dispatch —
            # that IS its submit->verdict latency, kept in the record
            stats.record_shed_fallback(prio, dt)
        direct = {i: bool(b) for i, b in zip(shed, got)}
    return [
        direct[i] if f is None else bool(f.result())
        for i, f in enumerate(futs)
    ]
