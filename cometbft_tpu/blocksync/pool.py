"""Blocksync block pool: parallel block download from many peers.

Reference: internal/blocksync/pool.go:72 BlockPool — a sliding window of
in-flight height requests assigned to peers that advertise the height,
with per-request timeouts, peer banning on bad blocks, and a two-block
verification frontier (``peek_two_blocks``): block H is verified with the
LastCommit carried by block H+1.
"""

from __future__ import annotations

import random
import threading

from cometbft_tpu.libs import sync as libsync
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from cometbft_tpu.libs import log as liblog

REQUEST_WINDOW = 40  # max heights in flight (reference: maxPendingRequests=600, scaled down)
REQUEST_TIMEOUT = 15.0  # reassign a request after this long


@dataclass
class _PeerData:
    peer_id: str
    base: int = 0
    height: int = 0  # highest block the peer claims
    num_pending: int = 0
    banned_until: float = 0.0


@dataclass
class _Request:
    height: int
    peer_id: str
    sent_at: float
    block: Optional[object] = None  # types.Block once received
    ext_commit: Optional[object] = None  # types.ExtendedCommit when served


class BlockPool:
    """Reference: pool.go BlockPool."""

    def __init__(
        self,
        start_height: int,
        send_request: Callable[[str, int], bool],
        logger: Optional[liblog.Logger] = None,
    ):
        self.height = start_height  # next height to pop
        self.send_request = send_request
        self.logger = logger or liblog.nop_logger()
        self._lock = libsync.rlock("blocksync.pool")
        self.peers: dict[str, _PeerData] = {}
        self.requests: dict[int, _Request] = {}
        self.ever_had_peers = False
        self._started_at = time.monotonic()

    # -- peers -------------------------------------------------------------

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        """Reference: pool.go SetPeerRange (from StatusResponse)."""
        with self._lock:
            pd = self.peers.get(peer_id)
            if pd is None:
                pd = _PeerData(peer_id)
                self.peers[peer_id] = pd
            self.ever_had_peers = True
            pd.base = base
            pd.height = max(pd.height, height)

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            self.peers.pop(peer_id, None)
            for h, req in list(self.requests.items()):
                if req.peer_id == peer_id and req.block is None:
                    del self.requests[h]  # will be re-requested

    def ban_peer(self, peer_id: str, duration: float = 60.0) -> None:
        """Reference: peer banning on bad blocks / timeouts
        (pool.go:153,431)."""
        with self._lock:
            pd = self.peers.get(peer_id)
            if pd is not None:
                pd.banned_until = time.monotonic() + duration

    def max_peer_height(self) -> int:
        with self._lock:
            return max((p.height for p in self.peers.values()), default=0)

    # -- blocks ------------------------------------------------------------

    def add_block(self, peer_id: str, block, ext_commit=None) -> bool:
        """Reference: pool.go:296 AddBlock — only accepted if this peer owns
        the outstanding request for that height.  ``ext_commit`` rides
        along when the serving peer stored one (vote extensions)."""
        height = block.header.height
        with self._lock:
            req = self.requests.get(height)
            if req is None or req.peer_id != peer_id or req.block is not None:
                return False
            req.block = block
            req.ext_commit = ext_commit
            pd = self.peers.get(peer_id)
            if pd is not None:
                pd.num_pending = max(pd.num_pending - 1, 0)
            return True

    def no_block(self, peer_id: str, height: int) -> None:
        """Peer explicitly has no such block: re-request elsewhere."""
        with self._lock:
            req = self.requests.get(height)
            if req is not None and req.peer_id == peer_id and req.block is None:
                del self.requests[height]
                pd = self.peers.get(peer_id)
                if pd is not None:
                    pd.num_pending = max(pd.num_pending - 1, 0)

    def peek_two_blocks(self):
        """Reference: pool.go:218 PeekTwoBlocks — (first, second) or Nones."""
        with self._lock:
            first = self.requests.get(self.height)
            second = self.requests.get(self.height + 1)
            return (
                first.block if first else None,
                second.block if second else None,
                first.peer_id if first else "",
                second.peer_id if second else "",
                first.ext_commit if first else None,
            )

    def peek_window(self, k: int):
        """The consecutive run of received blocks at the frontier, up to
        ``k`` heights past it: [(height, block, peer_id, ext_commit), ...]
        starting at ``self.height``, stopping at the first gap.  Feeds the
        reactor's fused verification prefetch — block H's commit rides in
        block H+1, so a window of n blocks lets n-1 commits be verified in
        one device dispatch instead of n-1."""
        with self._lock:
            out = []
            for h in range(self.height, self.height + max(k, 0) + 1):
                req = self.requests.get(h)
                if req is None or req.block is None:
                    break
                out.append((h, req.block, req.peer_id, req.ext_commit))
            return out

    def pop_request(self) -> None:
        """First block verified + applied: advance the frontier."""
        with self._lock:
            self.requests.pop(self.height, None)
            self.height += 1

    def redo_request(self, height: int) -> str:
        """Bad block at ``height``: drop the block, ban the sender
        (reference: pool.go RedoRequest)."""
        with self._lock:
            req = self.requests.pop(height, None)
            if req is None:
                return ""
            self.ban_peer(req.peer_id)
            return req.peer_id

    # -- request scheduling ------------------------------------------------

    def make_next_requests(self) -> None:
        """Fill the sliding window [height, height+WINDOW) with requests
        (reference: makeRequestersRoutine, pool.go:116)."""
        now = time.monotonic()
        with self._lock:
            max_h = self.max_peer_height()
            wanted = [
                h
                for h in range(self.height, min(self.height + REQUEST_WINDOW, max_h + 1))
                if h not in self.requests
            ]
            # expire timed-out requests
            for h, req in list(self.requests.items()):
                if req.block is None and now - req.sent_at > REQUEST_TIMEOUT:
                    self.ban_peer(req.peer_id, 30.0)
                    pd = self.peers.get(req.peer_id)
                    if pd is not None:
                        pd.num_pending = max(pd.num_pending - 1, 0)
                    del self.requests[h]
                    if h not in wanted:
                        wanted.append(h)
            candidates = [
                p
                for p in self.peers.values()
                if p.banned_until < now
            ]
            for h in sorted(wanted):
                peers = [
                    p
                    for p in candidates
                    if p.base <= h <= p.height and p.num_pending < 20
                ]
                if not peers:
                    continue
                pd = random.choice(peers)
                self.requests[h] = _Request(h, pd.peer_id, now)
                pd.num_pending += 1
                # send outside the lock would be nicer; try_send never blocks
                if not self.send_request(pd.peer_id, h):
                    del self.requests[h]
                    pd.num_pending -= 1

    # -- progress ----------------------------------------------------------

    def is_caught_up(self) -> bool:
        """Reference: pool.go IsCaughtUp — at (or past) the best peer
        height, with at least one peer heard from."""
        with self._lock:
            if not self.peers:
                return False
            return self.height >= self.max_peer_height()
