"""Blocksync block pool: parallel block download from many peers.

Reference: internal/blocksync/pool.go:72 BlockPool — a sliding window of
in-flight height requests assigned to peers that advertise the height,
with per-request timeouts, peer banning on bad blocks, and a two-block
verification frontier (``peek_two_blocks``): block H is verified with the
LastCommit carried by block H+1.

Beyond the reference, the pool is deterministic-fault-envelope clean
(docs/sim-design.md): the clock and rng are injected seams (the sim pins
both; production defaults to ``time.monotonic``/a private ``Random``), and
scheduling is WAN-aware:

  * **adaptive per-peer timeouts** — each peer keeps an RTT EWMA from its
    answered requests; a request to that peer expires after
    ``clamp(ewma * MULT, FLOOR, CAP)`` instead of one flat constant, so a
    slow-but-honest intercontinental peer is no longer indistinguishable
    from a dead one.
  * **exponential ban backoff + half-open probes** — a timed-out request
    alone is re-assigned, not punished (WAN loss is normal weather); a
    peer is banned only after ``BAN_STRIKES`` consecutive timeout scans
    with nothing served, on a bad block, or on a failed probe.  Bans
    double ``BAN_BASE * 2^n`` up to ``BAN_CAP``; when a ban expires, the
    peer is *half-open* (the ``backend_health`` one-bucket idiom): it
    gets exactly one in-flight probe request.  A served block re-admits
    it at full window share; a timed-out probe re-bans it at the next
    backoff level.  A still-bad peer costs one probe, never a window
    stall.
  * **stall-switch** — when the frontier height makes no progress for
    ``STALL_SECS``, its request is force-moved to the fastest advertising
    peer (lowest EWMA) so one wedged assignee cannot freeze catchup.

``COMETBFT_TPU_BSYNC_ADAPTIVE=0`` kills all three and restores the fixed
15 s timeout / flat ban scheduling bit-for-bit.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

from cometbft_tpu.blocksync import stats as bstats
from cometbft_tpu.libs import log as liblog
from cometbft_tpu.libs import sync as libsync

REQUEST_WINDOW = 40  # max heights in flight (reference: maxPendingRequests=600, scaled down)
REQUEST_TIMEOUT = 15.0  # reassign a request after this long (pre-EWMA / kill switch)
PEER_PENDING_CAP = 20  # max in-flight requests per (fully admitted) peer

# Adaptive-scheduling defaults (all overridable via env, read per pool):
_TIMEOUT_MULT = 4.0  # adaptive timeout = clamp(ewma * mult, floor, cap)
_TIMEOUT_FLOOR = 2.0
_TIMEOUT_CAP = 30.0
_BAN_BASE = 5.0  # first ban; doubles per consecutive ban up to the cap
_BAN_CAP = 60.0
_BAN_STRIKES = 3  # consecutive timeout scans with nothing served -> ban
_STALL_SECS = 10.0  # frontier quiet this long -> switch to fastest peer
_EWMA_ALPHA = 0.3


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


@dataclass
class PoolConfig:
    """Scheduling knobs, snapshotted from the environment at pool
    construction (scenarios override via extra_env before the joiner's
    pool exists)."""

    adaptive: bool = True
    timeout_mult: float = _TIMEOUT_MULT
    timeout_floor: float = _TIMEOUT_FLOOR
    timeout_cap: float = _TIMEOUT_CAP
    ban_base: float = _BAN_BASE
    ban_cap: float = _BAN_CAP
    ban_strikes: int = _BAN_STRIKES
    stall_secs: float = _STALL_SECS

    @classmethod
    def from_env(cls) -> "PoolConfig":
        return cls(
            adaptive=os.environ.get("COMETBFT_TPU_BSYNC_ADAPTIVE", "1") != "0",
            timeout_mult=_env_f("COMETBFT_TPU_BSYNC_TIMEOUT_MULT", _TIMEOUT_MULT),
            timeout_floor=_env_f("COMETBFT_TPU_BSYNC_TIMEOUT_FLOOR", _TIMEOUT_FLOOR),
            timeout_cap=_env_f("COMETBFT_TPU_BSYNC_TIMEOUT_CAP", _TIMEOUT_CAP),
            ban_base=_env_f("COMETBFT_TPU_BSYNC_BAN_BASE", _BAN_BASE),
            ban_cap=_env_f("COMETBFT_TPU_BSYNC_BAN_CAP", _BAN_CAP),
            ban_strikes=int(
                _env_f("COMETBFT_TPU_BSYNC_BAN_STRIKES", _BAN_STRIKES)
            ),
            stall_secs=_env_f("COMETBFT_TPU_BSYNC_STALL_SECS", _STALL_SECS),
        )


@dataclass
class _PeerData:
    peer_id: str
    base: int = 0
    height: int = 0  # highest block the peer claims
    num_pending: int = 0
    banned_until: float = 0.0
    # adaptive scheduling state:
    rtt_ewma: Optional[float] = None  # None until the first answered request
    ban_count: int = 0  # consecutive bans (backoff exponent); 0 = admitted
    probe_inflight: bool = False  # half-open: the one probe is out
    timeout_strikes: int = 0  # consecutive timeout scans with nothing served


@dataclass
class _Request:
    height: int
    peer_id: str
    sent_at: float
    block: Optional[object] = None  # types.Block once received
    ext_commit: Optional[object] = None  # types.ExtendedCommit when served
    probe: bool = False  # this request is a half-open re-admission probe


class BlockPool:
    """Reference: pool.go BlockPool."""

    def __init__(
        self,
        start_height: int,
        send_request: Callable[[str, int], bool],
        logger: Optional[liblog.Logger] = None,
        clock: Optional[Callable[[], float]] = None,
        rng: Optional[random.Random] = None,
        config: Optional[PoolConfig] = None,
    ):
        self.height = start_height  # next height to pop
        self.send_request = send_request
        self.logger = logger or liblog.nop_logger()
        # Injected seams: the sim pins both to its virtual clock / seeded
        # rng; production gets wall monotonic time and a private Random —
        # never the process-global ``random`` module, whose state any
        # library call can perturb.
        self._clock = clock if clock is not None else time.monotonic
        self._rng = rng if rng is not None else random.Random()
        self.config = config if config is not None else PoolConfig.from_env()
        self._lock = libsync.rlock("blocksync.pool")
        self.peers: dict[str, _PeerData] = {}
        self.requests: dict[int, _Request] = {}
        self.ever_had_peers = False
        self._started_at = self._clock()
        # stall-switch bookkeeping: last frontier height + when it moved
        self._progress_h = start_height
        self._progress_t = self._started_at

    # -- peers -------------------------------------------------------------

    def set_peer_range(
        self,
        peer_id: str,
        base: int,
        height: int,
        rtt: Optional[float] = None,
    ) -> None:
        """Reference: pool.go SetPeerRange (from StatusResponse).  When the
        reactor measured the status round trip, it seeds the RTT EWMA of a
        peer that has not served a block yet — otherwise that peer's first
        dropped response sits on the flat legacy REQUEST_TIMEOUT."""
        with self._lock:
            pd = self.peers.get(peer_id)
            if pd is None:
                pd = _PeerData(peer_id)
                self.peers[peer_id] = pd
            self.ever_had_peers = True
            pd.base = base
            pd.height = max(pd.height, height)
            if rtt is not None and rtt > 0.0 and pd.rtt_ewma is None:
                pd.rtt_ewma = rtt

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            self.peers.pop(peer_id, None)
            for h, req in list(self.requests.items()):
                if req.peer_id == peer_id and req.block is None:
                    del self.requests[h]  # will be re-requested

    def ban_peer(self, peer_id: str, duration: Optional[float] = None) -> None:
        """Reference: peer banning on bad blocks / timeouts
        (pool.go:153,431).  Adaptive mode ignores ``duration`` and applies
        exponential backoff: ``BAN_BASE * 2^bans`` capped at ``BAN_CAP``;
        the legacy path keeps the caller-supplied flat duration."""
        with self._lock:
            pd = self.peers.get(peer_id)
            if pd is None:
                return
            now = self._clock()
            if self.config.adaptive:
                if pd.banned_until > now:
                    # already banned: cached bad blocks surfacing while
                    # the ban runs are the same incident — escalation
                    # needs post-ban evidence (a failed probe or a fresh
                    # offence after re-admission)
                    return
                dur = min(
                    self.config.ban_base * (2.0 ** pd.ban_count),
                    self.config.ban_cap,
                )
                pd.ban_count += 1
                pd.probe_inflight = False
            else:
                dur = 60.0 if duration is None else duration
            pd.banned_until = now + dur
            pd.timeout_strikes = 0
            bstats.record_ban()
            self.logger.info(
                "blocksync peer banned",
                peer=peer_id,
                duration=dur,
                bans=pd.ban_count,
            )

    def max_peer_height(self) -> int:
        with self._lock:
            return max((p.height for p in self.peers.values()), default=0)

    # -- blocks ------------------------------------------------------------

    def add_block(self, peer_id: str, block, ext_commit=None) -> bool:
        """Reference: pool.go:296 AddBlock — only accepted if this peer owns
        the outstanding request for that height.  ``ext_commit`` rides
        along when the serving peer stored one (vote extensions)."""
        height = block.header.height
        with self._lock:
            req = self.requests.get(height)
            if req is None or req.peer_id != peer_id or req.block is not None:
                return False
            req.block = block
            req.ext_commit = ext_commit
            pd = self.peers.get(peer_id)
            if pd is not None:
                pd.num_pending = max(pd.num_pending - 1, 0)
                pd.timeout_strikes = 0  # it IS serving, just lossy/slow
                rtt = max(self._clock() - req.sent_at, 0.0)
                pd.rtt_ewma = (
                    rtt
                    if pd.rtt_ewma is None
                    else _EWMA_ALPHA * rtt + (1.0 - _EWMA_ALPHA) * pd.rtt_ewma
                )
                if req.probe and pd.probe_inflight:
                    # half-open probe answered: full re-admission.  (A bad
                    # block still takes the redo path afterwards, which
                    # re-bans the peer — a fresh incident, fresh backoff.)
                    pd.probe_inflight = False
                    pd.ban_count = 0
                    bstats.record_probe_pass()
                    self.logger.info(
                        "blocksync probe passed, peer re-admitted",
                        peer=peer_id,
                        height=height,
                    )
            bstats.record_block_received()
            return True

    def no_block(self, peer_id: str, height: int) -> None:
        """Peer explicitly has no such block: re-request elsewhere."""
        with self._lock:
            req = self.requests.get(height)
            if req is not None and req.peer_id == peer_id and req.block is None:
                del self.requests[height]
                pd = self.peers.get(peer_id)
                if pd is not None:
                    pd.num_pending = max(pd.num_pending - 1, 0)
                    if req.probe:
                        # an honest "don't have it" is not a failed probe:
                        # stay half-open, the next pass may probe again
                        pd.probe_inflight = False
                bstats.record_no_block()

    def peek_two_blocks(self):
        """Reference: pool.go:218 PeekTwoBlocks — (first, second) or Nones."""
        with self._lock:
            first = self.requests.get(self.height)
            second = self.requests.get(self.height + 1)
            return (
                first.block if first else None,
                second.block if second else None,
                first.peer_id if first else "",
                second.peer_id if second else "",
                first.ext_commit if first else None,
            )

    def peek_window(self, k: int):
        """The consecutive run of received blocks at the frontier, up to
        ``k`` heights past it: [(height, block, peer_id, ext_commit), ...]
        starting at ``self.height``, stopping at the first gap.  Feeds the
        reactor's fused verification prefetch — block H's commit rides in
        block H+1, so a window of n blocks lets n-1 commits be verified in
        one device dispatch instead of n-1."""
        with self._lock:
            out = []
            for h in range(self.height, self.height + max(k, 0) + 1):
                req = self.requests.get(h)
                if req is None or req.block is None:
                    break
                out.append((h, req.block, req.peer_id, req.ext_commit))
            return out

    def pop_request(self) -> None:
        """First block verified + applied: advance the frontier."""
        with self._lock:
            self.requests.pop(self.height, None)
            bstats.record_height_synced(self.height, self._clock())
            self.height += 1

    def redo_request(self, height: int) -> str:
        """Bad block at ``height``: drop the block, ban the sender
        (reference: pool.go RedoRequest)."""
        with self._lock:
            req = self.requests.pop(height, None)
            if req is None:
                return ""
            bstats.record_redo()
            self.ban_peer(req.peer_id)
            return req.peer_id

    # -- request scheduling ------------------------------------------------

    def _peer_timeout(self, pd: Optional[_PeerData]) -> float:
        """Per-peer adaptive request timeout; the flat constant before any
        RTT sample exists or when adaptivity is off."""
        if (
            not self.config.adaptive
            or pd is None
            or pd.rtt_ewma is None
        ):
            return REQUEST_TIMEOUT
        return min(
            max(pd.rtt_ewma * self.config.timeout_mult, self.config.timeout_floor),
            self.config.timeout_cap,
        )

    def _peer_cap(self, pd: _PeerData) -> int:
        """Window share: full cap when admitted, one probe when half-open,
        zero while the probe is still out."""
        if not self.config.adaptive or pd.ban_count == 0:
            return PEER_PENDING_CAP
        return 0 if pd.probe_inflight else 1

    def _assign(self, pd: _PeerData, h: int, now: float, to_send: list) -> None:
        """Create the request entry under the lock; the actual send happens
        after release (see make_next_requests)."""
        probe = self.config.adaptive and pd.ban_count > 0
        self.requests[h] = _Request(h, pd.peer_id, now, probe=probe)
        pd.num_pending += 1
        if probe:
            pd.probe_inflight = True
            bstats.record_probe()
            self.logger.info(
                "blocksync half-open probe", peer=pd.peer_id, height=h
            )
        to_send.append((pd.peer_id, h))

    def _check_stall(self, now: float, to_send: list) -> None:
        """Frontier quiet for STALL_SECS with its request outstanding:
        force-move it to the fastest advertising peer (lowest EWMA)."""
        if self.height != self._progress_h:
            self._progress_h = self.height
            self._progress_t = now
            return
        if now - self._progress_t <= self.config.stall_secs:
            return
        self._progress_t = now  # rate-limit switches to one per window
        req = self.requests.get(self.height)
        if req is None or req.block is not None:
            return
        fastest = None
        for p in self.peers.values():
            if (
                p.peer_id == req.peer_id
                or not (p.banned_until <= now)
                or not (p.base <= self.height <= p.height)
                or p.num_pending >= self._peer_cap(p)
            ):
                continue
            key = (
                p.rtt_ewma if p.rtt_ewma is not None else float("inf"),
                p.peer_id,
            )
            if fastest is None or key < fastest[0]:
                fastest = (key, p)
        if fastest is None:
            return
        old = self.peers.get(req.peer_id)
        if old is not None:
            old.num_pending = max(old.num_pending - 1, 0)
            if req.probe:
                old.probe_inflight = False
        del self.requests[self.height]
        bstats.record_stall_switch()
        self.logger.info(
            "blocksync stall: frontier switched",
            height=self.height,
            slow=req.peer_id,
            fast=fastest[1].peer_id,
        )
        self._assign(fastest[1], self.height, now, to_send)

    def make_next_requests(self) -> None:
        """Fill the sliding window [height, height+WINDOW) with requests
        (reference: makeRequestersRoutine, pool.go:116).  Requests are
        recorded under the lock but SENT after it is released — try_send
        may call back into reactor/switch locks, and holding the pool lock
        across that is a latent lock inversion."""
        to_send: list[tuple[str, int]] = []
        now = self._clock()
        with self._lock:
            max_h = self.max_peer_height()
            wanted = [
                h
                for h in range(self.height, min(self.height + REQUEST_WINDOW, max_h + 1))
                if h not in self.requests
            ]
            # expire timed-out requests (per-peer adaptive timeout); a
            # burst of losses expires many requests in one scan, but the
            # incident is at most ONE strike/ban — punishing per request
            # would escalate a single loss burst straight to the cap
            expired_peers: set = set()
            probe_expired: set = set()
            for h, req in list(self.requests.items()):
                pd = self.peers.get(req.peer_id)
                if req.block is None and now - req.sent_at > self._peer_timeout(pd):
                    bstats.record_timeout()
                    if pd is not None:
                        pd.num_pending = max(pd.num_pending - 1, 0)
                    if req.probe:
                        probe_expired.add(req.peer_id)
                    expired_peers.add(req.peer_id)
                    del self.requests[h]
                    if h not in wanted:
                        wanted.append(h)
            for peer_id in sorted(expired_peers):
                pd = self.peers.get(peer_id)
                if not self.config.adaptive:
                    self.ban_peer(peer_id, 30.0)
                    continue
                if peer_id in probe_expired:
                    # a timed-out half-open probe is the failed
                    # re-admission test: re-ban at the next backoff level
                    self.ban_peer(peer_id, 30.0)
                    continue
                if pd is None or pd.banned_until > now:
                    # escalation needs post-ban evidence — leftover
                    # in-flight requests expiring after the ban landed
                    # are the same incident
                    continue
                # ordinary loss re-assigns without punishment; only a
                # peer that times out BAN_STRIKES scans in a row without
                # serving anything (a mute/stalled peer, not a lossy
                # link) earns a ban
                pd.timeout_strikes += 1
                if pd.timeout_strikes >= self.config.ban_strikes:
                    self.ban_peer(peer_id, 30.0)
            if self.config.adaptive:
                self._check_stall(now, to_send)
            candidates = [
                p
                for p in self.peers.values()
                if p.banned_until <= now
            ]
            if self.config.adaptive:
                # deliberate half-open probes first: every ban-expired
                # peer gets its one probe at the HIGHEST wanted height it
                # can serve — the re-admission test runs promptly, and a
                # still-bad peer never holds the frontier hostage
                for pd in sorted(
                    (
                        p
                        for p in candidates
                        if p.ban_count > 0 and not p.probe_inflight
                    ),
                    key=lambda p: p.peer_id,
                ):
                    for h in sorted(wanted, reverse=True):
                        if pd.base <= h <= pd.height:
                            self._assign(pd, h, now, to_send)
                            wanted.remove(h)
                            break
            for h in sorted(wanted):
                peers = [
                    p
                    for p in candidates
                    if p.base <= h <= p.height
                    and p.num_pending < self._peer_cap(p)
                ]
                if not peers:
                    continue
                pd = self._rng.choice(peers)
                self._assign(pd, h, now, to_send)
            bstats.record_gauges(len(self.requests), len(self.peers))
        # send OUTSIDE the lock; unwind entries whose send failed
        failed: list[int] = []
        for peer_id, h in to_send:
            bstats.record_request()
            if not self.send_request(peer_id, h):
                failed.append(h)
        if failed:
            with self._lock:
                for h in failed:
                    req = self.requests.get(h)
                    if req is None or req.block is not None:
                        continue  # answered or reassigned meanwhile
                    del self.requests[h]
                    bstats.record_send_failure()
                    pd = self.peers.get(req.peer_id)
                    if pd is not None:
                        pd.num_pending = max(pd.num_pending - 1, 0)
                        if req.probe:
                            pd.probe_inflight = False

    # -- progress ----------------------------------------------------------

    def is_caught_up(self) -> bool:
        """Reference: pool.go IsCaughtUp — at (or past) the best peer
        height, with at least one peer heard from."""
        with self._lock:
            if not self.peers:
                return False
            return self.height >= self.max_peer_height()
