"""Process-wide counters for the blocksync pool/reactor.

Deliberately free of jax imports, exactly like ``verifysched/stats``:
``libs/metrics.NodeMetrics`` reads these through callback gauges and a
/metrics scrape must never be the thing that initializes an accelerator
backend.  ``blocksync/pool.py`` and ``blocksync/reactor.py`` write them.

Counters (all guarded by one lock):
  * ``requests``        — block requests actually sent to peers
  * ``send_failures``   — requests whose try_send returned False (unwound)
  * ``timeouts``        — in-flight requests expired by the (adaptive)
    per-peer timeout and re-assigned
  * ``bans``            — ban events (timeout or redo), any backoff level
  * ``probes``          — half-open re-admission probes issued to a peer
    whose ban expired (exactly one in-flight block request)
  * ``probe_passes``    — probes answered with a good block: the peer is
    re-admitted at full window share
  * ``redos``           — bad-block redo_request calls (verification or
    validation failure on a served block)
  * ``no_blocks``       — NoBlockResponse replies (peer advertised a range
    it could not serve)
  * ``stall_switches``  — frontier requests force-moved to the fastest
    advertising peer after the stall window elapsed with no progress
  * ``blocks_received`` — blocks accepted into the pool window
  * ``heights_synced``  — frontier blocks verified + applied (pop_request)
  * ``window_depth``    — in-flight requests right now (gauge-style)
  * ``peers``           — peers currently advertising a range (gauge-style)
  * ``synced_base`` / ``synced_head`` / ``sync_seconds`` — first and last
    applied height plus pool-clock seconds between them, for heights/s
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()


def _zero() -> dict:
    return {
        "requests": 0,
        "send_failures": 0,
        "timeouts": 0,
        "bans": 0,
        "probes": 0,
        "probe_passes": 0,
        "redos": 0,
        "no_blocks": 0,
        "stall_switches": 0,
        "blocks_received": 0,
        "heights_synced": 0,
        "window_depth": 0,
        "peers": 0,
        "synced_base": 0,
        "synced_head": 0,
        "sync_seconds": 0.0,
    }


_STATS = _zero()


def record_request(n: int = 1) -> None:
    with _LOCK:
        _STATS["requests"] += int(n)


def record_send_failure(n: int = 1) -> None:
    with _LOCK:
        _STATS["send_failures"] += int(n)


def record_timeout(n: int = 1) -> None:
    with _LOCK:
        _STATS["timeouts"] += int(n)


def record_ban(n: int = 1) -> None:
    with _LOCK:
        _STATS["bans"] += int(n)


def record_probe(n: int = 1) -> None:
    with _LOCK:
        _STATS["probes"] += int(n)


def record_probe_pass(n: int = 1) -> None:
    with _LOCK:
        _STATS["probe_passes"] += int(n)


def record_redo(n: int = 1) -> None:
    with _LOCK:
        _STATS["redos"] += int(n)


def record_no_block(n: int = 1) -> None:
    with _LOCK:
        _STATS["no_blocks"] += int(n)


def record_stall_switch(n: int = 1) -> None:
    with _LOCK:
        _STATS["stall_switches"] += int(n)


def record_block_received(n: int = 1) -> None:
    with _LOCK:
        _STATS["blocks_received"] += int(n)


def record_height_synced(height: int, now_s: float) -> None:
    """One frontier block applied.  ``now_s`` is the POOL's clock (virtual
    in the sim), so heights/s stays deterministic per seed there."""
    with _LOCK:
        _STATS["heights_synced"] += 1
        if _STATS["synced_base"] == 0:
            _STATS["synced_base"] = int(height)
            _STATS["_t0"] = float(now_s)
        _STATS["synced_head"] = int(height)
        _STATS["sync_seconds"] = max(
            0.0, float(now_s) - _STATS.get("_t0", float(now_s))
        )


def record_gauges(window_depth: int, peers: int) -> None:
    with _LOCK:
        _STATS["window_depth"] = int(window_depth)
        _STATS["peers"] = int(peers)


def snapshot() -> dict:
    """Copy for metrics/tests; adds derived aggregates."""
    with _LOCK:
        out = dict(_STATS)
    out.pop("_t0", None)
    out["heights_per_second"] = (
        out["heights_synced"] / out["sync_seconds"]
        if out["sync_seconds"] > 0
        else 0.0
    )
    return out


def reset() -> None:
    global _STATS
    with _LOCK:
        _STATS = _zero()
