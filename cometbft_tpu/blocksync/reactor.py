"""Blocksync reactor (reference: internal/blocksync/reactor.go).

Channel 0x40 (reference: reactor.go:20).  Serves stored blocks to
catching-up peers and drives the BlockPool: status exchange, parallel
block download, then the two-block verification pipeline —
``verify_commit_light`` on block H using block H+1's LastCommit routes
through the batch-verifier seam (the TPU path), making catchup the
biggest batch-verification consumer in the system (SURVEY.md §2.2).
On completion it hands off to consensus (SwitchToConsensus).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Optional

from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.crypto import sigcache
from cometbft_tpu.libs import log as liblog
from cometbft_tpu.libs import protoenc as pe
from cometbft_tpu.p2p.conn import ChannelDescriptor
from cometbft_tpu.p2p.reactor import Reactor
from cometbft_tpu.state.execution import InvalidBlockError
from cometbft_tpu.types import codec, validation
from cometbft_tpu.types.basic import BlockID

BLOCKSYNC_CHANNEL = 0x40

_MSG_BLOCK_REQUEST = 1
_MSG_BLOCK_RESPONSE = 2
_MSG_NO_BLOCK_RESPONSE = 3
_MSG_STATUS_REQUEST = 4
_MSG_STATUS_RESPONSE = 5

_STATUS_INTERVAL = 5.0
# Faster cadence for switch peers the pool has no range for yet: a lost
# StatusResponse otherwise blanks that peer's serving capacity for a full
# _STATUS_INTERVAL (painful when it is the fastest helper).
_STATUS_RETRY = 1.0
_SWITCH_TO_CONSENSUS_INTERVAL = 1.0
_POOL_TICK = 0.02

# Never heard from ANY peer for this long after starting: assume a solo
# chain / isolated node and run consensus (COMETBFT_TPU_BSYNC_SOLO_GRACE).
_SOLO_GRACE = 10.0

# Fused-verification window: how many frontier commits may share one device
# dispatch (COMETBFT_TPU_BLOCKSYNC_WINDOW; <2 disables the prefetch).
_DEFAULT_WINDOW = 8


def _window_k() -> int:
    try:
        return int(os.environ.get("COMETBFT_TPU_BLOCKSYNC_WINDOW", str(_DEFAULT_WINDOW)))
    except ValueError:
        return _DEFAULT_WINDOW


def _solo_grace() -> float:
    try:
        return float(
            os.environ.get("COMETBFT_TPU_BSYNC_SOLO_GRACE", str(_SOLO_GRACE))
        )
    except ValueError:
        return _SOLO_GRACE


def _enc(kind: int, body: bytes = b"") -> bytes:
    return bytes([kind]) + body


class BlocksyncReactor(Reactor):
    """Reference: internal/blocksync/reactor.go Reactor."""

    def __init__(
        self,
        state,  # sm.State at boot
        block_exec,
        block_store,
        consensus_reactor=None,  # for SwitchToConsensus
        enabled: bool = True,
        logger=None,
        clock=None,  # injected seam (sim: virtual clock); wall monotonic default
        rng=None,  # injected seam (sim: seeded Random) for the pool's choices
    ):
        super().__init__("BlocksyncReactor")
        self.state = state
        self.block_exec = block_exec
        self.block_store = block_store
        self.consensus_reactor = consensus_reactor
        self.logger = logger or liblog.nop_logger()
        self.syncing = enabled
        self._clock = clock if clock is not None else time.monotonic
        self._solo_grace = _solo_grace()
        start = max(block_store.height() + 1, state.initial_height)
        self.pool = BlockPool(
            start, self._send_block_request, self.logger, clock=clock, rng=rng
        )
        self._thread: Optional[threading.Thread] = None
        self.synced_at: Optional[float] = None
        # tick() pacing state: -inf so the first tick broadcasts status and
        # runs the switch check immediately, as the wall-clock loop did
        self._last_status = float("-inf")
        self._last_status_retry = float("-inf")
        self._status_req_at: Optional[float] = None
        self._last_switch_check = float("-inf")
        # fused-prefetch memo: commit fingerprint -> height, so a window is
        # dispatched once and apply/redo ticks never re-dispatch it
        self._fused: dict[bytes, int] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                BLOCKSYNC_CHANNEL,
                priority=5,
                send_queue_capacity=1000,
                recv_message_capacity=64 * 1024 * 1024,
            )
        ]

    def on_start(self) -> None:
        if self.syncing:
            self._start_pool()

    def _start_pool(self) -> None:
        self._thread = threading.Thread(
            target=self._pool_routine, name="blocksync-pool", daemon=True
        )
        self._thread.start()

    def start_sync(self, state) -> None:
        """Hand-off from statesync (reference: bcReactor.SwitchToBlockSync,
        node/setup.go:587-601): resume block download from the snapshot
        height."""
        self.state = state
        self.pool.height = max(
            self.block_store.height() + 1, state.last_block_height + 1
        )
        self.pool._started_at = self._clock()
        self.syncing = True
        self._start_pool()

    # -- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer) -> None:
        # announce our range + ask for theirs
        peer.try_send(BLOCKSYNC_CHANNEL, self._status_response())
        self._status_req_at = self._clock()
        peer.try_send(BLOCKSYNC_CHANNEL, _enc(_MSG_STATUS_REQUEST))

    def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id)

    def _status_response(self) -> bytes:
        body = pe.t_varint(1, self.block_store.height()) + pe.t_varint(
            2, self.block_store.base()
        )
        return _enc(_MSG_STATUS_RESPONSE, body)

    def _send_block_request(self, peer_id: str, height: int) -> bool:
        sw = self.switch
        if sw is None:
            return False
        peer = sw.get_peer(peer_id)
        if peer is None:
            return False
        return peer.try_send(
            BLOCKSYNC_CHANNEL, _enc(_MSG_BLOCK_REQUEST, pe.t_varint(1, height))
        )

    # -- receive -----------------------------------------------------------

    def receive(self, chan_id: int, peer, msg_bytes: bytes) -> None:
        kind, body = msg_bytes[0], msg_bytes[1:]
        if kind == _MSG_BLOCK_REQUEST:
            f = pe.fields_dict(body)
            height = pe.to_int64(f.get(1, [0])[-1])
            block = self.block_store.load_block(height)
            if block is not None:
                body = pe.t_message(1, codec.encode_block(block), always=True)
                # attach the extended commit when stored (vote extensions):
                # a catching-up validator needs it to propose (reference:
                # BlockResponse.ext_commit).  Gated on the enable height:
                # no store read on the serve path for extension-less chains.
                ext_h = (
                    self.state.consensus_params.feature.vote_extensions_enable_height
                )
                ec = (
                    self.block_store.load_extended_commit(height)
                    if 0 < ext_h <= height
                    else None
                )
                if ec is not None:
                    body += pe.t_message(
                        2, codec.encode_extended_commit(ec), always=True
                    )
                peer.try_send(
                    BLOCKSYNC_CHANNEL, _enc(_MSG_BLOCK_RESPONSE, body)
                )
            else:
                peer.try_send(
                    BLOCKSYNC_CHANNEL,
                    _enc(_MSG_NO_BLOCK_RESPONSE, pe.t_varint(1, height)),
                )
        elif kind == _MSG_BLOCK_RESPONSE:
            f = pe.fields_dict(body)
            block = codec.decode_block(f[1][-1])
            ec = (
                codec.decode_extended_commit(f[2][-1]) if 2 in f else None
            )
            self.pool.add_block(peer.id, block, ec)
        elif kind == _MSG_NO_BLOCK_RESPONSE:
            f = pe.fields_dict(body)
            self.pool.no_block(peer.id, pe.to_int64(f.get(1, [0])[-1]))
        elif kind == _MSG_STATUS_REQUEST:
            peer.try_send(BLOCKSYNC_CHANNEL, self._status_response())
        elif kind == _MSG_STATUS_RESPONSE:
            f = pe.fields_dict(body)
            height = pe.to_int64(f.get(1, [0])[-1])
            base = pe.to_int64(f.get(2, [0])[-1])
            # the status handshake doubles as the RTT bootstrap: without
            # it a new peer has no EWMA and falls back to the flat
            # 15 s REQUEST_TIMEOUT — one dropped first response would
            # wedge a frontier height for the full legacy timeout
            rtt = None
            if self._status_req_at is not None:
                rtt = self._clock() - self._status_req_at
            self.pool.set_peer_range(peer.id, base, height, rtt=rtt)

    # -- the sync loop (reference: reactor.go poolRoutine) -----------------

    def _check_ext_commit(
        self, block, block_id, ec, second_last_commit
    ) -> Optional[str]:
        return check_ext_commit(
            self.state.chain_id,
            self.state.validators,
            block,
            block_id,
            ec,
            second_last_commit,
        )


    def tick(self) -> bool:
        """One scheduler pass: periodic status broadcast, the
        switch-to-consensus check, window refill, then the verify/apply
        frontier.  Returns True when a block was processed (more frontier
        work may be immediately available).  The wall-clock thread loop
        wraps this; the deterministic sim drives it directly off the
        virtual clock (sim/blocksync.py)."""
        now = self._clock()
        if now - self._last_status > _STATUS_INTERVAL:
            self._last_status = now
            if self.switch is not None:
                self._status_req_at = now
                self.switch.broadcast(
                    BLOCKSYNC_CHANNEL, _enc(_MSG_STATUS_REQUEST)
                )
        elif now - self._last_status_retry > _STATUS_RETRY:
            # re-ask only the peers whose range we still don't know: their
            # StatusResponse (or our request) was lost in transit
            self._last_status_retry = now
            sw_peers = getattr(self.switch, "peers", None)
            if sw_peers:
                with self.pool._lock:
                    known = set(self.pool.peers)
                for p in list(sw_peers.values()):
                    if p.id not in known:
                        self._status_req_at = now
                        p.try_send(
                            BLOCKSYNC_CHANNEL, _enc(_MSG_STATUS_REQUEST)
                        )
        if now - self._last_switch_check > _SWITCH_TO_CONSENSUS_INTERVAL:
            self._last_switch_check = now
            if self._maybe_switch_to_consensus():
                return False
        self.pool.make_next_requests()
        return self._process_blocks()

    def _pool_routine(self) -> None:
        while self.is_running and self.syncing:
            try:
                if not self.tick():
                    time.sleep(_POOL_TICK)
            except Exception as e:  # noqa: BLE001
                self.logger.error("blocksync pool error", err=repr(e))
                time.sleep(0.5)

    # -- fused window prefetch --------------------------------------------

    @staticmethod
    def _commit_fingerprint(height: int, commit) -> bytes:
        """Cheap per-tick memo key: O(1) in validator count (hashing all
        10k signatures every 20 ms pool tick would be ~MBs of SHA-256 per
        tick once the window is already fused).  A redo replaces the whole
        served commit, so height + block id + round + size + the first and
        last signatures distinguish every case that matters; a collision
        merely skips a SPECULATIVE prefetch — the authoritative sequential
        verification is unaffected."""
        h = hashlib.sha256()
        h.update(height.to_bytes(8, "little", signed=True))
        h.update(commit.block_id.hash)
        h.update(commit.round_.to_bytes(4, "little", signed=True))
        h.update(len(commit.signatures).to_bytes(4, "little"))
        if commit.signatures:
            first, last = commit.signatures[0], commit.signatures[-1]
            h.update(bytes([first.block_id_flag]))
            h.update(first.signature)
            h.update(bytes([last.block_id_flag]))
            h.update(last.signature)
        return h.digest()

    def _prefetch_window(self) -> None:
        """Speculatively verify a window of frontier commits in ONE fused
        device dispatch (ops.verify.verify_segments), seeding the signature
        cache so the authoritative per-height ``verify_commit_light`` in
        ``_process_blocks`` resolves without re-dispatching.

        Safety: verdicts are keyed on the full (pub, msg, sig) triple.  A
        misprediction (validator set changed mid-window) caches triples the
        real verification never queries — it degrades to today's one-
        dispatch-per-height behavior, never to a wrong answer.  Block
        *application* stays strictly sequential in ``_process_blocks``;
        a bad block still takes the same redo/ban path there."""
        k = _window_k()
        if k < 2 or not sigcache.SigCache.enabled():
            return
        if not validation.fused_verify_eligible([self.state.validators]):
            # no trusted accelerator, every device breaker open (catchup
            # then degrades to the authoritative per-commit host verify in
            # _process_blocks; prefetch resumes once a half-open probe
            # passes), or non-ed25519 validators — nothing to fuse
            return
        peek = getattr(self.pool, "peek_window", None)
        if peek is None:
            return
        window = peek(k)
        if len(window) < 3:
            return  # the two-block pipeline covers short runs
        to_fuse = []  # (fingerprint, height, prepared, bits, miss_indices)
        for i in range(len(window) - 1):
            h = window[i][0]
            commit = window[i + 1][1].last_commit
            fp = self._commit_fingerprint(h, commit)
            if fp in self._fused:
                continue
            # best-effort validator-set prediction past the frontier; a miss
            # is safe (see docstring)
            vals = self.state.validators if i == 0 else self.state.next_validators
            try:
                # count_all: cover the full-verification superset, so both
                # the frontier verify_commit_light AND validate_block's
                # apply-time verify_commit resolve from cache
                prepared = validation.prepare_commit_light(
                    self.state.chain_id,
                    vals,
                    commit.block_id,
                    h,
                    commit,
                    count_all=True,
                )
            except validation.CommitVerificationError:
                continue  # malformed: let the sequential path raise/redo/ban
            bits, miss = sigcache.partition_misses(
                prepared.pubs, prepared.msgs, prepared.sigs
            )
            if not miss:
                self._fused[fp] = h  # fully cached already
                continue
            to_fuse.append((fp, h, prepared, bits, miss))
        if not to_fuse:
            return
        from cometbft_tpu.libs import tracing
        from cometbft_tpu.ops import verify as ov

        try:
            with tracing.span(
                "blocksync.prefetch",
                commits=len(to_fuse),
                h0=to_fuse[0][1],
                sigs=sum(len(miss) for *_, miss in to_fuse),
            ):
                results = ov.verify_segments(
                    [
                        (
                            [p.pubs[j] for j in miss],
                            [p.msgs[j] for j in miss],
                            [p.sigs[j] for j in miss],
                        )
                        for _, _, p, _, miss in to_fuse
                    ]
                )
        except Exception as e:  # noqa: BLE001 — prefetch must never stall sync
            self.logger.error("fused verify prefetch failed", err=repr(e))
            return
        for (fp, h, p, bits, miss), got in zip(to_fuse, results):
            sigcache.writeback(p.pubs, p.msgs, p.sigs, bits, miss, got)
            self._fused[fp] = h
        # trim memo entries behind the frontier
        frontier = self.pool.height
        if len(self._fused) > 4 * max(k, 1):
            self._fused = {
                fp: h for fp, h in self._fused.items() if h >= frontier
            }

    def _process_blocks(self) -> bool:
        """Verify + apply the frontier block using the NEXT block's
        LastCommit (reference: reactor.go:541)."""
        try:
            self._prefetch_window()
        except Exception as e:  # noqa: BLE001 — speculative only
            self.logger.error("blocksync prefetch error", err=repr(e))
        first, second, first_peer, second_peer, first_ext = (
            self.pool.peek_two_blocks()
        )
        if first is None or second is None:
            return False
        first_parts = first.make_part_set()
        first_id = BlockID(hash=first.hash(), part_set_header=first_parts.header)
        from cometbft_tpu import verifysched

        try:
            # THE verification: batch Ed25519 through the pluggable seam,
            # tagged bulk-priority for the shared verify scheduler —
            # catchup signature batches must never delay (and are the
            # first to be shed behind) live consensus votes
            with verifysched.priority_class(verifysched.PRIO_BLOCKSYNC):
                validation.verify_commit_light(
                    self.state.chain_id,
                    self.state.validators,
                    first_id,
                    first.header.height,
                    second.last_commit,
                )
                # The commit only signs the header hash; the block body
                # arrived from an untrusted peer and keeps its wire-carried
                # hashes (fill_header_hashes fills empty fields only).
                # Fully validate body-vs-header and header-vs-state before
                # applying, exactly as the reference does
                # (internal/blocksync/reactor.go:546 ValidateBlock) —
                # otherwise a peer could pair the legitimately signed
                # header with tampered txs/last_commit/evidence.
                self.block_exec.validate_block(self.state, first)
        except (validation.CommitVerificationError, InvalidBlockError) as e:
            self.logger.error(
                "invalid block in blocksync",
                height=first.header.height,
                err=str(e),
            )
            # ban both providers and retry (reference: reactor.go bad-block path)
            self.pool.redo_request(first.header.height)
            self.pool.redo_request(first.header.height + 1)
            for pid in (first_peer, second_peer):
                if self.switch is not None and pid:
                    p = self.switch.get_peer(pid)
                    if p is not None:
                        self.switch.stop_peer_for_error(p, e)
            return True
        ext_enabled = self.state.consensus_params.feature.vote_extensions_enable_height
        need_ext = 0 < ext_enabled <= first.header.height
        if need_ext:
            err = self._check_ext_commit(
                first, first_id, first_ext, second.last_commit
            )
            if err is not None:
                self.logger.error(
                    "bad extended commit in blocksync",
                    height=first.header.height,
                    err=err,
                )
                self.pool.redo_request(first.header.height)
                if self.switch is not None and first_peer:
                    p = self.switch.get_peer(first_peer)
                    if p is not None:
                        self.switch.stop_peer_for_error(p, ValueError(err))
                return True
        self.block_store.save_block(
            first,
            first_parts,
            second.last_commit,
            extended_commit=first_ext if need_ext else None,
        )
        self.state = self.block_exec.apply_verified_block(
            self.state, first_id, first
        )
        self.pool.pop_request()
        if self.block_store.height() % 100 == 0:
            self.logger.info(
                "blocksync progress",
                height=self.block_store.height(),
                target=self.pool.max_peer_height(),
            )
        return True

    def _maybe_switch_to_consensus(self) -> bool:
        """Reference: poolRoutine's switchToConsensusTicker."""
        if not self.pool.is_caught_up():
            # never heard from any peer after a grace period: we are alone
            # (solo chain / isolated) — run consensus.  A TEMPORARILY empty
            # peer set mid-sync must NOT trigger this: reconnect will refill
            if (
                not self.pool.ever_had_peers
                and self._clock() - self.pool._started_at > self._solo_grace
            ):
                return self._switch()
            return False
        return self._switch()

    def _switch(self) -> bool:
        self.syncing = False
        self.synced_at = self._clock()
        self.logger.info(
            "blocksync complete, switching to consensus",
            height=self.block_store.height(),
        )
        if self.consensus_reactor is not None:
            self.consensus_reactor.switch_to_consensus(self.state)
        return True



def check_ext_commit(
    chain_id, validators, block, block_id, ec, second_last_commit
) -> Optional[str]:
    """Validate a served extended commit.  The reference only checks
    structure (ExtendedCommit.EnsureExtensions; reactor.go:559 has a
    TODO about validating further) — we additionally verify +2/3 of
    the commit signatures (skipped when identical to the next block's
    already-verified LastCommit) AND every extension signature, both
    through the batch seam: extensions are NOT covered by the commit
    signatures, so a structural check alone would let one malicious
    peer serve real commit sigs with tampered extensions that later
    feed the app's ExtendedCommitInfo."""
    if ec is None:
        return "peer served no extended commit for an extension height"
    if ec.height != block.header.height:
        return f"extended commit height {ec.height} != block"
    if ec.block_id != block_id:
        return "extended commit is for a different block"
    for cs in ec.extended_signatures:
        if cs.for_block() and not cs.extension_signature:
            return "commit signature missing its extension signature"
    # bulk class for the whole check: ext-commit signature batches are
    # catchup traffic like the rest of blocksync — they must never ride
    # the shed-exempt consensus class ahead of live votes
    from cometbft_tpu import verifysched

    with verifysched.priority_class(verifysched.PRIO_BLOCKSYNC):
        return _check_ext_commit_sigs(
            chain_id, validators, block, block_id, ec, second_last_commit
        )


def _check_ext_commit_sigs(
    chain_id, validators, block, block_id, ec, second_last_commit
) -> Optional[str]:
    base = ec.to_commit()
    if base.signatures != second_last_commit.signatures:
        # usually identical to the (already verified) next block's
        # LastCommit; only a genuinely different signature set pays a
        # second +2/3 verification
        try:
            validation.verify_commit_light(
                chain_id,
                validators,
                block_id,
                block.header.height,
                base,
            )
        except Exception as e:  # noqa: BLE001
            return f"extended commit fails +2/3 verification: {e}"
    # Extension signatures are NOT covered by the commit signatures, so
    # verify them against the validator keys through the batch seam —
    # otherwise one malicious peer could serve real commit sigs with
    # tampered extensions, poisoning the app's future ExtendedCommitInfo.
    from cometbft_tpu.crypto import batch as cbatch
    from cometbft_tpu.types.canonical import (
        canonical_vote_extension_sign_bytes,
    )

    vals = validators.validators
    entries = []
    for i, cs in enumerate(ec.extended_signatures):
        if not cs.for_block():
            continue
        if i >= len(vals):
            return "extended commit has more signatures than validators"
        msg = canonical_vote_extension_sign_bytes(
            chain_id, ec.height, ec.round_, cs.extension
        )
        entries.append((vals[i].pub_key, msg, cs.extension_signature))
    # batch when every key supports it AND the key type is homogeneous
    # (same discipline as validation._should_batch — one batch verifier
    # handles one key type); per-signature fallback otherwise, so mixed or
    # secp256k1 validator sets must not stall blocksync
    if (
        len(entries) >= 2
        and len({getattr(pk, "type_", None) for pk, _, _ in entries}) == 1
        and all(cbatch.supports_batch_verifier(pk) for pk, _, _ in entries)
    ):
        bv = cbatch.create_batch_verifier(entries[0][0])
        for pk, msg, sig in entries:
            bv.add(pk, msg, sig)
        ok, _bits = bv.verify()
        if not ok:
            return "extension signature verification failed"
    else:
        for pk, msg, sig in entries:
            if not pk.verify_signature(msg, sig):
                return "extension signature verification failed"
    return None
