from cometbft_tpu.blocksync.pool import BlockPool
from cometbft_tpu.blocksync.reactor import BLOCKSYNC_CHANNEL, BlocksyncReactor

__all__ = ["BlockPool", "BlocksyncReactor", "BLOCKSYNC_CHANNEL"]
