from cometbft_tpu.cmd.main import main

if __name__ == "__main__":
    raise SystemExit(main())
