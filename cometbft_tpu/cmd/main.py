"""CLI entry point (reference: cmd/cometbft/main.go:15-35 + commands/).

Commands: init, start, show-node-id, show-validator, gen-validator,
unsafe-reset-all, version, testnet, rollback.  ``python -m cometbft_tpu.cmd
<command> --home <dir>``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time

from cometbft_tpu.config import config as cfgmod
from cometbft_tpu.version import __version__


def _load_config(home: str):
    path = os.path.join(home, "config", "config.toml")
    if os.path.exists(path):
        cfg = cfgmod.load_config(home)
    else:
        cfg = cfgmod.default_config()
    cfg.base.home = home
    return cfg



def _run_until_signal(cleanup_fn) -> int:
    """Block until SIGINT/SIGTERM, then run cleanup (shared by the daemon
    commands: start, inspect, light)."""
    import signal
    import time as _time

    stop = {"flag": False}

    def _sig(*_a):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        while not stop["flag"]:
            _time.sleep(0.2)
    finally:
        cleanup_fn()
    return 0


def cmd_init(args) -> int:
    """Reference: commands/init.go — write config, genesis, node key, privval."""
    from cometbft_tpu.node.nodekey import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.basic import Timestamp
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    home = args.home
    cfg = cfgmod.default_config()
    cfg.base.home = home
    cfgmod.write_config(cfg)

    pv = FilePV.load_or_generate(
        os.path.join(home, cfg.base.priv_validator_key_file),
        os.path.join(home, cfg.base.priv_validator_state_file),
    )
    NodeKey.load_or_generate(os.path.join(home, cfg.base.node_key_file))

    genesis_path = os.path.join(home, cfg.base.genesis_file)
    if not os.path.exists(genesis_path):
        chain_id = args.chain_id or f"test-chain-{int(time.time()) % 100000}"
        gdoc = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Timestamp.now(),
            validators=[GenesisValidator(pv.pub_key(), 10)],
        )
        os.makedirs(os.path.dirname(genesis_path), exist_ok=True)
        with open(genesis_path, "w") as f:
            f.write(gdoc.to_json())
        print(f"Generated genesis file {genesis_path}")
    print(f"Initialized node in {home}")
    return 0


def cmd_start(args) -> int:
    """Reference: commands/run_node.go."""
    from cometbft_tpu.node.node import Node

    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    err = cfg.validate_basic()
    if err:
        print(f"invalid config: {err}", file=sys.stderr)
        return 1
    node = Node(cfg)
    node.start()

    stop = []

    def on_signal(signum, frame):
        stop.append(signum)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        node.stop()
    return 0


def cmd_show_node_id(args) -> int:
    from cometbft_tpu.node.nodekey import NodeKey

    cfg = _load_config(args.home)
    nk = NodeKey.load_or_generate(
        os.path.join(args.home, cfg.base.node_key_file)
    )
    print(nk.node_id)
    return 0


def cmd_show_validator(args) -> int:
    import base64

    from cometbft_tpu.privval.file_pv import FilePV

    cfg = _load_config(args.home)
    pv = FilePV.load_or_generate(
        os.path.join(args.home, cfg.base.priv_validator_key_file),
        os.path.join(args.home, cfg.base.priv_validator_state_file),
    )
    print(
        json.dumps(
            {
                "type": "tendermint/PubKeyEd25519",
                "value": base64.b64encode(pv.pub_key().bytes()).decode(),
            }
        )
    )
    return 0


def cmd_gen_validator(args) -> int:
    import base64

    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    priv = Ed25519PrivKey.generate()
    print(
        json.dumps(
            {
                "address": priv.pub_key().address().hex().upper(),
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(priv.pub_key().bytes()).decode(),
                },
                "priv_key": {
                    "type": "tendermint/PrivKeyEd25519",
                    "value": base64.b64encode(priv.bytes()).decode(),
                },
            },
            indent=2,
        )
    )
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """Reference: commands/reset.go — wipe data dir, keep config + keys,
    reset privval state."""
    cfg = _load_config(args.home)
    data_dir = os.path.join(args.home, cfg.base.db_dir)
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir)
        os.makedirs(data_dir)
        print(f"Removed all data in {data_dir}")
    pv_state = os.path.join(args.home, cfg.base.priv_validator_state_file)
    os.makedirs(os.path.dirname(pv_state), exist_ok=True)
    with open(pv_state, "w") as f:
        json.dump({"height": 0, "round": 0, "step": 0}, f)
    print("Reset private validator state")
    return 0


def cmd_testnet(args) -> int:
    """Reference: commands/testnet.go — generate N validator home dirs
    sharing one genesis."""
    from cometbft_tpu.node.nodekey import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.basic import Timestamp
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    n = args.v
    out = args.o
    chain_id = args.chain_id or f"testnet-{int(time.time()) % 100000}"
    pvs = []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = cfgmod.default_config()
        cfg.base.home = home
        cfg.rpc.laddr = f"tcp://127.0.0.1:{26657 + 10 * i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{26656 + 10 * i}"
        cfgmod.write_config(cfg)
        pv = FilePV.load_or_generate(
            os.path.join(home, cfg.base.priv_validator_key_file),
            os.path.join(home, cfg.base.priv_validator_state_file),
        )
        NodeKey.load_or_generate(os.path.join(home, cfg.base.node_key_file))
        pvs.append(pv)

    gdoc = GenesisDoc(
        chain_id=chain_id,
        genesis_time=Timestamp.now(),
        validators=[GenesisValidator(pv.pub_key(), 10) for pv in pvs],
    )
    for i in range(n):
        path = os.path.join(out, f"node{i}", "config", "genesis.json")
        with open(path, "w") as f:
            f.write(gdoc.to_json())
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_rollback(args) -> int:
    """Reference: commands/rollback.go — roll state back one height."""
    from cometbft_tpu.node.rollback import rollback_state

    cfg = _load_config(args.home)
    height, app_hash = rollback_state(cfg, remove_block=args.hard)
    print(f"Rolled back state to height {height} and hash {app_hash.hex()}")
    return 0


def cmd_version(args) -> int:
    print(__version__)
    return 0



def cmd_inspect(args) -> int:
    """Reference: internal/inspect — read-only RPC over the data dir."""
    from cometbft_tpu.node.inspect import InspectNode

    cfg = _load_config(args.home)
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    node = InspectNode(cfg).serve()
    print(
        f"Inspect server listening on {cfg.rpc.laddr} "
        f"(store height {node.block_store.height()})"
    )
    return _run_until_signal(node.close)


def cmd_light(args) -> int:
    """Reference: cmd light — run a light-client RPC proxy daemon."""
    from cometbft_tpu.light import (
        SKIPPING,
        HTTPProvider,
        LightClient,
        LightStore,
        TrustOptions,
    )
    from cometbft_tpu.light.proxy import LightProxy
    from cometbft_tpu.store.kv import SqliteKV

    primary = HTTPProvider(args.chain_id, args.primary)
    witnesses = [
        HTTPProvider(args.chain_id, w) for w in (args.witnesses or "").split(",") if w
    ]
    if bool(args.trust_height) != bool(args.trust_hash):
        print("error: --trust-height and --trust-hash must be given together")
        return 1
    if args.trust_height:
        opts = TrustOptions(
            period_s=args.trust_period,
            height=args.trust_height,
            hash=bytes.fromhex(args.trust_hash),
        )
    else:
        lb = primary.light_block(0)
        opts = TrustOptions(
            period_s=args.trust_period, height=lb.height, hash=lb.hash()
        )
        print(f"WARNING: trusting the primary's latest header blindly "
              f"(height {lb.height}); pass --trust-height/--trust-hash")
    os.makedirs(os.path.join(args.home, "light"), exist_ok=True)
    store = LightStore(SqliteKV(os.path.join(args.home, "light", "trust.db")))
    client = LightClient(args.chain_id, opts, primary, witnesses, store)
    proxy = LightProxy(client, args.primary, laddr=args.laddr)
    proxy.start()
    print(f"Light client proxy listening on {args.laddr} "
          f"(trusted height {client.trusted_light_block().height})")
    return _run_until_signal(proxy.stop)


def cmd_confix(args) -> int:
    """Reference: internal/confix — migrate config.toml to this version."""
    from cometbft_tpu.config.confix import upgrade

    report = upgrade(args.home, dry_run=args.dry_run)
    for key in report["carried"]:
        print(f"carried: {key}")
    for key in report["unknown"]:
        print(f"unknown (dropped): {key}")
    if report["backup"]:
        print(f"backup written to {report['backup']}")
    elif args.dry_run:
        print("dry run: no files written")
    return 0


def cmd_compact_db(args) -> int:
    """Reference: commands/compact.go — compact the embedded database."""
    from cometbft_tpu.store.kv import SqliteKV

    cfg = _load_config(args.home)
    path = os.path.join(cfg.base.home, cfg.base.db_dir, "chain.db")
    if not os.path.exists(path):
        print(f"no database at {path}")
        return 1
    before = os.path.getsize(path)
    kv = SqliteKV(path)
    kv.compact()
    kv.close()
    after = os.path.getsize(path)
    print(f"compacted {path}: {before} -> {after} bytes")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cometbft_tpu", description="TPU-native BFT consensus node"
    )
    p.add_argument(
        "--home",
        default=os.environ.get("CMTHOME", os.path.expanduser("~/.cometbft_tpu")),
        help="node home directory",
    )
    sub = p.add_subparsers(dest="command")

    sp = sub.add_parser("init", help="initialize a node home directory")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--proxy_app", default="")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("show-node-id", help="show the p2p node ID")
    sp.set_defaults(fn=cmd_show_node_id)

    sp = sub.add_parser("show-validator", help="show validator pubkey")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("gen-validator", help="generate a validator keypair")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("unsafe-reset-all", help="wipe blockchain data")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("testnet", help="generate testnet home dirs")
    sp.add_argument("--v", type=int, default=4, help="number of validators")
    sp.add_argument("--o", default="./mytestnet", help="output directory")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("rollback", help="roll back one block")
    sp.add_argument("--hard", action="store_true", help="also remove the block")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser("inspect", help="read-only RPC over the data dir")
    sp.add_argument("--rpc-laddr", default="", help="override rpc listen addr")
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser("light", help="run a light-client RPC proxy")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True, help="primary node RPC URL")
    sp.add_argument("--witnesses", default="", help="comma-separated witness RPC URLs")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.add_argument("--trust-height", type=int, default=0)
    sp.add_argument("--trust-hash", default="")
    sp.add_argument("--trust-period", type=int, default=168 * 3600)
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("confix", help="migrate config.toml to this version")
    sp.add_argument("--dry-run", action="store_true")
    sp.set_defaults(fn=cmd_confix)

    sp = sub.add_parser("compact-db", help="compact the embedded database")
    sp.set_defaults(fn=cmd_compact_db)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 1
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
