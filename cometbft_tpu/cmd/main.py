"""CLI entry point (reference: cmd/cometbft/main.go:15-35 + commands/).

Commands: init, start, show-node-id, show-validator, gen-validator,
unsafe-reset-all, version, testnet, rollback.  ``python -m cometbft_tpu.cmd
<command> --home <dir>``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time

from cometbft_tpu.config import config as cfgmod
from cometbft_tpu.version import __version__


def _load_config(home: str):
    path = os.path.join(home, "config", "config.toml")
    if os.path.exists(path):
        cfg = cfgmod.load_config(home)
    else:
        cfg = cfgmod.default_config()
    cfg.base.home = home
    return cfg



def _run_until_signal(cleanup_fn) -> int:
    """Block until SIGINT/SIGTERM, then run cleanup (shared by the daemon
    commands: start, inspect, light)."""
    import signal
    import time as _time

    stop = {"flag": False}

    def _sig(*_a):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        while not stop["flag"]:
            _time.sleep(0.2)
    finally:
        cleanup_fn()
    return 0


def cmd_init(args) -> int:
    """Reference: commands/init.go — write config, genesis, node key, privval."""
    from cometbft_tpu.node.nodekey import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.basic import Timestamp
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    home = args.home
    cfg = cfgmod.default_config()
    cfg.base.home = home
    cfgmod.write_config(cfg)

    pv = FilePV.load_or_generate(
        os.path.join(home, cfg.base.priv_validator_key_file),
        os.path.join(home, cfg.base.priv_validator_state_file),
    )
    NodeKey.load_or_generate(os.path.join(home, cfg.base.node_key_file))

    genesis_path = os.path.join(home, cfg.base.genesis_file)
    if not os.path.exists(genesis_path):
        chain_id = args.chain_id or f"test-chain-{int(time.time()) % 100000}"
        gdoc = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Timestamp.now(),
            validators=[GenesisValidator(pv.pub_key(), 10)],
        )
        os.makedirs(os.path.dirname(genesis_path), exist_ok=True)
        with open(genesis_path, "w") as f:
            f.write(gdoc.to_json())
        print(f"Generated genesis file {genesis_path}")
    print(f"Initialized node in {home}")
    return 0


def cmd_start(args) -> int:
    """Reference: commands/run_node.go."""
    from cometbft_tpu.node.node import Node

    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    err = cfg.validate_basic()
    if err:
        print(f"invalid config: {err}", file=sys.stderr)
        return 1
    node = Node(cfg)
    node.start()

    stop = []

    def on_signal(signum, frame):
        stop.append(signum)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        node.stop()
    return 0


def cmd_show_node_id(args) -> int:
    from cometbft_tpu.node.nodekey import NodeKey

    cfg = _load_config(args.home)
    nk = NodeKey.load_or_generate(
        os.path.join(args.home, cfg.base.node_key_file)
    )
    print(nk.node_id)
    return 0


def cmd_show_validator(args) -> int:
    import base64

    from cometbft_tpu.privval.file_pv import FilePV

    cfg = _load_config(args.home)
    pv = FilePV.load_or_generate(
        os.path.join(args.home, cfg.base.priv_validator_key_file),
        os.path.join(args.home, cfg.base.priv_validator_state_file),
    )
    print(
        json.dumps(
            {
                "type": "tendermint/PubKeyEd25519",
                "value": base64.b64encode(pv.pub_key().bytes()).decode(),
            }
        )
    )
    return 0


def cmd_gen_validator(args) -> int:
    import base64

    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    priv = Ed25519PrivKey.generate()
    print(
        json.dumps(
            {
                "address": priv.pub_key().address().hex().upper(),
                "pub_key": {
                    "type": "tendermint/PubKeyEd25519",
                    "value": base64.b64encode(priv.pub_key().bytes()).decode(),
                },
                "priv_key": {
                    "type": "tendermint/PrivKeyEd25519",
                    "value": base64.b64encode(priv.bytes()).decode(),
                },
            },
            indent=2,
        )
    )
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """Reference: commands/reset.go — wipe data dir, keep config + keys,
    reset privval state."""
    cfg = _load_config(args.home)
    data_dir = os.path.join(args.home, cfg.base.db_dir)
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir)
        os.makedirs(data_dir)
        print(f"Removed all data in {data_dir}")
    pv_state = os.path.join(args.home, cfg.base.priv_validator_state_file)
    os.makedirs(os.path.dirname(pv_state), exist_ok=True)
    with open(pv_state, "w") as f:
        json.dump({"height": 0, "round": 0, "step": 0}, f)
    print("Reset private validator state")
    return 0


def cmd_testnet(args) -> int:
    """Reference: commands/testnet.go — generate N validator home dirs
    sharing one genesis."""
    from cometbft_tpu.node.nodekey import NodeKey
    from cometbft_tpu.privval.file_pv import FilePV
    from cometbft_tpu.types.basic import Timestamp
    from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator

    n = args.v
    out = args.o
    chain_id = args.chain_id or f"testnet-{int(time.time()) % 100000}"
    pvs = []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = cfgmod.default_config()
        cfg.base.home = home
        cfg.rpc.laddr = f"tcp://127.0.0.1:{26657 + 10 * i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{26656 + 10 * i}"
        cfgmod.write_config(cfg)
        pv = FilePV.load_or_generate(
            os.path.join(home, cfg.base.priv_validator_key_file),
            os.path.join(home, cfg.base.priv_validator_state_file),
        )
        NodeKey.load_or_generate(os.path.join(home, cfg.base.node_key_file))
        pvs.append(pv)

    gdoc = GenesisDoc(
        chain_id=chain_id,
        genesis_time=Timestamp.now(),
        validators=[GenesisValidator(pv.pub_key(), 10) for pv in pvs],
    )
    for i in range(n):
        path = os.path.join(out, f"node{i}", "config", "genesis.json")
        with open(path, "w") as f:
            f.write(gdoc.to_json())
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_rollback(args) -> int:
    """Reference: commands/rollback.go — roll state back one height."""
    from cometbft_tpu.node.rollback import rollback_state

    cfg = _load_config(args.home)
    height, app_hash = rollback_state(cfg, remove_block=args.hard)
    print(f"Rolled back state to height {height} and hash {app_hash.hex()}")
    return 0


def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_sim(args) -> int:
    """Deterministic simulation: run a fault scenario on virtual time and
    report whether the cluster reached the target height with the
    agreement/validity/WAL invariants intact.  Same seed ⇒ byte-identical
    event trace (sim/ package; docs/sim-design.md)."""
    from cometbft_tpu.sim import SCENARIOS, run_scenario

    if args.list:
        for name, sc in SCENARIOS.items():
            print(f"{name:20s} {sc.description}")
        return 0
    if args.scenario not in SCENARIOS:
        print(
            f"unknown scenario {args.scenario!r}; --list shows the options",
            file=sys.stderr,
        )
        return 1
    result = run_scenario(
        args.scenario,
        args.seed,
        n_vals=args.validators or None,
        target_height=args.height or None,
        max_time=args.max_time or None,
    )
    if args.trace_out:
        with open(args.trace_out, "w") as f:
            f.write("\n".join(result.trace) + "\n")
        print(f"wrote {len(result.trace)} trace lines to {args.trace_out}")
    summary = result.summary()
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(
            "scenario=%s seed=%d reached=%s heights=%s virtual_time=%.1fs "
            "events=%d commits_verified=%d"
            % (
                summary["scenario"],
                summary["seed"],
                summary["reached"],
                summary["heights"],
                summary["virtual_time"],
                summary["events"],
                summary["commits_verified"],
            )
        )
        for v in summary["violations"]:
            print(f"INVARIANT VIOLATION: {v}")
    return 0 if summary["reached"] and summary["invariants_ok"] else 1



def cmd_trace(args) -> int:
    """Snapshot the verify-pipeline flight recorder + pipeline health as
    one JSON document (docs/observability.md).  ``--rpc`` queries a
    running node's ``/debug/verify_trace`` endpoint; ``--local`` renders
    this process's own recorder (mostly useful under test harnesses that
    import the node in-process)."""
    if args.local:
        from cometbft_tpu.libs import tracing

        doc = tracing.trace_document(
            max_spans=args.spans, rounds=args.rounds
        )
    else:
        import urllib.request

        addr = args.rpc
        if addr.startswith("tcp://"):
            addr = "http://" + addr[len("tcp://"):]
        if not addr.startswith(("http://", "https://")):
            addr = "http://" + addr
        url = (
            f"{addr.rstrip('/')}/debug_verify_trace"
            f"?spans={args.spans}&rounds={args.rounds}"
        )
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                reply = json.loads(resp.read())
        except (OSError, ValueError) as e:
            # ValueError covers a non-JSON body (proxy error page,
            # truncated response) — a diagnostic CLI must not traceback
            print(f"cannot reach {url}: {e}", file=sys.stderr)
            return 1
        if "error" in reply:
            print(f"rpc error: {reply['error']}", file=sys.stderr)
            return 1
        doc = reply.get("result", {})
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    # human summary: health first, then the stage latency table
    t = doc.get("tracing", {})
    print(
        "flight recorder: %s spans=%s dropped=%s anomalies=%s dumps=%s"
        % (
            "on" if t.get("enabled") else "OFF",
            t.get("spans_recorded"),
            t.get("spans_dropped"),
            t.get("anomalies_total"),
            t.get("dump_count"),
        )
    )
    for kind, n in sorted((t.get("anomalies") or {}).items()):
        print(f"  anomaly {kind}: {n}")
    backend = doc.get("backend", {})
    for name, br in sorted((backend.get("breakers") or {}).items()):
        print(
            "breaker %-12s %-9s opens=%s last_error=%s"
            % (name, br.get("state"), br.get("opens"), br.get("last_error") or "-")
        )
    dev = doc.get("device", {})
    if dev and dev.get("up") is not None:
        print(
            "device: %s platform=%s probes=%s transitions=%s source=%s"
            % (
                "UP" if dev.get("up") else "DOWN",
                dev.get("platform") or "?",
                dev.get("probes"),
                dev.get("transitions"),
                dev.get("source"),
            )
        )
    disp = doc.get("dispatch", {})
    if disp.get("mesh_width"):
        print(
            "mesh: width=%s shrinks=%s restores=%s"
            % (
                disp.get("mesh_width"),
                disp.get("mesh_shrinks"),
                disp.get("mesh_restores"),
            )
        )
    bb = doc.get("blackbox", {})
    if bb and "records" in bb:
        print(
            "blackbox: records=%s bytes=%s dropped=%s segments=%s"
            % (
                bb.get("records"),
                bb.get("bytes"),
                bb.get("dropped"),
                bb.get("segments"),
            )
        )
    sig = doc.get("sigcache", {})
    if sig:
        print(
            "sigcache: hit_rate=%.2f size=%s/%s"
            % (sig.get("hit_rate", 0.0), sig.get("size"), sig.get("capacity"))
        )
    sched = doc.get("sched", {})
    if sched:
        print(
            "sched: queue_depth=%s shed=%s dedup=%s"
            % (
                sched.get("queue_depth"),
                sched.get("shed_total"),
                sched.get("dedup_hits"),
            )
        )
    warm = doc.get("warmboot", {})
    if warm:
        print(
            "warmboot: runs=%s shapes=%s compiles=%s exec_hits=%s"
            % (
                warm.get("warm_runs"),
                warm.get("shapes_warmed"),
                warm.get("compiles"),
                warm.get("exec_hits"),
            )
        )
    stages = doc.get("stages", {})
    if stages:
        print(f"{'stage':24s} {'count':>7s} {'p50ms':>9s} {'p99ms':>9s} {'maxms':>9s}")
        for stage, row in sorted(stages.items()):
            print(
                "%-24s %7d %9.3f %9.3f %9.3f"
                % (
                    stage,
                    row["count"],
                    row["p50_ms"],
                    row["p99_ms"],
                    row["max_ms"],
                )
            )
    rounds = doc.get("rounds") or {}
    if rounds.get("rounds_seen"):
        print(
            "rounds: seen=%s commits linked=%s unlinked=%s standalone=%s"
            % (
                rounds.get("rounds_seen"),
                rounds.get("commits_linked"),
                rounds.get("commits_unlinked"),
                rounds.get("commits_standalone"),
            )
        )
        for step, s in sorted((rounds.get("steps") or {}).items()):
            print(
                "  step %-22s n=%-5d p50=%8.3fms p99=%8.3fms"
                % (step, s.get("count", 0), s.get("p50_ms", 0.0),
                   s.get("p99_ms", 0.0))
            )
        for k, q in sorted((rounds.get("quorum") or {}).items()):
            if q.get("count"):
                print(
                    "  quorum %-20s n=%-5d p50=%8.3fms p99=%8.3fms"
                    % (k, q["count"], q.get("p50_ms", 0.0),
                       q.get("p99_ms", 0.0))
                )
        for g in rounds.get("rounds") or []:
            committed = sum(
                1 for nd in g["nodes"] if nd.get("committed")
            )
            print(
                "  round h=%-5s r=%-3s origin=%-4s trace=%-6s nodes=%d "
                "committed=%d verify_commits=%d"
                % (
                    g["h"], g["r"],
                    "?" if g["origin"] is None else g["origin"],
                    "?" if g["trace"] is None else g["trace"],
                    len(g["nodes"]), committed, g["commits"],
                )
            )
    return 0


def cmd_postmortem(args) -> int:
    """Reconstruct a dead node's final timeline from its black-box
    journal (docs/observability.md "Black box"): last committed height,
    the in-flight consensus round (step spans, quorum arrivals), open
    spans at death, the last verify-dispatch attribution triple, recent
    anomalies and last-known breaker states.  A torn final record is a
    normal crash artifact; corruption is skipped and counted, never
    raised.  ``--json`` prints the full report (sort_keys-stable, so two
    same-seed sim crashes byte-compare)."""
    from cometbft_tpu.libs import blackbox

    target = blackbox.resolve_dir(args.dir)
    if target is None:
        print(f"no black-box journal under {args.dir}", file=sys.stderr)
        return 1
    report = blackbox.postmortem_report(target, recent=args.recent)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    j = report["journal"]
    print(
        "journal: %d records in %d segment(s), %d bytes%s%s"
        % (
            j["records"],
            j["segments"],
            j["bytes"],
            ", %d corrupt skipped" % j["corrupt_skipped"]
            if j["corrupt_skipped"]
            else "",
            ", torn tail" if j["torn_tail"] else "",
        )
    )
    print(
        "shutdown: %s"
        % ("CLEAN" if report["clean_close"] else "UNCLEAN (no sentinel)")
    )
    print("last committed height: %s" % report["last_committed_height"])
    inf = report["in_flight"]
    if inf:
        print(
            "in-flight round at death: h=%s r=%s node=%s (opened t=%s)"
            % (inf["h"], inf["r"], inf["node"], inf["t0"])
        )
        for step, dur in sorted(inf["steps"].items()):
            print("  step %-24s %s ms" % (step, dur))
        for k, ms in sorted(inf["quorum"].items()):
            print("  quorum %-22s %s ms" % (k, ms))
    else:
        print("in-flight round at death: none recorded")
    ld = report["last_dispatch"]
    if ld:
        print(
            "last dispatch: tier=%s lanes=%s n=%s ordinal=%s%s"
            % (
                ld["tier"],
                ld["lanes"],
                ld["n"],
                ld["dispatch"],
                " mesh=%s" % ld["mesh"] if ld.get("mesh") else "",
            )
        )
    mesh = report.get("mesh") or {}
    if mesh.get("width") is not None:
        print("mesh width at death: %s" % mesh["width"])
        for ev in mesh.get("events") or ():
            a = ev.get("attrs") or {}
            print(
                "  mesh reconfig t=%s width=%s reason=%s%s%s"
                % (
                    ev.get("t"),
                    a.get("width"),
                    a.get("reason"),
                    " excluded=%s" % a["excluded"] if "excluded" in a else "",
                    " restored=%s" % a["restored"] if "restored" in a else "",
                )
            )
    for sp in report["open_spans"]:
        print(
            "open span at death: %s (span=%s t0=%s) %s"
            % (sp["stage"], sp["span"], sp["t0"], sp["attrs"])
        )
    for kind, n in sorted(report["anomaly_counts"].items()):
        print("anomaly %s: %d" % (kind, n))
    for backend, st in sorted(report["breakers"].items()):
        print(
            "breaker %-12s %-7s%s"
            % (
                backend,
                st["state"],
                " last_error=%s" % st["error"] if st.get("error") else "",
            )
        )
    for ev in report["device_events"]:
        a = ev.get("attrs") or {}
        print(
            "device probe t=%s up=%s platform=%s"
            % (ev.get("t"), a.get("up"), a.get("platform"))
        )
    return 0


def cmd_inspect(args) -> int:
    """Reference: internal/inspect — read-only RPC over the data dir."""
    from cometbft_tpu.node.inspect import InspectNode

    cfg = _load_config(args.home)
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    node = InspectNode(cfg).serve()
    print(
        f"Inspect server listening on {cfg.rpc.laddr} "
        f"(store height {node.block_store.height()})"
    )
    return _run_until_signal(node.close)


def cmd_light(args) -> int:
    """Reference: cmd light — run a light-client RPC proxy daemon."""
    from cometbft_tpu.light import (
        SKIPPING,
        HTTPProvider,
        LightClient,
        LightStore,
        TrustOptions,
    )
    from cometbft_tpu.light.proxy import LightProxy
    from cometbft_tpu.store.kv import SqliteKV

    primary = HTTPProvider(args.chain_id, args.primary)
    witnesses = [
        HTTPProvider(args.chain_id, w) for w in (args.witnesses or "").split(",") if w
    ]
    if bool(args.trust_height) != bool(args.trust_hash):
        print("error: --trust-height and --trust-hash must be given together")
        return 1
    if args.trust_height:
        opts = TrustOptions(
            period_s=args.trust_period,
            height=args.trust_height,
            hash=bytes.fromhex(args.trust_hash),
        )
    else:
        lb = primary.light_block(0)
        opts = TrustOptions(
            period_s=args.trust_period, height=lb.height, hash=lb.hash()
        )
        print(f"WARNING: trusting the primary's latest header blindly "
              f"(height {lb.height}); pass --trust-height/--trust-hash")
    os.makedirs(os.path.join(args.home, "light"), exist_ok=True)
    store = LightStore(
        SqliteKV(os.path.join(args.home, "light", "trust.db"), surface="light")
    )
    client = LightClient(args.chain_id, opts, primary, witnesses, store)
    proxy = LightProxy(client, args.primary, laddr=args.laddr)
    proxy.start()
    print(f"Light client proxy listening on {args.laddr} "
          f"(trusted height {client.trusted_light_block().height})")
    return _run_until_signal(proxy.stop)


def cmd_confix(args) -> int:
    """Reference: internal/confix — migrate config.toml to this version."""
    from cometbft_tpu.config.confix import upgrade

    report = upgrade(args.home, dry_run=args.dry_run)
    for key in report["carried"]:
        print(f"carried: {key}")
    for key in report["unknown"]:
        print(f"unknown (dropped): {key}")
    if report["backup"]:
        print(f"backup written to {report['backup']}")
    elif args.dry_run:
        print("dry run: no files written")
    return 0


def cmd_compact_db(args) -> int:
    """Reference: commands/compact.go — compact the embedded database."""
    from cometbft_tpu.store.kv import SqliteKV

    cfg = _load_config(args.home)
    path = os.path.join(cfg.base.home, cfg.base.db_dir, "chain.db")
    if not os.path.exists(path):
        print(f"no database at {path}")
        return 1
    before = os.path.getsize(path)
    kv = SqliteKV(path, surface="state")
    kv.compact()
    kv.close()
    after = os.path.getsize(path)
    print(f"compacted {path}: {before} -> {after} bytes")
    return 0


def _debug_collect(cfg, home: str, out_dir: str) -> list[str]:
    """Collect debug artifacts from a running node into ``out_dir``.

    Reference: cmd/cometbft/commands/debug/{kill,dump,util}.go — status,
    net_info, dump_consensus_state, the config file, plus the pprof
    goroutine/heap dumps when the profiling server is enabled.
    """
    import shutil
    import urllib.request

    collected = []

    def fetch(base: str, route: str, fname: str):
        try:
            with urllib.request.urlopen(f"{base}/{route}", timeout=5) as resp:
                data = resp.read()
            with open(os.path.join(out_dir, fname), "wb") as f:
                f.write(data)
            collected.append(fname)
        except Exception as e:  # noqa: BLE001
            print(f"warning: could not fetch {route}: {e}")

    rpc = cfg.rpc.laddr.replace("tcp://", "http://")
    fetch(rpc, "status", "status.json")
    fetch(rpc, "net_info", "net_info.json")
    fetch(rpc, "dump_consensus_state", "consensus_state.json")

    cfg_path = os.path.join(home, "config", "config.toml")
    if os.path.exists(cfg_path):
        shutil.copy(cfg_path, os.path.join(out_dir, "config.toml"))
        collected.append("config.toml")

    # pprof artifacts when the profiling server is up
    if cfg.rpc.pprof_laddr:
        pprof = cfg.rpc.pprof_laddr.replace("tcp://", "http://")
        fetch(pprof, "debug/pprof/goroutine", "goroutine.txt")
        fetch(pprof, "debug/pprof/heap", "heap.txt")
    return collected


def cmd_debug_kill(args) -> int:
    """Reference: commands/debug/kill.go — collect debug artifacts into a
    zip, then SIGKILL the node process."""
    import signal
    import tempfile
    import zipfile

    cfg = _load_config(args.home)
    with tempfile.TemporaryDirectory() as tmp:
        files = _debug_collect(cfg, args.home, tmp)
        with zipfile.ZipFile(args.output, "w", zipfile.ZIP_DEFLATED) as z:
            for fname in files:
                z.write(os.path.join(tmp, fname), fname)
    print(f"wrote {len(files)} artifacts to {args.output}")
    try:
        os.kill(args.pid, signal.SIGKILL)
        print(f"killed process {args.pid}")
    except ProcessLookupError:
        print(f"no such process: {args.pid}")
        return 1
    return 0


def cmd_debug_dump(args) -> int:
    """Reference: commands/debug/dump.go — periodically collect debug
    artifacts into timestamped zips under the output directory."""
    import time as _time
    import zipfile

    cfg = _load_config(args.home)
    os.makedirs(args.output_dir, exist_ok=True)
    iterations = args.iterations
    while True:
        # millisecond resolution: sub-second --frequency must not
        # overwrite the previous iteration's archive
        stamp = "%s%03d" % (
            _time.strftime("%Y%m%d%H%M%S"),
            int(_time.time() * 1000) % 1000,
        )
        tmp = os.path.join(args.output_dir, f".collect-{stamp}")
        os.makedirs(tmp, exist_ok=True)
        files = _debug_collect(cfg, args.home, tmp)
        out = os.path.join(args.output_dir, f"{stamp}.zip")
        with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
            for fname in files:
                z.write(os.path.join(tmp, fname), fname)
        for fname in files:
            os.unlink(os.path.join(tmp, fname))
        os.rmdir(tmp)
        print(f"wrote {out} ({len(files)} artifacts)")
        if iterations is not None:
            iterations -= 1
            if iterations <= 0:
                return 0
        _time.sleep(args.frequency)


def cmd_reindex_event(args) -> int:
    """Reference: commands/reindex_event.go — replay stored blocks and
    finalize-block responses through the configured indexer sinks.

    The node must NOT be running (the stores are opened directly)."""
    from cometbft_tpu.state.execution import fbr_from_json
    from cometbft_tpu.state.store import StateStore
    from cometbft_tpu.store.block_store import BlockStore
    from cometbft_tpu.store.kv import SqliteKV

    cfg = _load_config(args.home)
    db_path = os.path.join(cfg.base.home, cfg.base.db_dir, "chain.db")
    if not os.path.exists(db_path):
        print(f"no database at {db_path}")
        return 1
    db = SqliteKV(db_path, surface="state")
    index_db = None
    try:
        block_store = BlockStore(db)
        state_store = StateStore(db)

        base, height = block_store.base(), block_store.height()
        if height == 0:
            print("no blocks stored; nothing to reindex")
            return 1
        start = args.start_height or max(base, 1)
        end = args.end_height or height
        if start < base or end > height or start > end:
            print(
                f"height range [{start}, {end}] outside stored "
                f"[{base}, {height}]"
            )
            return 1

        if cfg.tx_index.indexer == "kv":
            from cometbft_tpu.indexer import KVBlockIndexer, KVTxIndexer

            # the live node reads tx_index.db (degradable surface) —
            # rebuilt rows written into chain.db would stay invisible
            # until a later boot's legacy drain
            index_db = SqliteKV(
                os.path.join(cfg.base.home, cfg.base.db_dir, "tx_index.db"),
                surface="indexer",
            )
            tx_indexer = KVTxIndexer(index_db)
            block_indexer = KVBlockIndexer(index_db)
        elif cfg.tx_index.indexer == "psql":
            from cometbft_tpu.indexer.psql import (
                PsqlBlockIndexerAdapter,
                PsqlEventSink,
                PsqlTxIndexerAdapter,
            )
            from cometbft_tpu.types.genesis import GenesisDoc

            gpath = os.path.join(cfg.base.home, cfg.base.genesis_file)
            with open(gpath) as f:
                chain_id = GenesisDoc.from_json(f.read()).chain_id
            sink = PsqlEventSink(cfg.tx_index.psql_conn, chain_id)
            tx_indexer = PsqlTxIndexerAdapter(sink)
            block_indexer = PsqlBlockIndexerAdapter(sink)
        else:
            print("reindex requires a non-null indexer")
            return 1

        n_blocks = n_txs = 0
        for h in range(start, end + 1):
            block = block_store.load_block(h)
            raw = state_store.load_finalize_block_response(h)
            if block is None or raw is None:
                print(f"warning: missing block or results at height {h}")
                continue
            res = fbr_from_json(raw)
            block_indexer.index(h, res.events)
            for i, tx in enumerate(block.data.txs):
                if i < len(res.tx_results):
                    tx_indexer.index(h, i, tx, res.tx_results[i])
                    n_txs += 1
            n_blocks += 1
        print(f"reindexed {n_blocks} blocks, {n_txs} txs in [{start}, {end}]")
        return 0
    finally:
        if index_db is not None:
            index_db.close()
        db.close()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cometbft_tpu", description="TPU-native BFT consensus node"
    )
    p.add_argument(
        "--home",
        default=os.environ.get("CMTHOME", os.path.expanduser("~/.cometbft_tpu")),
        help="node home directory",
    )
    sub = p.add_subparsers(dest="command")

    sp = sub.add_parser("init", help="initialize a node home directory")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--proxy_app", default="")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("show-node-id", help="show the p2p node ID")
    sp.set_defaults(fn=cmd_show_node_id)

    sp = sub.add_parser("show-validator", help="show validator pubkey")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("gen-validator", help="generate a validator keypair")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("unsafe-reset-all", help="wipe blockchain data")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("testnet", help="generate testnet home dirs")
    sp.add_argument("--v", type=int, default=4, help="number of validators")
    sp.add_argument("--o", default="./mytestnet", help="output directory")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("rollback", help="roll back one block")
    sp.add_argument("--hard", action="store_true", help="also remove the block")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser("inspect", help="read-only RPC over the data dir")
    sp.add_argument("--rpc-laddr", default="", help="override rpc listen addr")
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser("light", help="run a light-client RPC proxy")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True, help="primary node RPC URL")
    sp.add_argument("--witnesses", default="", help="comma-separated witness RPC URLs")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.add_argument("--trust-height", type=int, default=0)
    sp.add_argument("--trust-hash", default="")
    sp.add_argument("--trust-period", type=int, default=168 * 3600)
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("confix", help="migrate config.toml to this version")
    sp.add_argument("--dry-run", action="store_true")
    sp.set_defaults(fn=cmd_confix)

    sp = sub.add_parser("compact-db", help="compact the embedded database")
    sp.set_defaults(fn=cmd_compact_db)

    # debug kill/dump (reference: commands/debug/debug.go)
    sp = sub.add_parser("debug", help="debug utilities for a running node")
    dsub = sp.add_subparsers(dest="debug_command", required=True)
    dk = dsub.add_parser(
        "kill", help="collect debug artifacts into a zip, then kill the node"
    )
    dk.add_argument("pid", type=int, help="node process id")
    dk.add_argument("output", help="output zip path")
    dk.set_defaults(fn=cmd_debug_kill)
    dd = dsub.add_parser(
        "dump", help="periodically collect debug artifacts into a directory"
    )
    dd.add_argument("output_dir", help="directory for timestamped zips")
    dd.add_argument(
        "--frequency", type=float, default=30.0, help="seconds between dumps"
    )
    dd.add_argument(
        "--iterations",
        type=int,
        default=None,
        help="stop after N dumps (default: run until interrupted)",
    )
    dd.set_defaults(fn=cmd_debug_dump)

    sp = sub.add_parser(
        "reindex-event",
        help="re-run the indexers over stored blocks (node must be stopped)",
    )
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp.set_defaults(fn=cmd_reindex_event)

    sp = sub.add_parser(
        "sim",
        help="run a deterministic fault-injection scenario on virtual time",
    )
    sp.add_argument("--seed", type=int, default=42)
    sp.add_argument(
        "--scenario", default="baseline", help="scenario name (--list)"
    )
    sp.add_argument("--validators", type=int, default=0)
    sp.add_argument("--height", type=int, default=0, help="target height")
    sp.add_argument("--max-time", type=float, default=0.0, help="virtual-second budget")
    sp.add_argument("--trace-out", default="", help="write the event trace here")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--list", action="store_true", help="list scenarios")
    sp.set_defaults(fn=cmd_sim)

    sp = sub.add_parser(
        "trace",
        help="snapshot the verify-pipeline flight recorder + health "
        "(docs/observability.md)",
    )
    sp.add_argument(
        "--rpc", default="tcp://127.0.0.1:26657",
        help="node RPC address to query (default tcp://127.0.0.1:26657)",
    )
    sp.add_argument(
        "--local", action="store_true",
        help="render this process's own recorder instead of querying RPC",
    )
    sp.add_argument(
        "--spans", type=int, default=256,
        help="ring-tail spans to include (default 256)",
    )
    sp.add_argument(
        "--rounds", type=int, default=8,
        help="last-K merged consensus-round timelines to include "
             "(default 8; 0 skips the section)",
    )
    sp.add_argument("--json", action="store_true", help="raw JSON document")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser(
        "postmortem",
        help="reconstruct a dead node's final timeline from its black-box "
        "journal (docs/observability.md)",
    )
    sp.add_argument(
        "dir",
        help="journal directory (or a node home / data dir containing one)",
    )
    sp.add_argument(
        "--recent", type=int, default=16,
        help="recent anomalies/events to include (default 16)",
    )
    sp.add_argument(
        "--json", action="store_true",
        help="full report as sort_keys-stable JSON",
    )
    sp.set_defaults(fn=cmd_postmortem)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    # Pin the JAX platform before any lazy jax use.  This must happen at
    # the config level: some environments (axon) install a sitecustomize
    # that force-sets jax_platforms at interpreter start, overriding the
    # JAX_PLATFORMS env var — e.g. multi-node testnets on one host must
    # run the crypto backend on CPU, not fight over one TPU chip.
    plat = os.environ.get("COMETBFT_TPU_JAX_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    # SIGUSR1 dumps every thread's stack to stderr (the Go runtime's
    # SIGQUIT goroutine-dump analog) — first tool when a node wedges
    if hasattr(signal, "SIGUSR1"):
        import faulthandler

        faulthandler.register(signal.SIGUSR1, all_threads=True)
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 1
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
