"""Named fault scripts for the deterministic simulator.

A scenario is a list of ``Action``s — (virtual time, description, callable)
— applied to a running ``SimCluster``.  Everything an action does flows
through the cluster's seeded clock and RNG, so ``run_scenario(name, seed)``
reproduces byte-identically: same event trace, same commit hashes, same
failure (if any).

Built-ins:
  * ``baseline``           — clean run, default links
  * ``partition-minority`` — cut off f nodes, heal, expect full recovery
  * ``partition-leader``   — cut off the current proposer specifically
  * ``crash-restart``      — kill f nodes mid-run, restart from their stores
  * ``asymmetric-loss``    — 30% one-directional loss on node0's egress
  * ``message-storm``      — duplicates + aggressive reordering on all links
"""

from __future__ import annotations

import shutil
import tempfile
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Callable, Optional

from cometbft_tpu.sim.cluster import SimCluster


@dataclass
class Action:
    at: float
    name: str
    fn: Callable[[SimCluster], None]


@dataclass
class Scenario:
    name: str
    description: str
    n_vals: int = 4
    target_height: int = 5
    max_time: float = 120.0
    link_overrides: dict = field(default_factory=dict)
    actions: Callable[[Scenario], list[Action]] = lambda _s: []


@dataclass
class ScenarioResult:
    scenario: str
    seed: int
    n_vals: int
    target_height: int
    reached: bool
    heights: list[int]
    virtual_time: float
    events: int
    commits_verified: int
    violations: list[str]
    trace: list[str]
    cluster: Optional[SimCluster] = None

    def summary(self) -> dict:
        """JSON-serializable row for soak artifacts (scripts/sim_soak.py)."""
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "n_vals": self.n_vals,
            "target_height": self.target_height,
            "reached": self.reached,
            "heights": self.heights,
            "virtual_time": round(self.virtual_time, 6),
            "events": self.events,
            "commits_verified": self.commits_verified,
            "invariants_ok": not self.violations,
            "violations": self.violations,
        }


def _proposer_index(cluster: SimCluster) -> int:
    """Index of the proposer for the current round in the first live
    node's view (resolved at action-fire time, not script time)."""
    node = cluster.live_nodes()[0]
    addr = node.cs.rs.validators.get_proposer().address
    for i, priv in enumerate(cluster.privs):
        if priv.pub_key().address() == addr:
            return i
    return 0


def _f(n_vals: int) -> int:
    """Max tolerable faulty nodes for n validators (f < n/3), at least 1."""
    return max(1, (n_vals - 1) // 3)


def _partition_minority(s: Scenario) -> list[Action]:
    minority = list(range(s.n_vals - _f(s.n_vals), s.n_vals))
    return [
        Action(3.0, f"partition minority {minority}",
               lambda c, m=minority: c.net.partition(m)),
        Action(25.0, "heal", lambda c: c.net.heal()),
    ]


def _partition_leader(s: Scenario) -> list[Action]:
    def cut(c: SimCluster) -> None:
        leader = _proposer_index(c)
        c._log("scenario: partitioning leader node%d" % leader)
        c.net.partition([leader])

    return [
        Action(3.0, "partition current leader", cut),
        Action(25.0, "heal", lambda c: c.net.heal()),
    ]


def _crash_restart(s: Scenario) -> list[Action]:
    victims = list(range(1, 1 + _f(s.n_vals)))
    acts: list[Action] = []
    for v in victims:
        acts.append(Action(4.0, f"crash node{v}", lambda c, v=v: c.crash(v)))
        acts.append(Action(20.0, f"restart node{v}", lambda c, v=v: c.restart(v)))
    return acts


def _asymmetric_loss(s: Scenario) -> list[Action]:
    def degrade(c: SimCluster) -> None:
        for dst in range(1, c.n_vals):
            c.net.set_link(0, dst, drop_rate=0.3)  # egress only; ingress clean

    return [Action(0.0, "30% loss on node0 egress", degrade)]


def _message_storm(s: Scenario) -> list[Action]:
    def inject_txs(c: SimCluster) -> None:
        h = c.live_nodes()[0].cs.rs.height
        for node in c.live_nodes():
            node.mempool.check_tx(b"storm%d=%d" % (h, h))

    acts = [
        Action(
            0.0,
            "storm links: dup 25%, reorder 50%",
            lambda c: c.net.set_all_links(
                dup_rate=0.25, reorder_rate=0.5, reorder_jitter=0.5
            ),
        )
    ]
    acts += [
        Action(float(t), "inject txs", inject_txs) for t in (2, 5, 8, 11, 14)
    ]
    return acts


SCENARIOS: dict[str, Scenario] = {
    s.name: s
    for s in [
        Scenario(
            "baseline",
            "clean 4-validator run, default delay/jitter links",
        ),
        Scenario(
            "partition-minority",
            "cut off f nodes for 22 virtual seconds, heal, require full "
            "recovery with no fork",
            max_time=180.0,
            actions=_partition_minority,
        ),
        Scenario(
            "partition-leader",
            "cut off the current proposer, forcing round changes; heal and "
            "require it to catch back up",
            max_time=180.0,
            actions=_partition_leader,
        ),
        Scenario(
            "crash-restart",
            "kill f nodes mid-run; restart them from their stores (WAL + "
            "Handshaker replay) and require rejoin",
            max_time=180.0,
            actions=_crash_restart,
        ),
        Scenario(
            "asymmetric-loss",
            "30% one-directional message loss on node0's outbound links",
            max_time=240.0,
            actions=_asymmetric_loss,
        ),
        Scenario(
            "message-storm",
            "duplicate and aggressively reorder every link while txs flow",
            max_time=240.0,
            actions=_message_storm,
        ),
    ]
}


def run_scenario(
    name: str,
    seed: int,
    root=None,
    n_vals: Optional[int] = None,
    target_height: Optional[int] = None,
    max_time: Optional[float] = None,
    raise_on_violation: bool = False,
    keep_cluster: bool = False,
) -> ScenarioResult:
    """Build a cluster, script the scenario's actions onto its virtual
    clock, and drive it to the target height (or the time budget)."""
    scenario = SCENARIOS[name]
    # overrides flow into the scenario the action generators see, so e.g.
    # _partition_minority picks its victims from the real cluster size
    scenario = replace(
        scenario,
        n_vals=n_vals or scenario.n_vals,
        target_height=target_height or scenario.target_height,
        max_time=max_time or scenario.max_time,
    )
    created_root = root is None
    if created_root:
        root = Path(tempfile.mkdtemp(prefix=f"sim-{name}-{seed}-"))
    cluster = SimCluster(
        scenario.n_vals, root, seed=seed, raise_on_violation=raise_on_violation
    )
    for src_dst, overrides in scenario.link_overrides.items():
        cluster.net.set_link(*src_dst, **overrides)
    for action in scenario.actions(scenario):
        cluster.clock.call_at(
            action.at,
            lambda a=action: a.fn(cluster),
            label=f"scenario {action.name}",
        )
    try:
        reached = cluster.run(
            until_height=scenario.target_height, max_time=scenario.max_time
        )
    finally:
        cluster.stop()
        if created_root and not keep_cluster:
            shutil.rmtree(root, ignore_errors=True)
    return ScenarioResult(
        scenario=name,
        seed=seed,
        n_vals=scenario.n_vals,
        target_height=scenario.target_height,
        reached=reached,
        heights=cluster.heights(),
        virtual_time=cluster.clock.now(),
        events=cluster.events_fired,
        commits_verified=cluster.checker.commits_verified,
        violations=[str(v) for v in cluster.checker.violations],
        trace=cluster.trace,
        cluster=cluster if keep_cluster else None,
    )
