"""Named fault scripts for the deterministic simulator.

A scenario is a list of ``Action``s — (virtual time, description, callable)
— applied to a running ``SimCluster``.  Everything an action does flows
through the cluster's seeded clock and RNG, so ``run_scenario(name, seed)``
reproduces byte-identically: same event trace, same commit hashes, same
failure (if any).

Built-ins:
  * ``baseline``           — clean run, default links
  * ``partition-minority`` — cut off f nodes, heal, expect full recovery
  * ``partition-leader``   — cut off the current proposer specifically
  * ``crash-restart``      — kill f nodes mid-run, restart from their stores
  * ``asymmetric-loss``    — 30% one-directional loss on node0's egress
  * ``message-storm``      — duplicates + aggressive reordering on all links
  * ``backend-brownout``   — device crypto backend raises on f+1 nodes
    mid-run (t=5..10); supervisor must degrade to host, keep agreement,
    and re-promote after restore
  * ``backend-wedge``      — device dispatches hang past the watchdog
  * ``backend-flap``       — device fails in bursts; breaker must cycle
    open -> half-open -> closed with exponential backoff
  * ``gossip-burst``       — vote storm + bulk-class submission bursts
    overload the verification scheduler's bounded queue; only bulk items
    may shed, consensus votes never, agreement must hold
  * ``tx-flood``           — sustained scripted signed-tx bursts (valid /
    forged / malformed / oversize / duplicate mixes) against a small
    ingest-coalescer queue (docs/tx-ingest.md); batched admission must
    shed only to the per-tx sync path, consensus-class verify shed stays
    0, agreement holds, traces byte-identical per seed

The backend-* scenarios force the supervised device verify path
(``COMETBFT_TPU_CRYPTO_BACKEND=tpu`` — verdict-equal on CPU hosts via the
XLA kernel), disable the sigcache so every commit verification really
dispatches, pin the breaker clock to the cluster's ``VirtualClock`` (so
backoff windows are deterministic), and install a ``FaultyBackend``
injector at scripted virtual times.  One process hosts every sim node, so
the circuit breaker registry is shared: a victim node's failures demote
the device for the whole cluster — conservative over-degradation (verdicts
never change; per-node registries are e2e territory).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time as _wall
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Callable, Optional

from cometbft_tpu.config.config import MempoolConfig
from cometbft_tpu.ops import supervisor
from cometbft_tpu.sim.cluster import SimCluster


@dataclass
class Action:
    at: float
    name: str
    fn: Callable[[SimCluster], None]


def compose(*generators: Callable[["Scenario"], list[Action]]):
    """Merge several action generators into one scenario script — the
    combined-fault composition layer.  Actions keep their scripted times;
    the virtual clock's (time, schedule-order) ordering resolves ties
    deterministically, so composing scripts never changes the members'
    individual timing."""

    def gen(s: "Scenario") -> list[Action]:
        acts: list[Action] = []
        for g in generators:
            acts.extend(g(s))
        return acts

    return gen


@dataclass
class Scenario:
    name: str
    description: str
    n_vals: int = 4
    target_height: int = 5
    max_time: float = 120.0
    # standby full nodes beyond the genesis validator set (churn/rotation
    # scenarios spawn or statesync-join them mid-run)
    n_spares: int = 0
    link_overrides: dict = field(default_factory=dict)
    actions: Callable[[Scenario], list[Action]] = lambda _s: []
    # setup runs after the cluster is built but before it starts; teardown
    # runs in run_scenario's finally (process-global state the scenario
    # touched — env knobs, fault injectors, breaker clocks — MUST be
    # restored there)
    setup: Optional[Callable[[SimCluster], None]] = None
    teardown: Optional[Callable[[SimCluster], None]] = None
    # per-node app/mempool overrides (tx-flood wraps the kvstore in the
    # SigVerifyingApp middleware and turns recheck on)
    app_factory: Optional[Callable] = None
    mempool_config: Optional[object] = None


@dataclass
class ScenarioResult:
    scenario: str
    seed: int
    n_vals: int
    target_height: int
    reached: bool
    heights: list[int]
    virtual_time: float
    events: int
    commits_verified: int
    violations: list[str]
    trace: list[str]
    cluster: Optional[SimCluster] = None
    # backend supervisor counters captured at end-of-run (backend-* fault
    # scenarios only): demotions, repromotions, watchdog_fires, breakers…
    backend: dict = field(default_factory=dict)
    # verify-scheduler counters captured at end-of-run (scenarios that
    # force the tpu backend): submitted/shed per class, flushes, dedup…
    sched: dict = field(default_factory=dict)
    # tx-ingestion counters captured at end-of-run (tx-flood): enqueued,
    # shed_to_sync, flushes, batch occupancy, cache hits, rejections…
    ingest: dict = field(default_factory=dict)
    # evidence-pool counters captured at end-of-run (dup-vote-flood,
    # light-attack): added/dedup/dropped/rejected/committed…
    evidence: dict = field(default_factory=dict)
    # validator-set rotations the invariant checker authenticated
    rotations: int = 0
    # flight-recorder capture (docs/observability.md): span/anomaly
    # counts, per-stage latency summary over the ring, and the anomaly
    # dump files (name + sha256 — hashed BEFORE the run root is deleted,
    # so determinism tests byte-compare dumps across same-seed runs)
    spans: dict = field(default_factory=dict)
    # black-box journal counters (records/bytes/drops/rotations summed
    # over the cluster) plus the restart-time postmortem reports of every
    # crashed node, captured before the run root is deleted
    blackbox: dict = field(default_factory=dict)
    postmortems: list = field(default_factory=list)
    # disk-fault supervisor capture (libs/diskguard): per-surface
    # write/fsync/retry/drop/fatal/repair counters — attached when the
    # run saw injector or real-IO trouble — plus the fail-stopped nodes
    storage: dict = field(default_factory=dict)
    fail_stopped: list = field(default_factory=list)
    # Merkle/hash-plane + proof-server counters captured at end-of-run
    # (light-stampede): queries/cache hits per kind, sheds, tree builds…
    proofs: dict = field(default_factory=dict)
    # transport data-plane counters captured at end-of-run (dial-storm):
    # frames per route, AEAD dispatch tiers, handshake pool/sync/shed…
    transport: dict = field(default_factory=dict)
    # blocksync catchup counters captured at end-of-run (blocksync-storm,
    # wan-catchup): requests/timeouts/bans/probes/redos/stall-switches,
    # heights synced and the virtual-time catchup rate
    bsync: dict = field(default_factory=dict)

    def summary(self) -> dict:
        """JSON-serializable row for soak artifacts (scripts/sim_soak.py)."""
        row = {
            "scenario": self.scenario,
            "seed": self.seed,
            "n_vals": self.n_vals,
            "target_height": self.target_height,
            "reached": self.reached,
            "heights": self.heights,
            "virtual_time": round(self.virtual_time, 6),
            "events": self.events,
            "commits_verified": self.commits_verified,
            "invariants_ok": not self.violations,
            "violations": self.violations,
        }
        if self.backend:
            row["backend"] = self.backend
        if self.sched:
            row["sched"] = {
                "submitted": self.sched["submitted"],
                "shed": self.sched["shed"],
                "flushes": self.sched["flushes"],
                "dedup_hits": self.sched["dedup_hits"],
            }
        if self.ingest:
            row["ingest"] = {
                k: self.ingest[k]
                for k in (
                    "enqueued",
                    "shed_to_sync",
                    "flushes",
                    "batch_occupancy",
                    "cache_hits",
                    "admitted",
                    "rejected_total",
                    "app_batches",
                    "sig_prechecked",
                    "recheck_batches",
                )
            }
        if self.evidence:
            row["evidence"] = dict(self.evidence)
        if self.rotations:
            row["rotations"] = self.rotations
        if self.blackbox:
            row["blackbox"] = dict(self.blackbox)
        if self.storage:
            t = self.storage.get("totals", {})
            row["storage"] = {
                k: t.get(k, 0)
                for k in (
                    "writes",
                    "fsyncs",
                    "retries",
                    "drops",
                    "fatals",
                    "injected",
                    "repairs",
                    "repaired_bytes",
                )
            }
            if self.fail_stopped:
                row["storage"]["fail_stopped_nodes"] = list(
                    self.fail_stopped
                )
        if self.proofs:
            row["proofs"] = {
                k: self.proofs[k]
                for k in (
                    "queries_total",
                    "cache_hits_total",
                    "shed_total",
                    "serial_fallbacks",
                    "tree_builds_total",
                    "trees_device",
                    "trees_host",
                    "proof_cache_hit_rate",
                    "queries_per_flush",
                )
            }
        if self.transport:
            row["transport"] = {
                k: self.transport[k]
                for k in (
                    "frames_total",
                    "frames",
                    "dispatches",
                    "frames_per_batch",
                    "bad_tags",
                    "handshakes",
                    "hs_shed",
                    "handshakes_per_flush",
                )
            }
        if self.bsync:
            row["bsync"] = {
                k: self.bsync[k]
                for k in (
                    "requests",
                    "timeouts",
                    "bans",
                    "probes",
                    "probe_passes",
                    "redos",
                    "stall_switches",
                    "blocks_received",
                    "heights_synced",
                    "heights_per_second",
                )
            }
        if self.spans:
            row["spans"] = {
                "recorded": self.spans.get("recorded", 0),
                "anomalies": self.spans.get("anomalies", {}),
                "dumps": [d["file"] for d in self.spans.get("dumps", ())],
                # p99 per stage only — the full summary stays on the result
                "p99_ms": {
                    stage: s["p99_ms"]
                    for stage, s in self.spans.get("stages", {}).items()
                },
            }
            rounds = self.spans.get("rounds") or {}
            if rounds.get("rounds_seen"):
                # the round-timeline row: per-step p50/p99 (virtual ms),
                # quorum-arrival percentiles and the commit-to-proposal
                # linkage counts — a consensus latency regression is a
                # diffable soak column, not a rerun
                row["spans"]["rounds"] = {
                    "seen": rounds["rounds_seen"],
                    "commits_linked": rounds.get("commits_linked", 0),
                    "commits_unlinked": rounds.get("commits_unlinked", 0),
                    "steps": {
                        step: {
                            "p50_ms": s.get("p50_ms", 0.0),
                            "p99_ms": s.get("p99_ms", 0.0),
                        }
                        for step, s in rounds.get("steps", {}).items()
                    },
                    "quorum": {
                        k: {
                            "p50_ms": q.get("p50_ms", 0.0),
                            "p99_ms": q.get("p99_ms", 0.0),
                        }
                        for k, q in rounds.get("quorum", {}).items()
                        if q.get("count")
                    },
                }
        return row


def _proposer_index(cluster: SimCluster) -> int:
    """Index of the proposer for the current round in the first live
    node's view (resolved at action-fire time, not script time)."""
    node = cluster.live_nodes()[0]
    addr = node.cs.rs.validators.get_proposer().address
    for i, priv in enumerate(cluster.privs):
        if priv.pub_key().address() == addr:
            return i
    return 0


def _f(n_vals: int) -> int:
    """Max tolerable faulty nodes for n validators (f < n/3), at least 1."""
    return max(1, (n_vals - 1) // 3)


def _partition_minority(s: Scenario) -> list[Action]:
    minority = list(range(s.n_vals - _f(s.n_vals), s.n_vals))
    return [
        Action(3.0, f"partition minority {minority}",
               lambda c, m=minority: c.net.partition(m)),
        Action(25.0, "heal", lambda c: c.net.heal()),
    ]


def _partition_leader(s: Scenario) -> list[Action]:
    def cut(c: SimCluster) -> None:
        leader = _proposer_index(c)
        c._log("scenario: partitioning leader node%d" % leader)
        c.net.partition([leader])

    return [
        Action(3.0, "partition current leader", cut),
        Action(25.0, "heal", lambda c: c.net.heal()),
    ]


def _crash_restart(s: Scenario) -> list[Action]:
    victims = list(range(1, 1 + _f(s.n_vals)))
    acts: list[Action] = []
    for v in victims:
        acts.append(Action(4.0, f"crash node{v}", lambda c, v=v: c.crash(v)))
        acts.append(Action(20.0, f"restart node{v}", lambda c, v=v: c.restart(v)))
    return acts


def _asymmetric_loss(s: Scenario) -> list[Action]:
    def degrade(c: SimCluster) -> None:
        for dst in range(1, c.n_vals):
            c.net.set_link(0, dst, drop_rate=0.3)  # egress only; ingress clean

    return [Action(0.0, "30% loss on node0 egress", degrade)]


# -- backend fault scenarios -------------------------------------------------

_BACKEND_ENV_KNOBS = (
    "COMETBFT_TPU_CRYPTO_BACKEND",
    "COMETBFT_TPU_SIGCACHE",
    "COMETBFT_TPU_DISPATCH_TIMEOUT_MS",
    "COMETBFT_TPU_BREAKER_THRESHOLD",
    "COMETBFT_TPU_SUPERVISOR_BISECT",
    "COMETBFT_TPU_VERIFY_SCHED",
    "COMETBFT_TPU_SCHED_FLUSH_US",
    "COMETBFT_TPU_SCHED_QUEUE",
    "COMETBFT_TPU_SCHED_PIPELINE",
    "COMETBFT_TPU_SCHED_INFLIGHT",
    "COMETBFT_TPU_TXINGEST",
    "COMETBFT_TPU_TXINGEST_QUEUE",
    "COMETBFT_TPU_TXINGEST_BATCH",
    "COMETBFT_TPU_TXINGEST_FLUSH_US",
    # Merkle/hash plane + proof server (proofserve): light-stampede
    # overrides these via extra_env; same save/restore as the rest
    "COMETBFT_TPU_PROOFSERVE",
    "COMETBFT_TPU_PROOFSERVE_QUEUE",
    "COMETBFT_TPU_PROOFSERVE_FLUSH_US",
    "COMETBFT_TPU_PROOFSERVE_CACHE",
    "COMETBFT_TPU_MERKLE_MIN_BATCH",
    "COMETBFT_TPU_MERKLE_DEVICE",
    "COMETBFT_TPU_MERKLE_MAX_LANES",
    # encrypted transport data plane (transportplane + handshake_pool):
    # dial-storm overrides these via extra_env; same save/restore
    "COMETBFT_TPU_AEAD",
    "COMETBFT_TPU_AEAD_DEVICE",
    "COMETBFT_TPU_AEAD_MIN_BATCH",
    "COMETBFT_TPU_AEAD_MAX_LANES",
    "COMETBFT_TPU_HANDSHAKE",
    "COMETBFT_TPU_HANDSHAKE_QUEUE",
    "COMETBFT_TPU_HANDSHAKE_FLUSH_US",
    "COMETBFT_TPU_HANDSHAKE_MAX_BATCH",
    "COMETBFT_TPU_HANDSHAKE_TIMEOUT_S",
    "COMETBFT_TPU_X25519_DEVICE",
    "COMETBFT_TPU_X25519_MAX_LANES",
    # elastic mesh supervision (parallel/elastic): mesh scenarios force
    # membership + the shard runner in setup; these knobs ride the same
    # save/restore as everything else
    "COMETBFT_TPU_MESH_SUPERVISOR",
    "COMETBFT_TPU_MESH",
    "COMETBFT_TPU_MESH_MIN_BATCH",
    "COMETBFT_TPU_WARMBOOT_MESH_SHRINK",
    # blocksync adaptive catchup (blocksync/pool.py + reactor.py): the
    # WAN scenarios pin BAN_BASE/STALL_SECS via extra_env so ban/probe
    # cycles fit inside a catchup window; same save/restore as the rest
    "COMETBFT_TPU_BSYNC_ADAPTIVE",
    "COMETBFT_TPU_BSYNC_TIMEOUT_MULT",
    "COMETBFT_TPU_BSYNC_TIMEOUT_FLOOR",
    "COMETBFT_TPU_BSYNC_TIMEOUT_CAP",
    "COMETBFT_TPU_BSYNC_BAN_BASE",
    "COMETBFT_TPU_BSYNC_BAN_CAP",
    "COMETBFT_TPU_BSYNC_BAN_STRIKES",
    "COMETBFT_TPU_BSYNC_STALL_SECS",
    "COMETBFT_TPU_BSYNC_SOLO_GRACE",
    "COMETBFT_TPU_BLOCKSYNC_WINDOW",
    # observability knobs: saved/restored for cross-run hygiene only.
    # NOTE the cluster reads the BLACKBOX knobs at construction — before
    # setup hooks run — so a scenario override affects only journals
    # built AFTER setup (restart/spawn); flip these via the test/CLI
    # environment, not extra_env, to change a whole run's journaling
    "COMETBFT_TPU_TRACE_DUMP_ALL",
    "COMETBFT_TPU_BLACKBOX",
    "COMETBFT_TPU_BLACKBOX_SEGMENTS",
    "COMETBFT_TPU_BLACKBOX_SEGMENT_BYTES",
)


def _sim_device_runner(backend, pubs, msgs, sigs, lanes):
    """Host-backed stand-in for the device tier (supervisor device-runner
    seam): verdict-identical to the kernel by construction — it IS the
    kernel's differential oracle — but without the ~1.7 s-per-dispatch
    wall cost a real XLA dispatch pays on the throttled CI host.  The
    breaker/watchdog/injector machinery under test runs unchanged above
    this seam; COMETBFT_TPU_SIM_REAL_DEVICE=1 restores the real kernel."""
    import numpy as np

    from cometbft_tpu.crypto import ed25519_ref as ref

    out = np.zeros(lanes, dtype=bool)
    out[: len(pubs)] = [
        ref.verify_zip215(p, m, s) for p, m, s in zip(pubs, msgs, sigs)
    ]
    return out


def _backend_faults_setup(extra_env: Optional[dict] = None):
    """Build a Scenario.setup that forces the supervised device verify
    path and pins breaker backoff to the cluster's virtual clock.  The
    matching teardown restores every piece of process-global state."""

    def setup(cluster: SimCluster) -> None:
        from cometbft_tpu.crypto import backend_health
        from cometbft_tpu.crypto import batch as cbatch
        from cometbft_tpu.libs import tracing as _tracing

        saved_env = {k: os.environ.get(k) for k in _BACKEND_ENV_KNOBS}
        cluster._backend_saved = (saved_env, cbatch._DEFAULT_BACKEND)
        # the anomaly-dump latch (first-per-kind set + dump seq) is
        # process-global state exactly like the env knobs: setup hooks may
        # trip anomalies (warmup traffic, breaker pokes) and composed
        # scenarios run several setup/teardown pairs, so the latch rides
        # the same save/restore — teardown puts it back below
        cluster._dump_saved = _tracing.get_tracer().dump_state()
        # device path even on CPU hosts: the XLA kernel is verdict-equal to
        # the host reference, and that equality is what degradation relies on
        os.environ["COMETBFT_TPU_CRYPTO_BACKEND"] = "tpu"
        cbatch.set_default_backend("tpu")
        # without this every apply-time commit would resolve from verdicts
        # cached at gossip time and the fault window would exercise nothing
        os.environ["COMETBFT_TPU_SIGCACHE"] = "0"
        # scheduler OFF by default: the backend-* scenarios exercise the
        # supervisor chain BELOW the scheduler, and the per-verify flush
        # deadline would only slow them; gossip-burst re-enables it via
        # extra_env (it is the scheduler's own scenario)
        os.environ["COMETBFT_TPU_VERIFY_SCHED"] = "0"
        supervisor.clear_fault_injector()
        if os.environ.get("COMETBFT_TPU_SIM_REAL_DEVICE") == "1":
            # slow lane: real XLA dispatches.  Warm the kernel BEFORE the
            # scenario's env overrides apply — the first dispatch may
            # include a compile, which a scenario-shortened watchdog (e.g.
            # backend-wedge's 80 ms) would otherwise mistake for a wedge
            # and open the breaker at t=0.
            from cometbft_tpu.crypto import ed25519_ref as ref
            from cometbft_tpu.ops import verify as ov

            seed = b"\x07" * 32
            ov.verify_batch(
                [ref.pubkey_from_seed(seed)],
                [b"warmup"],
                [ref.sign(seed, b"warmup")],
            )
        else:
            supervisor.set_device_runner(_sim_device_runner)
        for k, v in (extra_env or {}).items():
            os.environ[k] = v
        # reset AFTER the env overrides so scenario breakers pick up the
        # overridden threshold (breaker knobs are read at creation), and
        # after the warmup so its breaker traffic doesn't leak into stats
        backend_health.reset()
        backend_health.registry().set_clock(cluster.clock.now)
        # fresh verify scheduler so it re-reads the scenario's flush/queue
        # knobs (the tpu backend forced above activates it), with clean
        # stats for the run's ScenarioResult capture
        from cometbft_tpu import verifysched

        verifysched.reset_scheduler()
        verifysched.stats.reset()

    return setup


def _backend_faults_teardown(cluster: SimCluster) -> None:
    from cometbft_tpu import verifysched
    from cometbft_tpu.crypto import backend_health
    from cometbft_tpu.crypto import batch as cbatch

    # drain + drop the scenario's scheduler BEFORE the env knobs flip back
    # (its dispatcher must finish under the scenario's device runner), and
    # zero its stats so nothing leaks into later tests
    verifysched.reset_scheduler()
    verifysched.stats.reset()
    supervisor.clear_fault_injector()
    supervisor.clear_device_runner()
    saved_env, saved_backend = getattr(cluster, "_backend_saved", ({}, None))
    for k in _BACKEND_ENV_KNOBS:
        v = saved_env.get(k)
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    cbatch.set_default_backend(saved_backend)
    backend_health.registry().set_clock(_wall.monotonic)
    backend_health.reset()
    dump_saved = getattr(cluster, "_dump_saved", None)
    if dump_saved is not None:
        from cometbft_tpu.libs import tracing as _tracing

        _tracing.get_tracer().restore_dump_state(dump_saved)
        cluster._dump_saved = None


def _sim_mesh_runner(ordinal, pubs, msgs, sigs, lanes):
    """Host-backed stand-in for ONE mesh shard (the elastic supervisor's
    ``set_mesh_runner`` seam): verdict-identical to the sharded kernel by
    construction — the host ZIP-215 oracle IS its differential oracle —
    without a real multi-device dispatch the 2-core CI host cannot
    afford.  Breakers, membership, the shrink ladder, re-admission probes
    and the FaultyDevice injector all run unchanged above this seam."""
    from cometbft_tpu.parallel import elastic

    return elastic.host_oracle_runner(ordinal, pubs, msgs, sigs, lanes)


SIM_MESH_WIDTH = 4  # virtual chip count the mesh scenarios run on


def _mesh_setup(extra_env: Optional[dict] = None, width: int = SIM_MESH_WIDTH):
    """Backend setup (forced tpu seam, virtual-clock breakers) PLUS an
    elastic mesh of ``width`` virtual ordinals on the per-shard host
    oracle.  Threshold 1 for the same reason the brownout scenario uses
    it: the in-process breaker registry is cluster-shared, so healthy
    traffic would otherwise keep resetting a sick ordinal's
    consecutive-failure count."""
    base = _backend_faults_setup(
        dict(
            {
                "COMETBFT_TPU_BREAKER_THRESHOLD": "1",
                # sim commits are a handful of signatures; the production
                # min-batch cutoff would keep them off the mesh path
                # under test
                "COMETBFT_TPU_MESH_MIN_BATCH": "1",
            },
            **(extra_env or {}),
        )
    )

    def setup(cluster: SimCluster) -> None:
        from cometbft_tpu.ops import device_health
        from cometbft_tpu.parallel import elastic

        base(cluster)
        # per-ordinal probe state is process-global like the breakers: a
        # previous run's down-marks must not swallow this run's flips
        device_health.reset()
        elastic.clear()
        elastic.configure(range(width))
        elastic.set_mesh_runner(_sim_mesh_runner)

    return setup


def _mesh_teardown(cluster: SimCluster) -> None:
    from cometbft_tpu.ops import device_health
    from cometbft_tpu.parallel import elastic

    elastic.clear()  # drops membership + runner + injector, zeroes width
    device_health.reset()
    _backend_faults_teardown(cluster)


def _chip_death(s: Scenario) -> list[Action]:
    """One chip of the virtual mesh dies mid-dispatch and STAYS dead:
    every later dispatch touching ordinal 2 raises, so the first dispatch
    after t=5 must shrink the mesh 4->3 and re-dispatch (only the failed
    dispatch re-runs); the mesh_dev2 breaker opens (threshold 1) and
    keeps the corpse out of membership, with each elapsed backoff costing
    exactly one failed one-bucket probe.  At t=8 a chip-watcher-style
    health probe reports ordinal 1 down too — PROACTIVE exclusion: the
    chip leaves membership before any dispatch pays a failure to find
    out; because that chip actually dispatches fine, its next half-open
    probe re-admits it (the probe dispatch is the arbiter, so a flaky
    watcher can't permanently cost a lane) while the truly dead ordinal
    2 stays out.  The fleet keeps committing throughout."""

    def die(c: SimCluster) -> None:
        from cometbft_tpu.parallel import elastic

        c._log("scenario: mesh ordinal 2 dies (every dispatch raises)")
        elastic.set_fault_injector(
            elastic.FaultyDevice("raise", ordinals=(2,))
        )

    def probe_down(c: SimCluster) -> None:
        from cometbft_tpu.ops import device_health

        c._log("scenario: health probe reports mesh ordinal 1 down")
        device_health.record_probe(
            False, source="chipwatch", t=c.clock.now(), ordinal=1
        )

    return [
        Action(5.0, "chip death: mesh ordinal 2", die),
        Action(8.0, "probe-down: mesh ordinal 1", probe_down),
    ]


def _mesh_brownout(s: Scenario) -> list[Action]:
    """A flapping chip: ordinal 1 fails in bursts (fail 2 / pass 4,
    counter-based so the run is deterministic per seed) from t=4 to t=12.
    The mesh must shrink on each failing burst, the mesh_dev1 breaker
    must cycle open -> half-open -> closed on the virtual-clock backoff
    (a pass-phase probe re-admits the chip: ``mesh_restore``), and after
    t=12 the mesh must settle back at full width — all without a single
    wrong verdict or a missed commit."""

    def flap(c: SimCluster) -> None:
        from cometbft_tpu.parallel import elastic

        c._log("scenario: mesh ordinal 1 flapping (fail 2 / pass 4)")
        elastic.set_fault_injector(
            elastic.FaultyDevice("flap", ordinals=(1,), fail_n=2, pass_n=4)
        )

    def stable(c: SimCluster) -> None:
        from cometbft_tpu.parallel import elastic

        c._log("scenario: mesh ordinal 1 stable again")
        elastic.clear_fault_injector()

    return [
        Action(4.0, "mesh brownout: ordinal 1 flaps", flap),
        Action(12.0, "mesh brownout ends", stable),
    ]


def _mesh_blackout(s: Scenario) -> list[Action]:
    """Three of the four mesh ordinals die at t=5 (overlapping the
    composed backend brownout's window): the mesh collapses below width 2
    and every batch falls into the SINGLE-CHIP chain — which the composed
    ``_backend_brownout`` is failing on the victim nodes at the same
    time, so the FULL ladder mesh(4)→3→2→xla→host is exercised in one
    storm.  At t=10.5 the chips heal; half-open probes re-admit them and
    the mesh climbs back to full width.  (A single flapping ordinal would
    never drop the width below 2, leaving the composed single-chip
    brownout dead code — this generator exists so combined-storm's
    degradation claim stays true with the mesh in the path.)"""

    def blackout(c: SimCluster) -> None:
        from cometbft_tpu.parallel import elastic

        c._log("scenario: mesh blackout (ordinals 1, 2, 3 die)")
        elastic.set_fault_injector(
            elastic.FaultyDevice("raise", ordinals=(1, 2, 3))
        )

    def restore(c: SimCluster) -> None:
        from cometbft_tpu.parallel import elastic

        c._log("scenario: mesh blackout ends")
        elastic.clear_fault_injector()

    return [
        Action(5.0, "mesh blackout: 3 of 4 ordinals die", blackout),
        Action(10.5, "mesh blackout ends", restore),
    ]


def _byzantine_voter(s: Scenario) -> list[Action]:
    """ROADMAP item 5 follow-up: a LIVE validator equivocates — from
    t=2 to t=8 the last validator double-signs every non-nil prevote and
    precommit it broadcasts (a second vote for a fabricated block id,
    signed with its real key, through the production gossip fabric).
    Honest nodes must detect the conflict in their vote sets
    (``ConflictingVoteError`` -> ``report_conflicting_votes``), convert
    it to ``DuplicateVoteEvidence`` at finalize through the evidence
    pool's consensus buffer, COMMIT the evidence in a later block, and
    keep agreement + validator-set invariants green — no crafted
    evidence anywhere in the path."""
    byz = s.n_vals - 1

    def start(c: SimCluster) -> None:
        import hashlib

        from cometbft_tpu.consensus.messages import VoteMessage
        from cometbft_tpu.types.basic import BlockID, PartSetHeader
        from cometbft_tpu.types.vote import Vote

        node = c.nodes[byz]
        if node is None:
            return
        orig = node.cs.broadcast_hook
        priv = c.privs[byz]
        chain_id = c.gdoc.chain_id
        c._log(
            "scenario: node%d turns byzantine (double-signs every vote)"
            % byz
        )

        def double(msg):
            orig(msg)
            if not isinstance(msg, VoteMessage):
                return
            v = msg.vote
            if v.block_id.is_zero():
                return
            # a second vote for a fabricated block at the SAME (height,
            # round, type) — a real equivocation, deterministically
            # derived from the honest vote it shadows
            alt = hashlib.sha256(
                b"byzantine-fork" + v.block_id.hash
                + v.height.to_bytes(8, "big") + bytes([v.type_])
            ).digest()
            v2 = Vote(
                type_=v.type_,
                height=v.height,
                round_=v.round_,
                block_id=BlockID(
                    hash=alt,
                    part_set_header=PartSetHeader(
                        total=1, hash=hashlib.sha256(alt + b"p").digest()
                    ),
                ),
                timestamp=v.timestamp,
                validator_address=v.validator_address,
                validator_index=v.validator_index,
            )
            v2.signature = priv.sign(v2.sign_bytes(chain_id))
            orig(VoteMessage(v2))

        c._byz_orig = (byz, orig)
        node.cs.broadcast_hook = double

    def stop(c: SimCluster) -> None:
        saved = getattr(c, "_byz_orig", None)
        if saved is None:
            return
        idx, orig = saved
        node = c.nodes[idx]
        if node is not None:
            node.cs.broadcast_hook = orig
            c._log("scenario: node%d honest again" % idx)
        c._byz_orig = None

    return [
        Action(2.0, "validator turns byzantine", start),
        Action(8.0, "byzantine validator stops double-signing", stop),
    ]


def _victims(n_vals: int) -> list[int]:
    """f+1 nodes lose their device: more than the Byzantine tolerance —
    agreement must survive anyway because degradation is verdict-
    preserving, not because the victims are outvoted."""
    return list(range(_f(n_vals) + 1))


def _install_victim_injector(cluster: SimCluster, shim) -> None:
    victims = set(_victims(cluster.n_vals))

    def inject(backend, pubs, msgs, sigs):
        if cluster.active_node not in victims:
            return None  # healthy node (or cluster-level work, e.g. checker)
        return shim(backend, pubs, msgs, sigs)

    supervisor.set_fault_injector(inject)


def _backend_brownout(s: Scenario) -> list[Action]:
    def down(c: SimCluster) -> None:
        c._log(
            "scenario: device backend down on nodes %s" % _victims(c.n_vals)
        )
        _install_victim_injector(c, supervisor.FaultyBackend("raise"))

    def aux_breakers(c: SimCluster) -> None:
        """Fail the single-tier secp256k1/BLS device breakers mid-brownout
        through the SAME supervised protocol the batch verifiers use: with
        the scenario's threshold of 1 each failure opens its breaker, and
        each breaker kind must produce its OWN anomaly dump — the ed25519
        brownout's breaker_open dump must not eat them
        (docs/observability.md anomaly taxonomy)."""

        def boom() -> None:
            raise RuntimeError("sim aux-device fault")

        for name in ("secp_device", "bls_g1"):
            out = supervisor.supervised_device_call(name, boom)
            c._log(
                "scenario: %s breaker poked (supervised -> %s)" % (name, out)
            )

    def up(c: SimCluster) -> None:
        c._log("scenario: device backend restored")
        supervisor.clear_fault_injector()

    return [
        Action(5.0, "device backend brownout (f+1 nodes)", down),
        Action(6.0, "secp/bls device breakers fail", aux_breakers),
        Action(10.0, "restore device backend", up),
    ]


def _backend_wedge(s: Scenario) -> list[Action]:
    def wedge(c: SimCluster) -> None:
        c._log(
            "scenario: device dispatches wedge on nodes %s" % _victims(c.n_vals)
        )
        # hang_s is real (wall) time: it must exceed the scenario's 80 ms
        # watchdog but stay small so abandoned workers drain quickly
        _install_victim_injector(
            c, supervisor.FaultyBackend("hang", hang_s=0.25)
        )

    def up(c: SimCluster) -> None:
        c._log("scenario: device backend unwedged")
        supervisor.clear_fault_injector()

    return [
        Action(4.0, "device backend wedge (f+1 nodes)", wedge),
        Action(9.0, "unwedge device backend", up),
    ]


def _backend_flap(s: Scenario) -> list[Action]:
    def flap(c: SimCluster) -> None:
        c._log("scenario: device backend flapping (all nodes)")
        # bursts of fail_n=4 failures (past the breaker threshold of 3)
        # followed by pass_n=2 clean dispatches: the breaker must open,
        # probe half-open on the virtual-clock backoff, re-promote on a
        # pass-phase probe, and re-open on the next burst
        supervisor.set_fault_injector(
            supervisor.FaultyBackend("flap", fail_n=4, pass_n=2)
        )

    def up(c: SimCluster) -> None:
        c._log("scenario: device backend stable")
        supervisor.clear_fault_injector()

    return [
        Action(3.0, "device backend flap", flap),
        Action(14.0, "stabilize device backend", up),
    ]


def _gossip_burst(s: Scenario) -> list[Action]:
    """Vote storm + scripted bulk-verify overload against the continuous-
    batching verification scheduler (docs/verify-scheduler.md): links
    duplicate and reorder gossip (so the same vote signature reaches nodes
    repeatedly and concurrently-queued duplicates exercise the in-flight
    dedup), while scripted bursts of seeded bulk-class submissions slam the
    scheduler's bounded queue past its (scenario-shrunk) capacity.
    Admission control must shed ONLY bulk-class items; consensus votes are
    exempt by design, so agreement and progress must be untouched and the
    trace stays byte-identical per seed (verdicts never depend on how items
    happened to coalesce)."""

    def storm(c: SimCluster) -> None:
        c.net.set_all_links(dup_rate=0.25, reorder_rate=0.5, reorder_jitter=0.5)

    def burst(c: SimCluster) -> None:
        import hashlib

        from cometbft_tpu import verifysched

        sched = verifysched.get_scheduler()
        tag = b"gossip-burst-%d-%d" % (c.seed, int(c.clock.now() * 1000))
        shed = 0
        futs = []
        # pause/resume brackets the burst so the overload is deterministic:
        # the sim is single-threaded (every consensus verify blocks on its
        # future), so the queue is empty here, the dispatcher cannot drain
        # mid-burst, and exactly queue_cap items are admitted
        sched.pause()
        try:
            for i in range(256):
                h = hashlib.sha256(tag + b"-%d" % i).digest()
                try:
                    futs.append(
                        sched.submit(
                            h,  # structurally valid, crypto garbage
                            b"burst-msg-%d" % i,
                            h + h,
                            verifysched.PRIO_BLOCKSYNC,
                        )
                    )
                except verifysched.QueueFullError:
                    shed += 1
        finally:
            sched.resume()
        # wait the admitted items out: the queue is empty again before the
        # action returns, so the next burst's shed count (logged into the
        # byte-compared trace) cannot depend on dispatcher wall-time
        for f in futs:
            assert f.result(timeout=30) is False  # garbage never verifies
        c._log("scenario: bulk burst of 256 submissions, %d shed" % shed)

    return [Action(0.0, "storm links: dup 25%, reorder 50%", storm)] + [
        Action(float(t), "bulk verify burst (256 items)", burst)
        for t in (3, 5, 7)
    ]


def _pipeline_burst(s: Scenario) -> list[Action]:
    """In-flight verify pipeline under deterministic load
    (docs/verify-scheduler.md "In-flight pipeline"): two paused bulk
    rounds submitted back-to-back while the completion pool is gated
    shut, so the dispatcher MUST ship the second fused flush while the
    first is still in flight (depth 2 — the pipelined high-water mark is
    captured in ScenarioResult.sched).  Every future still resolves with
    the definitive verdict before the action logs, so the byte-compared
    trace cannot depend on completion-pool timing."""

    def burst(c: SimCluster) -> None:
        import hashlib
        import threading
        import time as _time

        from cometbft_tpu import verifysched
        from cometbft_tpu.verifysched import stats as sstats

        sched = verifysched.get_scheduler()
        tag = b"pipeline-burst-%d-%d" % (c.seed, int(c.clock.now() * 1000))
        futs = []
        gate = threading.Event()
        orig = supervisor._DEVICE_RUNNER
        if orig is not None:
            # park the completion pool on the gate so the overlap is
            # deterministic, not a race the CI host may lose (slow lane
            # runs the real kernel and skips the gating)
            def gated(backend, pubs, msgs, sigs, lanes):
                gate.wait(20)
                return orig(backend, pubs, msgs, sigs, lanes)

            supervisor.set_device_runner(gated)
        try:
            # two paused rounds -> two separate drains -> two flushes;
            # flush B dispatches while flush A sits gated in flight
            for half in (b"a", b"b"):
                sched.pause()
                try:
                    for i in range(40):
                        h = hashlib.sha256(
                            tag + b"-" + half + b"-%d" % i
                        ).digest()
                        futs.append(
                            sched.submit(
                                h,  # structurally valid, crypto garbage
                                b"pipe-msg-%d" % i,
                                h + h,
                                verifysched.PRIO_BLOCKSYNC,
                            )
                        )
                finally:
                    sched.resume()
                if half == b"a" and orig is not None:
                    # don't let round b land in round a's drain: wait
                    # until flush A is dispatched (the gate pins it in
                    # flight), so round b forces a SECOND fused flush
                    deadline = _time.monotonic() + 10
                    while (
                        sstats.snapshot()["inflight_depth"] < 1
                        and _time.monotonic() < deadline
                    ):
                        _time.sleep(0.002)
            if orig is not None:
                deadline = _time.monotonic() + 10
                while (
                    sstats.snapshot()["inflight_depth"] < 2
                    and _time.monotonic() < deadline
                ):
                    _time.sleep(0.002)
            gate.set()
            # block on EVERY future before logging: nothing timing-
            # dependent may precede the byte-compared trace line
            for f in futs:
                assert f.result(timeout=30) is False
        finally:
            gate.set()
            if orig is not None:
                supervisor.set_device_runner(orig)
        c._log(
            "scenario: pipelined burst of %d submissions resolved"
            % len(futs)
        )

    return [
        Action(float(t), "pipelined bulk burst (2x40 items)", burst)
        for t in (3, 5)
    ]


def _light_stampede(s: Scenario) -> list[Action]:
    """Light-client read stampede against the proof-serving coalescer
    (docs/proof-serving.md): scripted bursts of tx/header/valset proof
    queries — thousands per burst against a scenario-shrunk queue — fire
    mid-consensus on node0's stores, on the host-oracle tree-runner seam.
    Admission control sheds only proof queries (nothing consensus-class
    rides this queue by construction); consensus agreement and progress
    must be untouched, every admitted future must resolve, and the
    response-bytes digest logged into the byte-compared trace makes the
    answers themselves part of the determinism check."""

    def stampede(c: SimCluster) -> None:
        import hashlib

        from cometbft_tpu import proofserve

        node = c.nodes[0]
        if node is None:
            return
        bs, ss = node.block_store, node.state_store

        def tx_loader(h):
            blk = bs.load_block(int(h))
            return None if blk is None else list(blk.data.txs)

        def header_hasher(h):
            meta = bs.load_block_meta(int(h))
            return None if meta is None else meta.header.hash()

        def valset_hasher(h):
            try:
                vals = ss.load_validators(int(h))
            except Exception:  # noqa: BLE001 — pruned/unknown height
                return None
            return None if vals is None else vals.hash()

        srv = proofserve.get_server()
        if srv is None:
            srv = proofserve.configure(tx_loader, header_hasher, valset_hasher)
        top = max(bs.height(), 1)
        shed = 0
        futs = []
        # pause/resume brackets the burst so the overload is
        # deterministic: the sim is single-threaded, so the dispatcher
        # cannot drain mid-burst and exactly queue_cap non-cache-hit
        # queries are admitted (LRU hits resolve without a slot)
        srv.pause()
        try:
            for i in range(1500):
                kind = ("header", "valset", "tx")[i % 3]
                h = max(1, top - (i % 2))
                try:
                    futs.append((kind, srv.submit(kind, h)))
                except proofserve.QueueFullError:
                    shed += 1
        finally:
            srv.resume()
        # wait every admitted future out (queue empty again before the
        # action returns — the next burst's shed count cannot depend on
        # dispatcher wall-time), folding the response bytes into a
        # digest: the ANSWERS are part of the byte-compared trace
        digest = hashlib.sha256()
        for kind, f in futs:
            res = f.result(timeout=30)
            if res is None:
                digest.update(b"\x00none")
            elif kind == "tx":
                root, proofs = res
                digest.update(root)
                for p in proofs:
                    digest.update(p.leaf_hash)
                    for a in p.aunts:
                        digest.update(a)
            else:
                digest.update(res)
        c._log(
            "scenario: proof stampede of 1500 queries at h=%d, %d shed, "
            "digest=%s" % (top, shed, digest.hexdigest()[:16])
        )

    return [
        Action(float(t), "light-client proof stampede (1500 queries)", stampede)
        for t in (3, 5, 7)
    ]


def _light_stampede_setup():
    base = _backend_faults_setup(
        {
            # verify scheduler ON so the run proves proof traffic cannot
            # shed consensus-class verifies (they ride different queues)
            "COMETBFT_TPU_VERIFY_SCHED": "1",
            "COMETBFT_TPU_PROOFSERVE": "1",
            "COMETBFT_TPU_PROOFSERVE_QUEUE": "512",
            "COMETBFT_TPU_PROOFSERVE_FLUSH_US": "500",
            # sim blocks are small: drop the min-batch gate so tree
            # passes actually traverse the plane's device path (the
            # host-oracle runner below keeps it off real XLA)
            "COMETBFT_TPU_MERKLE_MIN_BATCH": "4",
        }
    )

    def setup(cluster: SimCluster) -> None:
        base(cluster)
        from cometbft_tpu import proofserve
        from cometbft_tpu.ops import sha256_tree

        # host-oracle tree-runner seam: the breaker/stats machinery above
        # the seam runs unchanged, with no real XLA dispatch (mirrors
        # _sim_device_runner); cleared in teardown
        sha256_tree.set_tree_runner(sha256_tree.host_tree_runner)
        proofserve.reset_server()
        proofserve.stats.reset()

    return setup


def _light_stampede_teardown(cluster: SimCluster) -> None:
    from cometbft_tpu import proofserve
    from cometbft_tpu.ops import sha256_tree

    # drain the proof server BEFORE the env knobs flip back (its
    # dispatcher must finish under the scenario's tree runner)
    proofserve.reset_server()
    proofserve.stats.reset()
    sha256_tree.clear_tree_runner()
    _backend_faults_teardown(cluster)


def _dial_storm(s: Scenario) -> list[Action]:
    """Inbound-connection storm against the encrypted transport plane
    mid-consensus (docs/transport-plane.md): scripted waves of 600
    concurrent X25519 handshake admissions against a 256-slot pool queue
    plus coalesced AEAD frame batches (sizes straddling the 64-byte
    block edges, one deliberately tampered frame).  Shed handshakes fall
    to the sync dial — never a dropped connection — and every count and
    digest logged into the byte-compared trace is a function of the
    seeded inputs and verdicts only, never of flush timing.  The final
    wave re-runs a slice with both kill switches off and asserts the
    bytes are identical: the plane is an optimization, not a cipher."""

    def storm(c: SimCluster, wave: int) -> None:
        import hashlib

        from cometbft_tpu.crypto import aead_ref
        from cometbft_tpu.ops import x25519_ladder
        from cometbft_tpu.p2p import handshake_pool as hp
        from cometbft_tpu.p2p import transport_stats as tstats
        from cometbft_tpu.p2p import transportplane

        # deterministic dial population: scalars and peer keys are pure
        # functions of (wave, i) — the storm's trace bytes depend on
        # nothing else
        def scalar(i: int) -> bytes:
            return hashlib.sha256(b"dial-storm-%d-%d" % (wave, i)).digest()

        peer_pubs = [
            aead_ref.x25519(
                hashlib.sha256(b"dial-storm-peer-%d" % j).digest(),
                x25519_ladder.BASE_U,
            )
            for j in range(8)
        ]
        pairs = [(scalar(i), peer_pubs[i % 8]) for i in range(600)]

        # pause/resume brackets the burst so the overload is
        # deterministic: the sim is single-threaded, so the dispatcher
        # cannot drain mid-burst and exactly queue_cap dials are
        # admitted; the rest shed to the sync ladder
        pool = hp.get_pool()
        futs = []
        pool.pause()
        try:
            for p in pairs:
                try:
                    futs.append(pool.submit(*p))
                except hp.QueueFullError:
                    tstats.record_hs_shed()
                    futs.append(None)
        finally:
            pool.resume()
        digest = hashlib.sha256()
        shed = 0
        for f, p in zip(futs, pairs):
            if f is None:
                shed += 1
                tstats.record_handshake("sync")
                secret = hp.sync_exchange(*p)
            else:
                secret = f.result(timeout=30)
                tstats.record_handshake("pool")
            digest.update(secret)

        # coalesced AEAD leg: one batch of frames straddling the 64-byte
        # ChaCha block edges, with frame 25 tampered — the batch must
        # deliver exactly the 25-frame prefix and reject the rest
        key = hashlib.sha256(b"dial-storm-key-%d" % wave).digest()
        sizes = (0, 1, 63, 64, 65, 100, 128, 500, 1021, 1024) * 4
        payloads = [
            hashlib.sha256(b"frame-%d-%d" % (wave, i)).digest() * 32
            for i in range(len(sizes))
        ]
        payloads = [p[:n] for p, n in zip(payloads, sizes)]
        sealed = transportplane.seal_frames(key, 0, payloads)
        for ct in sealed:
            digest.update(ct)
        tampered = list(sealed)
        tampered[25] = tampered[25][:-1] + bytes(
            [tampered[25][-1] ^ 0x01]
        )
        pts, bad = transportplane.open_frames(key, 0, tampered)
        assert bad == 25 and pts == payloads[:25], (
            "tampered batch must deliver exactly the prefix before the "
            "bad tag"
        )
        c._log(
            "scenario: dial storm wave %d: 600 dials, %d shed, "
            "aead frames=%d delivered=%d bad_at=%d digest=%s"
            % (wave, shed, len(sealed), len(pts), bad,
               digest.hexdigest()[:16])
        )

    def kill_switch_parity(c: SimCluster) -> None:
        import hashlib

        from cometbft_tpu.p2p import handshake_pool as hp
        from cometbft_tpu.p2p import transportplane

        # plane output for a slice of deterministic inputs...
        key = hashlib.sha256(b"dial-storm-parity-key").digest()
        payloads = [
            hashlib.sha256(b"parity-%d" % i).digest() * 8 for i in range(8)
        ]
        scalars = [
            hashlib.sha256(b"parity-scalar-%d" % i).digest()
            for i in range(4)
        ]
        plane_sealed = transportplane.seal_frames(key, 0, payloads)
        plane_secrets = [hp.public_key(s) for s in scalars]
        # ...must be byte-identical with both kill switches off (the
        # serial pure-Python path): the plane is an optimization, never
        # a different cipher
        saved = {
            k: os.environ.get(k)
            for k in ("COMETBFT_TPU_AEAD", "COMETBFT_TPU_HANDSHAKE")
        }
        os.environ["COMETBFT_TPU_AEAD"] = "0"
        os.environ["COMETBFT_TPU_HANDSHAKE"] = "0"
        try:
            from cometbft_tpu.p2p.secret_connection import _HalfDuplex

            hd = _HalfDuplex(key)
            serial_sealed = [hd.seal(p) for p in payloads]
            serial_secrets = [hp.public_key(s) for s in scalars]
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        assert plane_sealed == serial_sealed, (
            "COMETBFT_TPU_AEAD=0 kill-switch parity broken"
        )
        assert plane_secrets == serial_secrets, (
            "COMETBFT_TPU_HANDSHAKE=0 kill-switch parity broken"
        )
        c._log("scenario: dial-storm kill-switch parity ok (8 frames, 4 keys)")

    return [
        Action(float(t), "inbound dial storm (600 handshakes)",
               lambda c, w=w: storm(c, w))
        for w, t in enumerate((3, 5, 7))
    ] + [
        Action(9.0, "kill-switch parity check", kill_switch_parity)
    ]


def _dial_storm_setup():
    base = _backend_faults_setup(
        {
            # verify scheduler ON so the run proves transport traffic
            # cannot shed consensus-class verifies (different queues)
            "COMETBFT_TPU_VERIFY_SCHED": "1",
            "COMETBFT_TPU_AEAD": "1",
            # sim batches are small: drop the min-batch gate so frame
            # batches actually traverse the plane (the host-oracle
            # runners below keep everything off real XLA)
            "COMETBFT_TPU_AEAD_MIN_BATCH": "4",
            "COMETBFT_TPU_HANDSHAKE": "1",
            "COMETBFT_TPU_HANDSHAKE_QUEUE": "256",
            "COMETBFT_TPU_HANDSHAKE_FLUSH_US": "500",
            "COMETBFT_TPU_HANDSHAKE_MAX_BATCH": "128",
        }
    )

    def setup(cluster: SimCluster) -> None:
        base(cluster)
        from cometbft_tpu.ops import chacha_aead, x25519_ladder
        from cometbft_tpu.p2p import handshake_pool
        from cometbft_tpu.p2p import transport_stats as tstats

        # host-oracle runner seams: the pool/breaker/stats machinery
        # above the seams runs unchanged, with no real XLA dispatch
        # (mirrors _sim_device_runner); cleared in teardown
        x25519_ladder.set_ladder_runner(x25519_ladder.host_ladder_runner)
        chacha_aead.set_aead_runner(chacha_aead.host_aead_runner)
        handshake_pool.reset_pool()
        tstats.reset()

    return setup


def _dial_storm_teardown(cluster: SimCluster) -> None:
    from cometbft_tpu.ops import chacha_aead, x25519_ladder
    from cometbft_tpu.p2p import handshake_pool
    from cometbft_tpu.p2p import transport_stats as tstats

    # drain the pool BEFORE the env knobs flip back (its dispatcher must
    # finish under the scenario's ladder runner)
    handshake_pool.reset_pool()
    tstats.reset()
    x25519_ladder.clear_ladder_runner()
    chacha_aead.clear_aead_runner()
    _backend_faults_teardown(cluster)


def _txflood_app():
    """Envelope-verifying kvstore: signature checks hoisted onto the
    crypto seam, payloads (``key=value``) served by the stock app."""
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.txingest import SigVerifyingApp

    return SigVerifyingApp(KVStoreApplication())


def _tx_flood_setup(cluster: SimCluster) -> None:
    from cometbft_tpu.txingest import stats as istats

    _backend_faults_setup(
        {
            # apply-time re-checks (process-proposal, finalize, recheck)
            # must resolve from cache — that's the pipeline under test
            "COMETBFT_TPU_SIGCACHE": "1",
            "COMETBFT_TPU_VERIFY_SCHED": "1",
            "COMETBFT_TPU_SCHED_FLUSH_US": "500",
            "COMETBFT_TPU_TXINGEST": "1",
            # a queue far smaller than the burst: most of each burst must
            # shed to the per-tx sync path and STILL reach a verdict
            "COMETBFT_TPU_TXINGEST_QUEUE": "32",
            "COMETBFT_TPU_TXINGEST_BATCH": "24",
        }
    )(cluster)
    istats.reset()


def _tx_flood_teardown(cluster: SimCluster) -> None:
    from cometbft_tpu.txingest import stats as istats

    _backend_faults_teardown(cluster)
    istats.reset()


def _tx_flood(s: Scenario) -> list[Action]:
    """Sustained signed-tx bursts against every node's mempool through a
    deterministically-driven ingest coalescer (docs/tx-ingest.md).  Each
    burst mixes valid ed25519/secp256k1 envelopes, forged signatures,
    malformed envelopes, an oversize tx, in-burst duplicates and re-sends
    of burst 0 (cross-burst duplicates, incl. committed txs).  The
    coalescer queue (32 slots, scenario-shrunk) is far smaller than the
    burst, so most submissions shed to the per-tx sync path — a shed
    costs the batching win, never a verdict.  Every count logged into the
    byte-compared trace is a function of verdicts and the seeded
    submission order only, never of flush timing."""

    def burst(c: SimCluster, burst_no: int) -> None:
        from cometbft_tpu.abci import types as at
        from cometbft_tpu.crypto import keys as ck
        from cometbft_tpu.crypto.secp256k1 import Secp256k1PrivKey
        from cometbft_tpu.mempool.clist_mempool import (
            MempoolError,
            TxInCacheError,
        )
        from cometbft_tpu.txingest import IngestCoalescer
        from cometbft_tpu.txingest import envelope as ev

        privs = [
            ck.Ed25519PrivKey.from_seed(bytes([0x20 + i]) * 32)
            for i in range(3)
        ]
        secp = Secp256k1PrivKey.from_secret(b"\x41" * 32)

        def valid(b: int, i: int) -> bytes:
            # nonces advance across bursts (b*100+i): a well-behaved sender
            # never reuses one, so the coalescer's replay LRU only fires on
            # the scripted replays below
            return ev.sign_tx(
                privs[i % len(privs)], b"f%d_%d=%d" % (b, i, i),
                nonce=b * 100 + i,
            )

        txs: "list[bytes]" = [valid(burst_no, i) for i in range(36)]
        txs.append(
            ev.sign_tx(secp, b"s%d=%d" % (burst_no, burst_no), nonce=burst_no)
        )
        # forged: structurally valid envelope, signature from a different
        # preimage (nonce bumped after signing — far past any nonce a later
        # burst will legitimately use, and never recorded by the replay LRU
        # because the signature never verifies)
        for i in range(4):
            g = ev.decode(txs[i])
            txs.append(
                ev.encode(
                    ev.Envelope(
                        g.key_type, g.pubkey, g.nonce + 100_000, g.payload,
                        g.signature,
                    )
                )
            )
        # malformed: envelope magic, garbage structure
        for i in range(3):
            txs.append(ev.MAGIC + b"\x7fgarbage-%d-%d" % (burst_no, i))
        # oversize: past the scenario mempool's 2048-byte max_tx_bytes
        txs.append(
            ev.sign_tx(privs[0], b"big%d=" % burst_no + b"x" * 4096, nonce=99)
        )
        # in-burst duplicates (same bytes twice before any flush) plus,
        # after burst 0, re-sends of burst 0's first txs — cross-burst
        # duplicates that are by then cached and possibly committed — and
        # REPLAYS: fresh payloads re-signed under burst 0's nonces, which
        # must die at ingest with the canonical stale-nonce code instead
        # of reaching the app (docs/tx-ingest.md replay protection)
        txs += [valid(burst_no, 0), valid(burst_no, 1)]
        if burst_no > 0:
            txs += [valid(0, 0), valid(0, 1)]
            for i in range(2):
                txs.append(
                    ev.sign_tx(
                        privs[i], b"replay%d_%d=1" % (burst_no, i), nonce=i
                    )
                )
        c.rng.shuffle(txs)

        ingestors = getattr(c, "_flood_ingest", None)
        if ingestors is None:
            ingestors = c._flood_ingest = {}
        for i, node in enumerate(c.live_nodes()):
            outcomes = {"ok": 0, "rejected": 0, "errors": 0}

            def note(sender, res, o=outcomes):
                if isinstance(res, at.CheckTxResponse):
                    o["ok" if res.ok else "rejected"] += 1
                else:
                    o["errors"] += 1

            # one coalescer per node for the whole run (like production):
            # its verified-nonce LRU must span bursts for replay rejection
            ing = ingestors.get(node.index)
            if ing is None:
                ing = ingestors[node.index] = IngestCoalescer(
                    node.mempool, start_thread=False
                )
            ing.on_result = note
            queued = dedup = synced = 0
            for tx in txs:
                try:
                    res = ing.submit(tx, sender="flood")
                except TxInCacheError:
                    dedup += 1
                    continue
                except MempoolError:
                    outcomes["errors"] += 1
                    synced += 1
                    continue
                if res is None:
                    queued += 1
                else:
                    synced += 1
                    note("flood", res)
            ing.flush_now()
            c._log(
                "scenario: tx-flood burst %d node%d: queued=%d shed_sync=%d "
                "dedup=%d ok=%d rejected=%d errors=%d"
                % (
                    burst_no,
                    i,
                    queued,
                    synced,
                    dedup,
                    outcomes["ok"],
                    outcomes["rejected"],
                    outcomes["errors"],
                )
            )

    return [
        Action(float(t), "signed-tx flood burst %d" % b,
               lambda c, b=b: burst(c, b))
        for b, t in enumerate((2, 4, 6, 8))
    ]


def _message_storm(s: Scenario) -> list[Action]:
    def inject_txs(c: SimCluster) -> None:
        h = c.live_nodes()[0].cs.rs.height
        for node in c.live_nodes():
            node.mempool.check_tx(b"storm%d=%d" % (h, h))

    acts = [
        Action(
            0.0,
            "storm links: dup 25%, reorder 50%",
            lambda c: c.net.set_all_links(
                dup_rate=0.25, reorder_rate=0.5, reorder_jitter=0.5
            ),
        )
    ]
    acts += [
        Action(float(t), "inject txs", inject_txs) for t in (2, 5, 8, 11, 14)
    ]
    return acts


# -- fleet-scale churn / rotation scenarios ----------------------------------


def _retrying_join(
    c: SimCluster, idx: int, attempt: int = 0, max_attempts: int = 10
) -> None:
    """Statesync-join ``idx``, retrying every 2 virtual seconds while no
    viable snapshot exists (or another join is mid-flight).  All retries
    ride the scripted clock, so the whole dance replays from the seed."""
    if c.nodes[idx] is not None:
        return
    if not c.join(idx) and attempt + 1 < max_attempts:
        c.clock.call_later(
            2.0,
            lambda: _retrying_join(c, idx, attempt + 1, max_attempts),
            label=f"scenario join-retry node{idx}",
        )


def _validator_rotation(s: Scenario) -> list[Action]:
    """A standby full node comes online at genesis, gets voted in, and a
    genesis validator is voted out — the minimal end-to-end rotation on
    the production validate_validator_updates path."""
    spare = s.n_vals  # first spare index

    return [
        Action(1.0, f"spawn standby node{spare}",
               lambda c: c.spawn_spare(spare)),
        Action(3.0, f"vote node{spare} into the validator set",
               lambda c: c.add_validator(spare)),
        Action(7.0, "vote node0 out of the validator set",
               lambda c: c.remove_validator(0)),
    ]


def _fleet_churn(s: Scenario) -> list[Action]:
    """The fleet acceptance script: validator rotation + node churn in one
    run.  A spare is voted in and later joins as a FRESH machine via
    statesync; the last genesis validator is voted out and gracefully
    leaves; another validator hard-crashes and restarts from its stores.
    Scales with n_vals — the nightly lane runs it at 100 validators, the
    tier-1 lane at a single-digit size."""
    spare = s.n_vals
    leaver = s.n_vals - 1
    crasher = 1

    return [
        Action(2.0, f"vote spare node{spare} in",
               lambda c: c.add_validator(spare)),
        Action(3.0, f"vote node{leaver} out",
               lambda c: c.remove_validator(leaver)),
        Action(8.0, f"node{leaver} leaves gracefully",
               lambda c: c.leave(leaver)),
        Action(9.0, f"node{spare} joins via statesync",
               lambda c: _retrying_join(c, spare)),
        Action(11.0, f"crash node{crasher}", lambda c: c.crash(crasher)),
        Action(15.0, f"restart node{crasher}", lambda c: c.restart(crasher)),
    ]


def _statesync_storm(s: Scenario) -> list[Action]:
    """Two joiners statesync through lossy links while a serving peer
    crashes mid-sync: chunk re-requests must back off exponentially and
    rotate to surviving peers, and both joins must still complete."""
    j1, j2 = s.n_vals, s.n_vals + 1

    def degrade(c: SimCluster) -> None:
        c.net.set_node_links(j1, drop_rate=0.25)
        c.net.set_node_links(j2, drop_rate=0.25)

    return [
        Action(0.0, "25% loss on both joiners' links", degrade),
        Action(9.0, f"node{j1} joins via statesync (lossy)",
               lambda c: _retrying_join(c, j1)),
        Action(10.0, "crash node3 (a chunk-serving peer)",
               lambda c: c.crash(3)),
        Action(11.0, f"node{j2} joins via statesync (lossy)",
               lambda c: _retrying_join(c, j2)),
        Action(15.0, "restart node3", lambda c: c.restart(3)),
    ]


# -- blocksync catchup scenarios ---------------------------------------------


def _retrying_bsync_join(
    c: SimCluster, idx: int, attempt: int = 0, max_attempts: int = 10
) -> None:
    """Blocksync-join ``idx``, retrying every 2 virtual seconds while no
    live helper exists (or a previous join is still in flight).  All
    retries ride the scripted clock, so the dance replays from the seed."""
    if c.nodes[idx] is not None:
        return
    if not c.blocksync_join(idx) and attempt + 1 < max_attempts:
        c.clock.call_later(
            2.0,
            lambda: _retrying_bsync_join(c, idx, attempt + 1, max_attempts),
            label=f"scenario bsync-retry node{idx}",
        )


def _bsync_fault(j: int, method: str, src: int, on: bool):
    """Action body toggling a fault hook on joiner ``j``'s live harness —
    a no-op once the joiner has promoted (the harness is gone), so late
    scripted toggles can't crash a run that synced faster than scripted."""

    def act(c: SimCluster) -> None:
        h = c.blocksync_harness(j)
        if h is not None:
            getattr(h, method)(src, on)

    return act


def _blocksync_storm(s: Scenario) -> list[Action]:
    """A late joiner blocksyncs 40+ heights through lossy high-latency
    links while the helper set misbehaves: node1 goes mute mid-window
    (adaptive RTT timeouts fire and its requests re-assign), node2 serves
    forged block bodies (validate_block redo + exponential ban, then a
    half-open probe re-admits it once clean), and the joiner itself
    crash-restarts mid-catchup and resumes from its surviving stores."""
    j = s.n_vals

    def shape(c: SimCluster) -> None:
        c.net.set_node_links(
            j,
            delay_min=0.25,
            delay_max=0.7,
            drop_rate=0.2,
            bandwidth_bytes_per_s=65536.0,
        )

    return [
        Action(0.0, "WAN-grade loss/latency on the joiner's links", shape),
        Action(45.0, f"node{j} joins via blocksync (lossy)",
               lambda c: _retrying_bsync_join(c, j)),
        Action(47.0, "mute helper node1 (stall mid-window)",
               _bsync_fault(j, "set_mute", 1, True)),
        Action(48.0, "helper node2 starts forging block bodies",
               _bsync_fault(j, "set_tamper", 2, True)),
        Action(49.5, f"crash joiner node{j} mid-catchup",
               lambda c: c.blocksync_crash(j)),
        Action(50.5, f"node{j} resumes blocksync from its stores",
               lambda c: _retrying_bsync_join(c, j)),
        # the fresh pool's first volley lands on a forging node2: the
        # forged bodies sit just ahead of the low frontier, so the
        # validate-redo ban fires mid-storm and the half-open probe (and
        # re-admission) play out while the catchup is still running
        Action(50.6, "helper node2 forges again (post-restart)",
               _bsync_fault(j, "set_tamper", 2, True)),
        Action(53.0, "helper node2 behaves again",
               _bsync_fault(j, "set_tamper", 2, False)),
        Action(53.5, "mute helper node1 again (post-restart)",
               _bsync_fault(j, "set_mute", 1, True)),
        Action(58.0, "unmute helper node1",
               _bsync_fault(j, "set_mute", 1, False)),
        # the storm passes: with ~1.5 s lossy RTT against a ~1 s block
        # interval the head-chase can hover forever; clean links let the
        # joiner converge and switch to consensus
        Action(60.0, "storm passes: joiner links recover",
               lambda c, j=j: c.net.set_node_links(
                   j, delay_min=0.01, delay_max=0.05, drop_rate=0.0,
                   bandwidth_bytes_per_s=0.0)),
    ]


def _wan_catchup(s: Scenario) -> list[Action]:
    """3-region geo topology: a joiner in region 2 blocksyncs cross-region
    while consensus continues; mid-sync its whole region is geo-partitioned
    off (the 5-of-7 majority keeps committing — cutting the region costs
    only 2 validators — while the joiner drains its frozen intra-region
    helpers) and after heal it catches the moving head."""
    j = s.n_vals
    regions = [[0, 1, 2], [3, 4], [5, 6, j]]

    def shape(c: SimCluster) -> None:
        c.net.set_geo_clusters(regions, bandwidth_bytes_per_s=262144.0)

    return [
        Action(0.0, "geo-cluster fabric: 3 regions, bandwidth-shaped",
               shape),
        Action(62.0, f"node{j} joins via blocksync cross-region",
               lambda c: _retrying_bsync_join(c, j)),
        # mid-volley: cross-region requests in flight are yanked with the
        # cable, time out on the adaptive schedule and re-assign to the
        # joiner's (frozen) intra-region helpers
        Action(62.3, "geo-partition the joiner's region off",
               lambda c: c.net.geo_partition(2)),
        Action(68.0, "heal the geo-partition", lambda c: c.net.heal()),
    ]


# -- adversarial evidence scenarios ------------------------------------------


def _craft_dup_vote(c: SimCluster, signer: int, height: int, round_: int,
                    tag: bytes, forge: bool = False):
    """Real (or, with ``forge``, signature-broken) DuplicateVoteEvidence:
    validator ``signer`` double-signs two synthetic block ids at a
    committed height, timestamped to that height's block time so the
    production evidence verification chain accepts it."""
    import hashlib

    from cometbft_tpu.types.basic import (
        PRECOMMIT_TYPE,
        BlockID,
        PartSetHeader,
    )
    from cometbft_tpu.types.evidence import DuplicateVoteEvidence
    from cometbft_tpu.types.vote import Vote

    node = c.live_nodes()[0]
    meta = node.block_store.load_block_meta(height)
    vals = node.state_store.load_validators(height)
    priv = c.privs[signer]
    addr = priv.pub_key().address()
    idx, val = vals.get_by_address(addr)

    def mk(sub: bytes) -> Vote:
        seed = tag + sub
        bid = BlockID(
            hash=hashlib.sha256(seed).digest(),
            part_set_header=PartSetHeader(
                total=1, hash=hashlib.sha256(seed + b"p").digest()
            ),
        )
        v = Vote(
            type_=PRECOMMIT_TYPE,
            height=height,
            round_=round_,
            block_id=bid,
            timestamp=meta.header.time,
            validator_address=addr,
            validator_index=idx,
        )
        v.signature = priv.sign(v.sign_bytes(c.gdoc.chain_id))
        return v

    v1, v2 = mk(b"a"), mk(b"b")
    if forge:
        v2.signature = bytes(64)  # structurally plausible, never verifies
    return DuplicateVoteEvidence.from_votes(
        v1, v2, meta.header.time, val.voting_power, vals.total_voting_power()
    )


def _craft_light_attack(c: SimCluster, common_height: int,
                        signers: list[int], forge: bool = False):
    """Lunatic light-client attack: the header at common_height+1 with a
    forged app_hash, committed by ``signers`` (a >1/3 subset of the common
    validator set).  With ``forge`` the signatures are broken, so the
    evidence must be REJECTED."""
    import dataclasses
    import hashlib

    from cometbft_tpu.types.basic import (
        BLOCK_ID_FLAG_ABSENT,
        BLOCK_ID_FLAG_COMMIT,
        PRECOMMIT_TYPE,
        BlockID,
        PartSetHeader,
    )
    from cometbft_tpu.types.block import Commit
    from cometbft_tpu.types.evidence import LightClientAttackEvidence
    from cometbft_tpu.types.light import LightBlock, SignedHeader
    from cometbft_tpu.types.vote import CommitSig, Vote

    node = c.live_nodes()[0]
    h = common_height + 1
    real = node.block_store.load_block_meta(h)
    common_meta = node.block_store.load_block_meta(common_height)
    vals_h = node.state_store.load_validators(h)
    common_vals = node.state_store.load_validators(common_height)

    forged_header = dataclasses.replace(
        real.header, app_hash=hashlib.sha256(b"lunatic-app-state").digest()
    )
    bid = BlockID(
        hash=forged_header.hash(),
        part_set_header=PartSetHeader(
            total=1, hash=hashlib.sha256(b"lunatic-parts").digest()
        ),
    )
    signer_addrs = {c.privs[i].pub_key().address() for i in signers}
    sigs = []
    for idx, val in enumerate(vals_h.validators):
        if val.address not in signer_addrs:
            sigs.append(
                CommitSig(
                    block_id_flag=BLOCK_ID_FLAG_ABSENT,
                    validator_address=b"",
                    timestamp=forged_header.time,
                    signature=b"",
                )
            )
            continue
        priv = next(
            c.privs[i]
            for i in signers
            if c.privs[i].pub_key().address() == val.address
        )
        v = Vote(
            type_=PRECOMMIT_TYPE,
            height=h,
            round_=0,
            block_id=bid,
            timestamp=forged_header.time,
            validator_address=val.address,
            validator_index=idx,
        )
        sig = priv.sign(v.sign_bytes(c.gdoc.chain_id))
        sigs.append(
            CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=val.address,
                timestamp=forged_header.time,
                signature=bytes(64) if forge else sig,
            )
        )
    commit = Commit(height=h, round_=0, block_id=bid, signatures=sigs)
    byzantine = [
        common_vals.get_by_address(a)[1]
        for a in sorted(signer_addrs)
        if common_vals.get_by_address(a) is not None
    ]
    return LightClientAttackEvidence(
        conflicting_block=LightBlock(
            signed_header=SignedHeader(header=forged_header, commit=commit),
            validator_set=vals_h,
        ),
        common_height=common_height,
        byzantine_validators=byzantine,
        total_voting_power=common_vals.total_voting_power(),
        timestamp=common_meta.header.time,
    )


def _flood_pools(c: SimCluster, pieces: list, label: str) -> None:
    """Offer every crafted piece to every live node's evidence pool (the
    sim analog of evidence gossip), counting outcomes per node into the
    byte-compared trace."""
    from cometbft_tpu.types.evidence import EvidenceError

    for node in c.live_nodes():
        before = node.evidence_pool.occupancy()
        rejected = 0
        for ev in pieces:
            try:
                node.evidence_pool.add_evidence(ev)
            except EvidenceError:
                rejected += 1
        depth, size = node.evidence_pool.occupancy()
        c._log(
            "scenario: %s node%d: offered=%d rejected=%d pool=%d->%d (%dB)"
            % (label, node.index, len(pieces), rejected, before[0], depth, size)
        )


def _dup_vote_flood(s: Scenario) -> list[Action]:
    """Duplicate-vote flood into the evidence pool: each wave mixes fresh
    real equivocations (distinct rounds), byte-identical duplicates of the
    first wave, and signature-forged pieces.  Dedup must catch repeats
    before any signature work, the scenario-shrunk pool bound must degrade
    overflow to counted drops, forgeries must be rejected — and verified
    evidence must still reach blocks through proposals while consensus
    stays unshed."""

    def flood(c: SimCluster, wave: int) -> None:
        height = 2  # committed well before the first wave fires
        pieces = []
        for j in range(12):
            pieces.append(
                _craft_dup_vote(
                    c, signer=1, height=height, round_=wave * 32 + j,
                    tag=b"flood-%d-%d" % (wave, j),
                )
            )
        # duplicates of wave 0 (identical bytes -> pool dedup, no sig work)
        for j in range(12):
            pieces.append(
                _craft_dup_vote(
                    c, signer=1, height=height, round_=j,
                    tag=b"flood-0-%d" % j,
                )
            )
        # forged: must be rejected by verification, never pooled
        for j in range(4):
            pieces.append(
                _craft_dup_vote(
                    c, signer=2, height=height, round_=wave * 32 + j,
                    tag=b"forged-%d-%d" % (wave, j), forge=True,
                )
            )
        _flood_pools(c, pieces, "dup-vote flood wave %d" % wave)

    return [
        Action(float(t), "duplicate-vote flood wave %d" % w,
               lambda c, w=w: flood(c, w))
        for w, t in enumerate((4, 6, 8))
    ]


def _light_attack(s: Scenario) -> list[Action]:
    """Light-client-attack evidence: a real lunatic forgery (>1/3 of the
    common set double-signing a conflicting header) must verify on the
    evidence seam and reach a block; a signature-broken variant must be
    rejected.  Both ride the verify scheduler's evidence class without
    ever blocking consensus submissions."""

    def attack(c: SimCluster) -> None:
        real = _craft_light_attack(c, common_height=2, signers=[0, 1])
        broken = _craft_light_attack(
            c, common_height=3, signers=[0, 1], forge=True
        )
        _flood_pools(c, [real, broken], "light attack")

    return [Action(6.0, "light-client attack evidence", attack)]


def _evidence_setup(extra_env: Optional[dict] = None, pool_max: int = 16):
    """Backend setup (host-oracle seam, scheduler ON so evidence checks
    ride the evidence class) plus a scenario-shrunk evidence pool bound and
    clean evidence counters."""
    base = _backend_faults_setup(
        dict(
            {
                "COMETBFT_TPU_VERIFY_SCHED": "1",
                "COMETBFT_TPU_SCHED_FLUSH_US": "500",
            },
            **(extra_env or {}),
        )
    )

    def setup(cluster: SimCluster) -> None:
        from cometbft_tpu.evidence import stats as evstats

        base(cluster)
        evstats.reset()
        for node in cluster.live_nodes():
            node.evidence_pool.max_pending = pool_max

    return setup


def _evidence_teardown(cluster: SimCluster) -> None:
    from cometbft_tpu.evidence import stats as evstats

    _backend_faults_teardown(cluster)
    evstats.reset()


# -- disk-fault scenarios (docs/storage-robustness.md) ------------------------


def _disk_setup(cluster: SimCluster) -> None:
    """Install a fresh ``diskguard.FaultPlan`` for the run and pin the
    retry-backoff sleeper to a no-op: injection windows are COUNT-based
    (rule ordinals over the deterministic per-seed IO sequence), so wall
    sleeps would only slow the run without adding determinism."""
    from cometbft_tpu.libs import diskguard as dg

    cluster._disk_prev_plan = dg.set_fault_plan(dg.FaultPlan())
    dg.set_sleeper(lambda _s: None)


def _disk_teardown(cluster: SimCluster) -> None:
    from cometbft_tpu.libs import diskguard as dg

    dg.set_fault_plan(getattr(cluster, "_disk_prev_plan", None))
    dg.set_sleeper(None)


DISK_VICTIM = 1  # the node whose disk the disk-* scenarios break


def _disk_full(s: Scenario) -> list[Action]:
    """ENOSPC on one node's whole disk at t=5: its WAL (fail-stop) halts
    it before its next vote — no equivocation, ever — while its blackbox
    journal (degradable) degrades to counted drops.  The survivors keep
    agreement and reach the target without it."""

    def fill(c: SimCluster) -> None:
        import errno as _errno

        from cometbft_tpu.libs import diskguard as dg

        plan = dg.get_fault_plan()
        c._log(
            "scenario: node%d disk full (ENOSPC, wal fail-stop + "
            "blackbox degrade)" % DISK_VICTIM
        )
        node_tag = "node%d/" % DISK_VICTIM
        plan.add(
            surface="wal", path_substr=node_tag, err=_errno.ENOSPC
        )
        plan.add(
            surface="blackbox", path_substr=node_tag, err=_errno.ENOSPC
        )

    return [Action(5.0, "disk full on node%d" % DISK_VICTIM, fill)]


def _disk_brownout(s: Scenario) -> list[Action]:
    """Transient EIO bursts against the degradable blackbox surface:
    short bursts (shorter than the retry budget) recover via bounded
    exponential backoff with ZERO drops; one long burst exhausts the
    budget and degrades to counted drops.  Consensus never notices."""

    def burst(c: SimCluster, n: int) -> None:
        import errno as _errno

        from cometbft_tpu.libs import diskguard as dg

        dg.get_fault_plan().add(
            surface="blackbox", err=_errno.EIO, count=n
        )
        c._log("scenario: blackbox EIO burst len=%d" % n)

    return [
        Action(4.0, "EIO burst (retries recover)", lambda c: burst(c, 2)),
        Action(6.0, "EIO burst (retries recover)", lambda c: burst(c, 2)),
        Action(8.0, "EIO burst (retries recover)", lambda c: burst(c, 2)),
        Action(10.0, "EIO burst (exhausts retries)", lambda c: burst(c, 8)),
    ]


def _torn_wal_restart(s: Scenario) -> list[Action]:
    """Kill a node mid-frame: crash it, then cut its WAL head mid-way
    through the final frame — the torn tail a power cut leaves.  On
    restart the boot-time scrub truncates to the last CRC-valid frame
    (``wal_repair`` journaled, dropped bytes counted), the node replays
    to the repaired tail and rejoins the fleet."""

    def kill_mid_frame(c: SimCluster) -> None:
        import io as _io

        from cometbft_tpu.consensus.wal import read_frame

        c.crash(DISK_VICTIM)
        wal_path = c.root / ("node%d" % DISK_VICTIM) / "cs.wal"
        try:
            data = wal_path.read_bytes()
        except OSError:
            data = b""
        # walk the valid frames (the WAL's own parser); cut halfway into
        # the final one
        f = _io.BytesIO(data)
        pos, last_start = 0, None
        while True:
            _kind, _payload, reason = read_frame(f)
            if reason is not None:
                break
            last_start = pos
            pos = f.tell()
        if last_start is not None:
            cut = last_start + 8 + max((pos - last_start - 8) // 2, 1)
        else:
            cut = max(len(data) - 1, 0)
        os.truncate(wal_path, cut)
        c._log(
            "scenario: tore node%d WAL mid-frame at byte %d (was %d)"
            % (DISK_VICTIM, cut, len(data))
        )

    return [
        Action(6.0, "kill node%d mid-frame" % DISK_VICTIM, kill_mid_frame),
        Action(
            20.0,
            "restart node%d (scrub + replay)" % DISK_VICTIM,
            lambda c: c.restart(DISK_VICTIM),
        ),
    ]


SCENARIOS: dict[str, Scenario] = {
    s.name: s
    for s in [
        Scenario(
            "baseline",
            "clean 4-validator run, default delay/jitter links",
        ),
        Scenario(
            "partition-minority",
            "cut off f nodes for 22 virtual seconds, heal, require full "
            "recovery with no fork",
            max_time=180.0,
            actions=_partition_minority,
        ),
        Scenario(
            "partition-leader",
            "cut off the current proposer, forcing round changes; heal and "
            "require it to catch back up",
            max_time=180.0,
            actions=_partition_leader,
        ),
        Scenario(
            "crash-restart",
            "kill f nodes mid-run; restart them from their stores (WAL + "
            "Handshaker replay) and require rejoin",
            max_time=180.0,
            actions=_crash_restart,
        ),
        Scenario(
            "asymmetric-loss",
            "30% one-directional message loss on node0's outbound links",
            max_time=240.0,
            actions=_asymmetric_loss,
        ),
        Scenario(
            "message-storm",
            "duplicate and aggressively reorder every link while txs flow",
            max_time=240.0,
            actions=_message_storm,
        ),
        Scenario(
            "gossip-burst",
            "vote storm (dup/reorder links) plus scripted 256-item bulk "
            "bursts against a 48-slot verify-scheduler queue: admission "
            "control must shed only bulk-class items, never consensus "
            "votes; agreement holds and traces stay byte-identical per "
            "seed.  Runs on the host-oracle device-runner seam so tier-1 "
            "never pays real XLA dispatches",
            target_height=6,
            max_time=180.0,
            actions=_gossip_burst,
            setup=_backend_faults_setup(
                {
                    "COMETBFT_TPU_VERIFY_SCHED": "1",
                    "COMETBFT_TPU_SCHED_QUEUE": "48",
                    "COMETBFT_TPU_SCHED_FLUSH_US": "500",
                }
            ),
            teardown=_backend_faults_teardown,
        ),
        Scenario(
            "pipeline-burst",
            "in-flight verify pipeline: back-to-back paused bulk rounds "
            "with the completion pool gated, so two fused flushes must "
            "genuinely overlap (in-flight depth 2) while consensus keeps "
            "committing; every future resolves with the definitive "
            "verdict and traces stay byte-identical per seed with the "
            "completion pool in the loop.  Runs on the host-oracle "
            "device-runner seam so tier-1 never pays real XLA dispatches",
            target_height=6,
            max_time=180.0,
            actions=_pipeline_burst,
            setup=_backend_faults_setup(
                {
                    "COMETBFT_TPU_VERIFY_SCHED": "1",
                    "COMETBFT_TPU_SCHED_PIPELINE": "1",
                    "COMETBFT_TPU_SCHED_INFLIGHT": "2",
                    "COMETBFT_TPU_SCHED_FLUSH_US": "500",
                }
            ),
            teardown=_backend_faults_teardown,
        ),
        Scenario(
            "light-stampede",
            "light-client read stampede: scripted 1500-query proof "
            "bursts (tx/header/valset mixes) against a 512-slot proof "
            "queue mid-consensus, on the host-oracle tree-runner seam: "
            "coalescing must collapse each burst into a handful of tree "
            "builds, shed only proof queries (consensus-class verify "
            "shed stays 0 by construction), answer every admitted "
            "future, and keep the response digest byte-identical per "
            "seed.  Runs on the host-oracle seam so tier-1 never pays "
            "real XLA dispatches",
            target_height=6,
            max_time=180.0,
            actions=_light_stampede,
            setup=_light_stampede_setup(),
            teardown=_light_stampede_teardown,
        ),
        Scenario(
            "dial-storm",
            "inbound-connection storm against the encrypted transport "
            "plane: scripted 600-dial handshake waves against a 256-slot "
            "pool queue mid-consensus plus coalesced AEAD frame batches "
            "with a tampered frame, on the host-oracle ladder/AEAD "
            "runner seams: shed dials fall to the sync ladder (never a "
            "dropped connection), consensus-class verify shed stays 0 "
            "by construction, the tampered batch delivers exactly the "
            "prefix before the bad tag, traces stay byte-identical per "
            "seed, and a final wave proves COMETBFT_TPU_AEAD=0 / "
            "COMETBFT_TPU_HANDSHAKE=0 kill-switch byte parity.  Runs on "
            "the host-oracle seams so tier-1 never pays real XLA "
            "dispatches",
            target_height=6,
            max_time=180.0,
            actions=_dial_storm,
            setup=_dial_storm_setup(),
            teardown=_dial_storm_teardown,
        ),
        Scenario(
            "tx-flood",
            "sustained scripted signed-tx bursts (valid/forged/malformed/"
            "oversize/duplicate mixes) from every peer against a 32-slot "
            "ingest queue: batched admission must produce the same "
            "verdicts as the per-tx path, shed only to the sync path, "
            "keep consensus-class verify shed at 0 and agreement intact.  "
            "Runs on the host-oracle device-runner seam so tier-1 never "
            "pays real XLA dispatches",
            target_height=6,
            max_time=240.0,
            actions=_tx_flood,
            setup=_tx_flood_setup,
            teardown=_tx_flood_teardown,
            app_factory=_txflood_app,
            # recheck=True so every commit exercises the batched recheck
            # round trip; the small max_tx_bytes makes oversize txs cheap
            mempool_config=MempoolConfig(recheck=True, max_tx_bytes=2048),
        ),
        Scenario(
            "backend-brownout",
            "device crypto backend raises on every dispatch on f+1 nodes "
            "from t=5 to t=10; supervisor degrades to host verify, keeps "
            "agreement, re-promotes after restore.  Breaker threshold 1: "
            "the registry is cluster-shared in-process, so healthy nodes' "
            "successes would otherwise keep resetting the victims' "
            "consecutive-failure count",
            target_height=14,
            max_time=180.0,
            actions=_backend_brownout,
            setup=_backend_faults_setup(
                {"COMETBFT_TPU_BREAKER_THRESHOLD": "1"}
            ),
            teardown=_backend_faults_teardown,
        ),
        Scenario(
            "backend-wedge",
            "device dispatches hang past the watchdog deadline on f+1 "
            "nodes from t=4 to t=9; the watchdog abandons them and the "
            "chain degrades without blocking consensus",
            target_height=14,
            max_time=180.0,
            actions=_backend_wedge,
            setup=_backend_faults_setup(
                {
                    "COMETBFT_TPU_DISPATCH_TIMEOUT_MS": "80",
                    "COMETBFT_TPU_BREAKER_THRESHOLD": "1",
                }
            ),
            teardown=_backend_faults_teardown,
        ),
        Scenario(
            "validator-rotation",
            "a standby full node spawns at genesis, is voted into the "
            "validator set via a val: tx (validate_validator_updates "
            "path), then a genesis validator is voted out; the invariant "
            "checker authenticates every header's validator hashes "
            "against its own replay of the rotation and verifies commits "
            "against the height-correct set",
            n_spares=1,
            target_height=12,
            max_time=180.0,
            actions=_validator_rotation,
        ),
        Scenario(
            "fleet-churn",
            "the fleet acceptance script: rotation + churn in one run — a "
            "spare is voted in and statesync-joins as a fresh machine "
            "(snapshot offer -> chunk fetch over the faulty fabric -> "
            "catchup tail), the last genesis validator is voted out and "
            "leaves gracefully, another validator crash-restarts from its "
            "stores.  Scales with --validators: the nightly soak runs it "
            "at 100 validators, tier-1 at 8",
            n_spares=1,
            target_height=14,
            max_time=300.0,
            actions=_fleet_churn,
        ),
        Scenario(
            "statesync-storm",
            "two fresh nodes statesync-join through 25%-lossy links while "
            "a chunk-serving peer crashes mid-sync: chunk re-requests must "
            "back off exponentially (statesync/syncer.py retry seam), "
            "rotate to surviving peers, and both joins must complete with "
            "invariants green",
            n_spares=2,
            target_height=16,
            max_time=300.0,
            actions=_statesync_storm,
        ),
        Scenario(
            "dup-vote-flood",
            "waves of duplicate-vote evidence (fresh equivocations + "
            "byte-identical repeats + signature forgeries) flood every "
            "node's evidence pool against a scenario-shrunk 8-entry "
            "bound: dedup before signature work, verified overflow "
            "degrades to counted drops (never memory), forgeries are "
            "rejected, and real evidence still reaches committed blocks "
            "through the verifysched evidence class with consensus shed "
            "0.  Runs on the host-oracle device-runner seam",
            target_height=12,
            max_time=240.0,
            actions=_dup_vote_flood,
            setup=_evidence_setup(pool_max=8),
            teardown=_evidence_teardown,
        ),
        Scenario(
            "light-attack",
            "a real lunatic light-client attack (2 of 4 validators "
            "double-sign a conflicting app_hash at a committed height) "
            "must verify through the evidence seam and land in a block; a "
            "signature-broken variant must be rejected — both on the "
            "verifysched evidence class, consensus never shed.  Runs on "
            "the host-oracle device-runner seam",
            target_height=12,
            max_time=240.0,
            actions=_light_attack,
            setup=_evidence_setup(),
            teardown=_evidence_teardown,
        ),
        Scenario(
            "chip-death",
            "one chip of the 4-wide elastic mesh dies mid-dispatch at "
            "t=5 and stays dead: the failed dispatch (alone) re-runs on "
            "the shrunken 3-device mesh, the mesh_dev2 breaker opens and "
            "keeps the corpse out of membership (each elapsed backoff "
            "costs one failed one-bucket probe, never a production "
            "batch), and at t=8 a chip-watcher probe marks ordinal 1 "
            "down — PROACTIVE exclusion before any dispatch fails; since "
            "that chip actually dispatches fine, its next half-open "
            "probe re-admits it (mesh_restore) while the dead chip stays "
            "out.  The fleet keeps committing throughout, verdicts never "
            "change, traces byte-identical per seed.  Runs on the "
            "per-shard host-oracle runner seam",
            target_height=14,
            max_time=240.0,
            actions=_chip_death,
            setup=_mesh_setup(),
            teardown=_mesh_teardown,
        ),
        Scenario(
            "mesh-brownout",
            "a flapping chip: mesh ordinal 1 fails in deterministic "
            "bursts (fail 2 / pass 4) from t=4 to t=12 — the mesh must "
            "shrink on failing bursts, the mesh_dev1 breaker must cycle "
            "open -> half-open -> closed on the virtual-clock backoff "
            "with a pass-phase probe re-admitting the chip "
            "(mesh_restore), and the mesh settles back at full width "
            "after the brownout.  Runs on the per-shard host-oracle "
            "runner seam",
            target_height=14,
            max_time=240.0,
            actions=_mesh_brownout,
            setup=_mesh_setup(),
            teardown=_mesh_teardown,
        ),
        Scenario(
            "byzantine-voter",
            "one LIVE validator double-signs every non-nil prevote and "
            "precommit from t=2 to t=8 (a second vote for a fabricated "
            "block id, signed with its real key, through the production "
            "gossip fabric — no crafted evidence): honest nodes must "
            "detect the equivocation in their vote sets, convert it to "
            "DuplicateVoteEvidence at finalize, commit it, and hold "
            "agreement + validator-set invariants, byte-deterministic "
            "per seed",
            target_height=12,
            max_time=240.0,
            actions=_byzantine_voter,
        ),
        Scenario(
            "combined-storm",
            "the composition layer's proof: minority partition + device "
            "backend brownout on f+1 nodes + scripted bulk verify bursts "
            "+ a mesh blackout (3 of 4 ordinals die t=5..10.5, so the "
            "mesh collapses below width 2 and the single-chip brownout "
            "REALLY fires underneath it) composed in ONE script "
            "(compose()).  Agreement must hold, only bulk-class verify "
            "work may shed, the full ladder mesh(4)->...->xla->host must "
            "degrade and every layer must re-promote after the storm",
            target_height=14,
            max_time=300.0,
            actions=compose(
                _partition_minority,
                _backend_brownout,
                _gossip_burst,
                _mesh_blackout,
            ),
            setup=_mesh_setup(
                {
                    "COMETBFT_TPU_VERIFY_SCHED": "1",
                    "COMETBFT_TPU_SCHED_QUEUE": "48",
                    "COMETBFT_TPU_SCHED_FLUSH_US": "500",
                    # failed probes during the blackout double each dead
                    # chip's backoff; cap it low so re-admission probes
                    # recur fast enough to restore full width before the
                    # run ends (deterministic: virtual clock)
                    "COMETBFT_TPU_BREAKER_BACKOFF_MAX_MS": "2000",
                }
            ),
            teardown=_mesh_teardown,
        ),
        Scenario(
            "disk-full",
            "node1's disk fills at t=5 (injected ENOSPC): the next WAL "
            "append fail-stops it with a typed StorageFatal BEFORE it "
            "can vote on unpersisted state (journaled disk_fatal with "
            "surface/errno attribution), its blackbox degrades to "
            "counted drops, and the survivors keep agreement and reach "
            "the target without it — byte-deterministic per seed",
            target_height=10,
            max_time=180.0,
            actions=_disk_full,
            setup=_disk_setup,
            teardown=_disk_teardown,
        ),
        Scenario(
            "disk-brownout",
            "transient EIO bursts on the degradable blackbox surface "
            "(t=4..10): bursts shorter than the retry budget recover "
            "via bounded exponential backoff with zero drops; one long "
            "burst degrades to counted drops + a disk_fault anomaly.  "
            "No node halts, consensus never notices, agreement holds",
            target_height=12,
            max_time=180.0,
            actions=_disk_brownout,
            setup=_disk_setup,
            teardown=_disk_teardown,
        ),
        Scenario(
            "torn-wal-restart",
            "node1 is killed mid-frame at t=6 (its WAL head cut halfway "
            "through the final frame, the torn tail a power cut "
            "leaves); on restart at t=20 the boot-time scrub truncates "
            "to the last CRC-valid frame (wal_repair journaled, dropped "
            "bytes counted), the node replays to the repaired tail and "
            "rejoins — byte-deterministic per seed",
            target_height=12,
            max_time=240.0,
            actions=_torn_wal_restart,
            setup=_disk_setup,
            teardown=_disk_teardown,
        ),
        Scenario(
            "backend-flap",
            "device backend fails in bursts of 4 with 2 clean dispatches "
            "between (t=3..14): breaker cycles open/half-open/closed on "
            "the virtual-clock backoff schedule.  Bisection is disabled — "
            "a flapping backend would let the bisector spuriously 'solve' "
            "each burst and mask the breaker cycling under test",
            target_height=12,
            max_time=240.0,
            actions=_backend_flap,
            setup=_backend_faults_setup(
                {"COMETBFT_TPU_SUPERVISOR_BISECT": "0"}
            ),
            teardown=_backend_faults_teardown,
        ),
        Scenario(
            "blocksync-storm",
            "a late joiner blocksyncs 40+ heights through lossy "
            "high-latency links while the helper set misbehaves: node1 "
            "goes mute mid-window (adaptive RTT timeouts fire and its "
            "requests re-assign), node2 serves forged block bodies "
            "(validate_block redo + exponential ban, half-open probe "
            "re-admits it once clean), and the joiner crash-restarts "
            "mid-catchup and resumes from its surviving stores.  Commit "
            "verification rides the fused-prefetch dispatch windows; "
            "the whole dance is byte-deterministic per seed",
            target_height=75,
            max_time=420.0,
            n_spares=1,
            actions=_blocksync_storm,
            setup=_backend_faults_setup(
                {
                    "COMETBFT_TPU_SIGCACHE": "1",
                    # short ban base + stall window + tight timeout mult
                    # so the exponential ban -> half-open probe ->
                    # re-admission cycle and a stall-switch all land
                    # INSIDE the catchup window (at mult 4 a dropped
                    # frontier response costs ~5 s — two in a row and the
                    # frontier never reaches the forged heights)
                    "COMETBFT_TPU_BSYNC_BAN_BASE": "2.0",
                    "COMETBFT_TPU_BSYNC_STALL_SECS": "3.0",
                    "COMETBFT_TPU_BSYNC_TIMEOUT_MULT": "2.0",
                }
            ),
            teardown=_backend_faults_teardown,
        ),
        Scenario(
            "wan-catchup",
            "3-region geo topology (intra 2-10ms, inter 60-180ms "
            "one-way, bandwidth-shaped links): a joiner in region 2 "
            "blocksyncs 40+ heights cross-region while consensus "
            "continues; mid-sync its whole region is geo-partitioned "
            "off — the 5-of-7 majority keeps committing, the joiner "
            "drains its frozen intra-region helpers and stalls — and "
            "after heal it catches the head and promotes.  Per-round "
            "quorum timelines land in the flight-recorder rounds report",
            n_vals=7,
            target_height=60,
            max_time=420.0,
            n_spares=1,
            actions=_wan_catchup,
            setup=_backend_faults_setup(
                {
                    "COMETBFT_TPU_SIGCACHE": "1",
                    "COMETBFT_TPU_BSYNC_BAN_BASE": "2.0",
                }
            ),
            teardown=_backend_faults_teardown,
        ),
    ]
}


def run_scenario(
    name: str,
    seed: int,
    root=None,
    n_vals: Optional[int] = None,
    target_height: Optional[int] = None,
    max_time: Optional[float] = None,
    raise_on_violation: bool = False,
    keep_cluster: bool = False,
) -> ScenarioResult:
    """Build a cluster, script the scenario's actions onto its virtual
    clock, and drive it to the target height (or the time budget)."""
    scenario = SCENARIOS.get(name) or SCENARIOS[name.replace("_", "-")]
    name = scenario.name
    # overrides flow into the scenario the action generators see, so e.g.
    # _partition_minority picks its victims from the real cluster size
    scenario = replace(
        scenario,
        n_vals=n_vals or scenario.n_vals,
        target_height=target_height or scenario.target_height,
        max_time=max_time or scenario.max_time,
    )
    created_root = root is None
    if created_root:
        root = Path(tempfile.mkdtemp(prefix=f"sim-{name}-{seed}-"))
    cluster = SimCluster(
        scenario.n_vals,
        root,
        seed=seed,
        raise_on_violation=raise_on_violation,
        app_factory=scenario.app_factory,
        mempool_config=scenario.mempool_config,
        n_spares=scenario.n_spares,
    )
    for src_dst, overrides in scenario.link_overrides.items():
        cluster.net.set_link(*src_dst, **overrides)
    for action in scenario.actions(scenario):
        cluster.clock.call_at(
            action.at,
            lambda a=action: a.fn(cluster),
            label=f"scenario {action.name}",
        )
    backend_stats: dict = {}
    sched_stats: dict = {}
    ingest_counters: dict = {}
    evidence_counters: dict = {}
    spans_capture: dict = {}
    blackbox_capture: dict = {}
    postmortem_capture: list = []
    # per-run evidence counters: the process-wide stats must not bleed one
    # run's flood into the next run's ScenarioResult
    from cometbft_tpu.evidence import stats as _evstats

    _evstats.reset()
    # flight recorder on the virtual clock: reset per run (span ids and
    # therefore anomaly-dump bytes become a pure function of the seed),
    # dumps land under the run root unless the caller pinned a dir
    from cometbft_tpu.libs import tracing as _tracing

    _tracer = _tracing.get_tracer()
    _saved_trace_dir = os.environ.get("COMETBFT_TPU_TRACE_DIR")
    _trace_dir = Path(root) / "flight"
    os.environ["COMETBFT_TPU_TRACE_DIR"] = str(_trace_dir)
    _tracer.reset()
    _tracer.set_clock(cluster.clock.now)
    # the dispatch ordinal in verify.dispatch spans comes from the
    # process-wide dispatch counter — zero it so dump bytes are a pure
    # function of the seed (tests only ever use dispatch-count DELTAS)
    from cometbft_tpu.ops import dispatch_stats as _dstats

    _dstats.reset()
    # journal HEALTH records snapshot the sched/ingest counters, so those
    # must be per-run too or the black-box bytes of two same-seed runs in
    # one process would differ (the backend scenarios already reset them
    # in setup; plain scenarios need the same hygiene)
    from cometbft_tpu.txingest import stats as _istats
    from cometbft_tpu.verifysched import stats as _sstats

    _sstats.reset()
    _istats.reset()
    # proof-plane counters are per-run too: every scenario's commits hash
    # through the plane, and a soak row must reflect ITS run alone
    from cometbft_tpu.proofserve import stats as _pstats

    _pstats.reset()
    proofs_counters: dict = {}
    # transport-plane counters are per-run too (dial-storm): a soak row
    # must reflect ITS run's frames and handshakes alone
    from cometbft_tpu.p2p import transport_stats as _tpstats

    _tpstats.reset()
    transport_counters: dict = {}
    # blocksync catchup counters are per-run too (blocksync-storm /
    # wan-catchup): a soak row must reflect ITS run's catchup alone
    from cometbft_tpu.blocksync import stats as _bstats

    _bstats.reset()
    bsync_counters: dict = {}
    # disk-fault counters are per-run too: every scenario writes WALs
    # through the guard, and a soak row must reflect ITS run's IO alone
    from cometbft_tpu.libs import storage_stats as _ss

    _ss.reset()
    storage_capture: dict = {}
    fail_stopped_capture: list = []
    try:
        if scenario.setup is not None:
            scenario.setup(cluster)
        reached = cluster.run(
            until_height=scenario.target_height, max_time=scenario.max_time
        )
        if scenario.setup is not None:
            # capture BEFORE teardown resets the registry
            from cometbft_tpu.crypto import backend_health

            snap = backend_health.snapshot()
            backend_stats = {
                "demotions": snap["demotions"],
                "repromotions": snap["repromotions"],
                "watchdog_fires": snap["watchdog_fires"],
                "fallback_signatures": snap["fallback_signatures"],
                "quarantined": snap["quarantined"],
                "breaker_opens": sum(
                    b["opens"] for b in snap["breakers"].values()
                ),
                "breakers": {
                    n: b["state"] for n, b in snap["breakers"].items()
                },
            }
            # elastic-mesh shape of the run (chip-death / mesh-brownout /
            # combined-storm): width at end of run + shrink/restore
            # counts — only when the mesh actually ran, so non-mesh
            # backend rows don't grow dead columns
            msnap = _dstats.snapshot()
            if msnap["mesh_width"] or msnap["mesh_shrinks"]:
                backend_stats["mesh_width"] = msnap["mesh_width"]
                backend_stats["mesh_shrinks"] = msnap["mesh_shrinks"]
                backend_stats["mesh_restores"] = msnap["mesh_restores"]
            # only when the scenario ran with the scheduler enabled —
            # backend-* scenarios pin it off, and an all-zero sched block
            # in their soak rows would read as "scheduler ran, idle"
            if os.environ.get("COMETBFT_TPU_VERIFY_SCHED", "1") != "0":
                from cometbft_tpu.verifysched import stats as sstats

                sched_stats = sstats.snapshot()
            # tx-ingestion counters (tx-flood): only when the pipeline
            # actually ran — an all-zero block would read as "ran, idle"
            from cometbft_tpu.txingest import stats as istats

            isnap = istats.snapshot()
            if isnap["enqueued"] or isnap["shed_to_sync"] or isnap["flushes"]:
                ingest_counters = isnap
        # proof-plane counters (light-stampede): only when the proof
        # server / tree plane actually saw traffic this run
        psnap = _pstats.snapshot()
        if psnap["queries_total"] or psnap["trees_device"] or psnap[
            "trees_host"
        ]:
            proofs_counters = psnap
        # transport-plane counters (dial-storm): only when the plane or
        # the handshake pool actually saw traffic this run
        tpsnap = _tpstats.snapshot()
        if tpsnap["frames_total"] or tpsnap["handshakes_total"]:
            transport_counters = tpsnap
        # blocksync catchup counters (blocksync-storm / wan-catchup):
        # only when a joiner actually catch-up-synced this run
        bsnap = _bstats.snapshot()
        if bsnap["requests"] or bsnap["blocks_received"]:
            bsync_counters = bsnap
        # evidence-pool counters (dup-vote-flood / light-attack): only
        # when the pool actually saw traffic this run
        from cometbft_tpu.evidence import stats as evstats

        esnap = evstats.snapshot()
        if esnap["added"] or esnap["dedup"] or esnap["rejected"]:
            evidence_counters = esnap
        # flight-recorder capture — dumps hashed NOW, before the run root
        # (and the dump files under it) are deleted below
        tsnap = _tracer.snapshot()
        dumps = []
        for dump_name in tsnap["dumps"]:
            try:
                blob = (_trace_dir / dump_name).read_bytes()
            except OSError:
                continue
            import hashlib as _hashlib

            dumps.append(
                {
                    "file": dump_name,
                    "bytes": len(blob),
                    "sha256": _hashlib.sha256(blob).hexdigest(),
                }
            )
        # black-box capture — journal counters + crashed nodes' restart
        # postmortems, read NOW, before the run root (and the journal
        # files under it) are deleted below
        blackbox_capture = (
            cluster.blackbox_stats() if cluster.blackbox else {}
        )
        postmortem_capture = list(cluster.postmortems)
        # disk-fault capture: attached only when something actually went
        # wrong on the storage plane (faults, retries, drops, repairs) —
        # clean rows must not grow dead all-zero columns
        if _ss.faulted():
            storage_capture = _ss.snapshot()
        fail_stopped_capture = sorted(cluster.fail_stopped)
        spans_capture = {
            "recorded": tsnap["spans_recorded"],
            "dropped": tsnap["spans_dropped"],
            "anomalies": tsnap["anomalies"],
            "stages": _tracer.stage_summary(),
            "dumps": dumps,
            # merged cross-node round timelines (the whole ring window):
            # per-(height, round) causal trees rooted at the originating
            # proposal, per-step p50/p99, quorum-arrival percentiles and
            # the commit-to-proposal trace linkage counts.  A pure
            # function of the seed — determinism tests byte-compare its
            # sort_keys JSON across same-seed runs.
            "rounds": _tracer.rounds_report(),
        }
    finally:
        _tracer.set_clock(None)
        _tracer.reset()
        if _saved_trace_dir is None:
            os.environ.pop("COMETBFT_TPU_TRACE_DIR", None)
        else:
            os.environ["COMETBFT_TPU_TRACE_DIR"] = _saved_trace_dir
        if scenario.teardown is not None:
            scenario.teardown(cluster)
        cluster.stop()
        if created_root and not keep_cluster:
            shutil.rmtree(root, ignore_errors=True)
    return ScenarioResult(
        scenario=name,
        seed=seed,
        n_vals=scenario.n_vals,
        target_height=scenario.target_height,
        reached=reached,
        heights=cluster.heights(),
        virtual_time=cluster.clock.now(),
        events=cluster.events_fired,
        commits_verified=cluster.checker.commits_verified,
        violations=[str(v) for v in cluster.checker.violations],
        trace=cluster.trace,
        cluster=cluster if keep_cluster else None,
        backend=backend_stats,
        sched=sched_stats,
        ingest=ingest_counters,
        evidence=evidence_counters,
        rotations=cluster.checker.rotations_seen,
        spans=spans_capture,
        blackbox=blackbox_capture,
        postmortems=postmortem_capture,
        storage=storage_capture,
        fail_stopped=fail_stopped_capture,
        proofs=proofs_counters,
        transport=transport_counters,
        bsync=bsync_counters,
    )
