"""Safety invariants checked after every simulated event.

Checks are incremental — each (node, height) pair is verified exactly once
when the node first stores that height — so running them after every
delivered message costs O(new commits), not O(history):

  * **agreement** — no two nodes ever commit different blocks at one height
    (the first committed hash per height is the canonical one).
  * **validity** — every stored seen-commit carries +2/3 valid signatures
    from the genesis validator set, checked through the production
    ``verify_commit`` path (and therefore the BatchVerifier seam).
  * **wal-replay** — the fsync'd ``#ENDHEIGHT h`` marker exists for every
    height the node committed, so a crash after this point replays
    deterministically; on restart the rebuilt state must agree with the
    stores it was rebuilt from.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cometbft_tpu.types.validation import CommitVerificationError, verify_commit


class InvariantViolation(AssertionError):
    """Raised (or recorded) when a safety property breaks."""

    def __init__(self, name: str, detail: str, time: float = 0.0):
        super().__init__(f"[{name}] at t={time:.6f}: {detail}")
        self.invariant = name
        self.detail = detail
        self.time = time


@dataclass
class Violation:
    invariant: str
    detail: str
    time: float


class InvariantChecker:
    def __init__(self, chain_id: str, validators, check_wal: bool = True):
        self.chain_id = chain_id
        self.validators = validators  # genesis ValidatorSet (no updates in sim)
        self.check_wal = check_wal
        self.canonical: dict[int, bytes] = {}  # height -> first committed hash
        self._checked: dict[int, int] = {}  # node index -> last verified height
        # incremental WAL readers: node -> (byte offset, end-heights seen);
        # keeps the per-event WAL check O(new bytes), not O(log) per height
        self._wal_tail: dict[int, tuple[int, set]] = {}
        self.violations: list[Violation] = []
        self.commits_verified = 0

    # -- driver hooks ------------------------------------------------------

    def on_event(self, cluster) -> list[str]:
        """Verify every height newly stored since the last call; returns
        deterministic trace lines for fresh commits."""
        lines: list[str] = []
        for node in cluster.live_nodes():
            i = node.index
            top = node.block_store.height()
            for h in range(self._checked.get(i, 0) + 1, top + 1):
                lines.extend(self._check_height(cluster, node, h))
            self._checked[i] = max(self._checked.get(i, 0), top)
        return lines

    def on_restart(self, cluster, index: int) -> None:
        """WAL/store consistency after a crash-restart rebuild."""
        node = cluster.nodes[index]
        state = node.state_store.load()
        store_h = node.block_store.height()
        state_h = state.last_block_height if state is not None else -1
        if state_h != store_h:
            self._violate(
                cluster,
                "wal-replay",
                f"node{index} restarted with state height {state_h} != "
                f"block store height {store_h}",
            )
        # the consensus state must resume at the next height
        if node.cs.rs.height != store_h + 1 and store_h > 0:
            self._violate(
                cluster,
                "wal-replay",
                f"node{index} consensus resumed at {node.cs.rs.height}, "
                f"store at {store_h}",
            )
        # re-verification of already-committed heights must still pass
        self._checked[index] = 0

    # -- checks ------------------------------------------------------------

    def _check_height(self, cluster, node, h: int) -> list[str]:
        meta = node.block_store.load_block_meta(h)
        if meta is None:
            self._violate(
                cluster, "agreement", f"node{node.index} height {h} has no meta"
            )
            return []
        block_hash = meta.block_id.hash
        lines = [
            "%.6f commit node%d h=%d hash=%s"
            % (cluster.clock.now(), node.index, h, block_hash.hex()[:16])
        ]

        canonical = self.canonical.setdefault(h, block_hash)
        if canonical != block_hash:
            self._violate(
                cluster,
                "agreement",
                f"fork at height {h}: node{node.index} committed "
                f"{block_hash.hex()[:16]}, canonical is {canonical.hex()[:16]}",
            )

        commit = node.block_store.load_seen_commit(h)
        if commit is None:
            self._violate(
                cluster,
                "validity",
                f"node{node.index} stored height {h} without a seen commit",
            )
        else:
            try:
                verify_commit(
                    self.chain_id,
                    self.validators,
                    meta.block_id,
                    h,
                    commit,
                    backend="cpu",
                )
                self.commits_verified += 1
            except Exception as e:  # noqa: BLE001 — any rejection is a violation
                self._violate(
                    cluster,
                    "validity",
                    f"node{node.index} height {h} commit rejected: {e!r}",
                )

        if self.check_wal and node.cs.wal is not None:
            if h not in self._wal_ends(node):
                self._violate(
                    cluster,
                    "wal-replay",
                    f"node{node.index} committed height {h} without an "
                    f"#ENDHEIGHT marker in its WAL",
                )
        return lines

    def _wal_ends(self, node) -> set:
        """End-height markers in the node's WAL, read incrementally (only
        the bytes appended since the previous check are parsed)."""
        import os as _os

        offset, ends = self._wal_tail.get(node.index, (0, set()))
        wal = node.cs.wal
        try:
            size = _os.path.getsize(wal.path)
        except OSError:
            size = 0
        if size < offset:  # truncated (crash dropped an unflushed tail)
            offset, ends = 0, set()
        fresh, offset = wal.scan_end_heights(offset)
        ends |= fresh
        self._wal_tail[node.index] = (offset, ends)
        return ends

    def _violate(self, cluster, name: str, detail: str) -> None:
        v = Violation(name, detail, cluster.clock.now())
        self.violations.append(v)
        if cluster.raise_on_violation:
            raise InvariantViolation(name, detail, cluster.clock.now())
