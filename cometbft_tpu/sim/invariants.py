"""Safety invariants checked after every simulated event.

Checks are incremental — each (node, height) pair is verified exactly once
when the node first stores that height — so running them after every
delivered message costs O(new commits), not O(history):

  * **agreement** — no two nodes ever commit different blocks at one height
    (the first committed hash per height is the canonical one).
  * **validity** — every stored seen-commit carries +2/3 valid signatures
    from the *height-correct* validator set, checked through the production
    ``verify_commit`` path (and therefore the BatchVerifier seam).
  * **validator-set** — the checker replays validator-set evolution itself
    (genesis set + the ``validator_updates`` each committed block's
    finalize response carries, through the production
    ``validate_validator_updates`` path) and requires every header's
    ``validators_hash`` / ``next_validators_hash`` to match the tracked
    sets.  Since the header hash is what the commit signs, this chains
    custody light-client-style: a rotation can only be accepted if the
    previous height's (+2/3-signed) header committed to it.
  * **wal-replay** — the fsync'd ``#ENDHEIGHT h`` marker exists for every
    height the node committed *through consensus*, so a crash after this
    point replays deterministically; on restart the rebuilt state must
    agree with the stores it was rebuilt from.  Heights below a node's
    block-store base (obtained via statesync, not consensus) are exempt.

Limitation: consensus-param updates are not replayed (no sim scenario
issues them); ``validate_validator_updates`` runs against genesis params.
"""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.types.validation import verify_commit


class InvariantViolation(AssertionError):
    """Raised (or recorded) when a safety property breaks."""

    def __init__(self, name: str, detail: str, time: float = 0.0):
        super().__init__(f"[{name}] at t={time:.6f}: {detail}")
        self.invariant = name
        self.detail = detail
        self.time = time


@dataclass
class Violation:
    invariant: str
    detail: str
    time: float


class InvariantChecker:
    def __init__(self, chain_id: str, genesis_state, check_wal: bool = True):
        self.chain_id = chain_id
        self.consensus_params = genesis_state.consensus_params
        initial = genesis_state.initial_height
        # canonical validator set per height, advanced as blocks commit:
        # vals[h+2] = update(vals[h+1], updates-from-block-h), exactly the
        # state/execution.go updateState schedule
        self.val_sets = {
            initial: genesis_state.validators.copy(),
            initial + 1: genesis_state.next_validators.copy(),
        }
        self.val_updates: dict[int, list] = {}  # height -> canonical updates
        self.check_wal = check_wal
        self.canonical: dict[int, bytes] = {}  # height -> first committed hash
        self._checked: dict[int, int] = {}  # node index -> last verified height
        # incremental WAL readers: node -> (byte offset, end-heights seen);
        # keeps the per-event WAL check O(new bytes), not O(log) per height
        self._wal_tail: dict[int, tuple[int, set]] = {}
        # node index -> heights at/below this arrived via statesync (no WAL)
        self._wal_floor: dict[int, int] = {}
        self.violations: list[Violation] = []
        self.commits_verified = 0
        self.rotations_seen = 0  # heights whose canonical updates were non-empty

    # -- driver hooks ------------------------------------------------------

    def on_event(self, cluster) -> list[str]:
        """Verify every height newly stored since the last call; returns
        deterministic trace lines for fresh commits."""
        lines: list[str] = []
        for node in cluster.live_nodes():
            i = node.index
            top = node.block_store.height()
            for h in range(self._checked.get(i, 0) + 1, top + 1):
                lines.extend(self._check_height(cluster, node, h))
            self._checked[i] = max(self._checked.get(i, 0), top)
        return lines

    def on_restart(self, cluster, index: int) -> None:
        """WAL/store consistency after a crash-restart rebuild."""
        node = cluster.nodes[index]
        state = node.state_store.load()
        store_h = node.block_store.height()
        state_h = state.last_block_height if state is not None else -1
        # a statesync joiner restarted before its first post-join commit
        # has state at the snapshot height but an empty block store; every
        # other node must agree exactly
        floor = self._wal_floor.get(index, 0)
        expect_h = max(store_h, floor)
        if state_h != expect_h:
            self._violate(
                cluster,
                "wal-replay",
                f"node{index} restarted with state height {state_h} != "
                f"block store height {expect_h}",
            )
        # the consensus state must resume at the next height
        if node.cs.rs.height != expect_h + 1:
            self._violate(
                cluster,
                "wal-replay",
                f"node{index} consensus resumed at {node.cs.rs.height}, "
                f"store at {expect_h}",
            )
        # re-verification of already-committed heights must still pass —
        # from the node's base, not genesis (a statesync joiner never held
        # the pre-snapshot blocks)
        base = node.block_store.base()
        self._checked[index] = max(0, base - 1, floor)

    def on_join(self, cluster, index: int, base_height: int) -> None:
        """A node bootstrapped via statesync at ``base_height``: its first
        consensus-made commit is base_height+1, and nothing below it exists
        in its stores or WAL."""
        self._checked[index] = base_height
        self._wal_floor[index] = base_height
        self._wal_tail.pop(index, None)

    # -- checks ------------------------------------------------------------

    def _vals_at(self, h: int):
        return self.val_sets.get(h)

    def _advance_val_sets(self, cluster, node, h: int) -> None:
        """Record height h's canonical validator updates (first node to
        commit h wins) and derive the set for h+2, mirroring
        state/execution updateState.  The derived set is authenticated one
        height later, when header h+1's next_validators_hash is checked."""
        if h in self.val_updates or (h + 2) in self.val_sets:
            return
        raw = node.state_store.load_finalize_block_response(h)
        if raw is None:
            return  # another node will supply it when it commits h
        from cometbft_tpu.state.execution import (
            fbr_from_json,
            validate_validator_updates,
        )

        base = self._vals_at(h + 1)
        if base is None:
            return
        res = fbr_from_json(raw)
        try:
            updates = validate_validator_updates(
                res.validator_updates, self.consensus_params
            )
        except Exception as e:  # noqa: BLE001 — an invalid committed update
            self._violate(
                cluster,
                "validator-set",
                f"height {h} committed invalid validator updates: {e!r}",
            )
            return
        self.val_updates[h] = updates
        nxt = base.copy()
        if updates:
            nxt.update_with_change_set(updates)
            self.rotations_seen += 1
        nxt.increment_proposer_priority(1)
        self.val_sets[h + 2] = nxt

    def _check_height(self, cluster, node, h: int) -> list[str]:
        meta = node.block_store.load_block_meta(h)
        if meta is None:
            self._violate(
                cluster, "agreement", f"node{node.index} height {h} has no meta"
            )
            return []
        block_hash = meta.block_id.hash
        lines = [
            "%.6f commit node%d h=%d hash=%s"
            % (cluster.clock.now(), node.index, h, block_hash.hex()[:16])
        ]

        canonical = self.canonical.setdefault(h, block_hash)
        if canonical != block_hash:
            self._violate(
                cluster,
                "agreement",
                f"fork at height {h}: node{node.index} committed "
                f"{block_hash.hex()[:16]}, canonical is {canonical.hex()[:16]}",
            )

        vals = self._vals_at(h)
        if vals is None:
            self._violate(
                cluster,
                "validator-set",
                f"node{node.index} committed height {h} but the canonical "
                f"validator set for it is unknown (tracking hole)",
            )
        else:
            if meta.header.validators_hash != vals.hash():
                self._violate(
                    cluster,
                    "validator-set",
                    f"node{node.index} height {h} header validators_hash "
                    f"does not match the tracked set",
                )
            nxt = self._vals_at(h + 1)
            if nxt is not None and meta.header.next_validators_hash != nxt.hash():
                self._violate(
                    cluster,
                    "validator-set",
                    f"node{node.index} height {h} header "
                    f"next_validators_hash does not match the tracked set",
                )

        commit = node.block_store.load_seen_commit(h)
        if commit is None:
            self._violate(
                cluster,
                "validity",
                f"node{node.index} stored height {h} without a seen commit",
            )
        elif vals is not None:
            try:
                verify_commit(
                    self.chain_id,
                    vals,
                    meta.block_id,
                    h,
                    commit,
                    backend="cpu",
                )
                self.commits_verified += 1
            except Exception as e:  # noqa: BLE001 — any rejection is a violation
                self._violate(
                    cluster,
                    "validity",
                    f"node{node.index} height {h} commit rejected: {e!r}",
                )

        self._advance_val_sets(cluster, node, h)

        if (
            self.check_wal
            and node.cs.wal is not None
            and h > self._wal_floor.get(node.index, 0)
        ):
            if h not in self._wal_ends(node):
                self._violate(
                    cluster,
                    "wal-replay",
                    f"node{node.index} committed height {h} without an "
                    f"#ENDHEIGHT marker in its WAL",
                )
        return lines

    def _wal_ends(self, node) -> set:
        """End-height markers in the node's WAL, read incrementally (only
        the bytes appended since the previous check are parsed)."""
        import os as _os

        offset, ends = self._wal_tail.get(node.index, (0, set()))
        wal = node.cs.wal
        try:
            size = _os.path.getsize(wal.path)
        except OSError:
            size = 0
        if size < offset:  # truncated (crash dropped an unflushed tail)
            offset, ends = 0, set()
        fresh, offset = wal.scan_end_heights(offset)
        ends |= fresh
        self._wal_tail[node.index] = (offset, ends)
        return ends

    def _violate(self, cluster, name: str, detail: str) -> None:
        v = Violation(name, detail, cluster.clock.now())
        self.violations.append(v)
        if cluster.raise_on_violation:
            raise InvariantViolation(name, detail, cluster.clock.now())
