"""Deterministic simulation & fault-injection harness.

FoundationDB-style discipline: an N-validator cluster runs entirely in one
thread on *virtual* time.  Every source of scheduling nondeterminism — link
delays, drops, duplicates, reordering, partitions, crashes, consensus
timeouts — flows through one seeded ``random.Random`` and one event heap
(``VirtualClock``), so a failing run reproduces byte-identically from its
seed.  Invariant checkers (agreement / validity / WAL replay) run after
every delivered event.

Entry points:
  * ``SimCluster``   — assemble and drive a cluster programmatically
  * ``run_scenario`` — named fault scripts (``cometbft-tpu sim`` CLI)
"""

from cometbft_tpu.sim.clock import SimTicker, VirtualClock
from cometbft_tpu.sim.cluster import SimCluster
from cometbft_tpu.sim.invariants import InvariantChecker, InvariantViolation
from cometbft_tpu.sim.network import LinkConfig, SimNetwork
from cometbft_tpu.sim.scenarios import SCENARIOS, run_scenario

__all__ = [
    "SCENARIOS",
    "InvariantChecker",
    "InvariantViolation",
    "LinkConfig",
    "SimCluster",
    "SimNetwork",
    "SimTicker",
    "VirtualClock",
    "run_scenario",
]
