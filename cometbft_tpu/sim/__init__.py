"""Deterministic simulation & fault-injection harness.

FoundationDB-style discipline: an N-validator cluster runs entirely in one
thread on *virtual* time.  Every source of scheduling nondeterminism — link
delays, drops, duplicates, reordering, partitions, crashes, churn
(statesync joins, graceful leaves), validator-set rotation, consensus
timeouts — flows through one seeded ``random.Random`` and one event heap
(``VirtualClock``), so a failing run reproduces byte-identically from its
seed.  Invariant checkers (agreement / validity / validator-set / WAL
replay) run after every delivered event, verifying commits against the
height-correct validator set across rotations.

Entry points:
  * ``SimCluster``   — assemble and drive a cluster programmatically
  * ``run_scenario`` — named fault scripts (``cometbft-tpu sim`` CLI)
  * ``compose``      — merge fault scripts into combined-fault scenarios
"""

from cometbft_tpu.sim.clock import SimTicker, VirtualClock
from cometbft_tpu.sim.cluster import SimCluster
from cometbft_tpu.sim.invariants import InvariantChecker, InvariantViolation
from cometbft_tpu.sim.network import LinkConfig, SimNetwork
from cometbft_tpu.sim.scenarios import SCENARIOS, compose, run_scenario

__all__ = [
    "SCENARIOS",
    "InvariantChecker",
    "InvariantViolation",
    "LinkConfig",
    "SimCluster",
    "SimNetwork",
    "SimTicker",
    "VirtualClock",
    "compose",
    "run_scenario",
]
