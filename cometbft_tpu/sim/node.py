"""Validator node assembly shared by the simulator and the test harness.

This is ``tests/net_harness.py``'s node wiring promoted into the package:
kvstore app, ``BlockStore``/``StateStore`` over one KV db, ``Handshaker``
replay on boot, WAL, FilePV — everything a real node has except sockets.
The sim passes ``clock``/``ticker_factory``/``threaded=False`` to run the
consensus state machine on virtual time; the thread-based loopback harness
passes nothing and gets wall-clock behaviour.

Crash-restart support falls out of the assembly being a function of
``(db, home)``: keep the ``MemKV`` and the home dir (WAL + privval files),
call ``build_node`` again, and the ``Handshaker`` + WAL catchup replay
rebuild the consensus state the dead process was in.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Callable, Optional

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config.config import ConsensusConfig, MempoolConfig
from cometbft_tpu.consensus.replay import Handshaker
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import WAL
from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.evidence.pool import EvidencePool
from cometbft_tpu.mempool.clist_mempool import CListMempool
from cometbft_tpu.privval.file_pv import FilePV
from cometbft_tpu.proxy.multi_app_conn import AppConns, local_client_creator
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import state_from_genesis
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.store.block_store import BlockStore
from cometbft_tpu.store.kv import MemKV
from cometbft_tpu.types.basic import Timestamp
from cometbft_tpu.types.events import EventBus
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator


@dataclass
class NodeHandle:
    """Everything a driver needs to poke at one assembled validator."""

    index: int
    cs: ConsensusState
    app: KVStoreApplication
    app_conns: AppConns
    mempool: CListMempool
    block_store: BlockStore
    state_store: StateStore
    event_bus: EventBus
    priv_val: FilePV
    evidence_pool: EvidencePool


def sim_consensus_config(**overrides) -> ConsensusConfig:
    """Round-trip friendly virtual-time timeouts (virtual seconds are free,
    so these only shape the event schedule, not the wall-clock runtime)."""
    cfg = ConsensusConfig(
        timeout_propose_ms=1000,
        timeout_propose_delta_ms=500,
        timeout_vote_ms=500,
        timeout_vote_delta_ms=250,
        # ~1 height per virtual second: keeps scripted fault times (t=3.0,
        # heal at t=25.0, ...) meaningful in heights, like production pacing
        timeout_commit_ms=1000,
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def make_genesis(
    n_vals: int,
    chain_id: str,
    seed_tag: bytes = b"netval%d",
    n_nodes: Optional[int] = None,
) -> tuple[list[Ed25519PrivKey], GenesisDoc]:
    """Deterministic validator keys + a genesis doc naming the first
    ``n_vals`` of them.  ``n_nodes`` (>= n_vals) mints extra keys for
    standby full nodes — churn/rotation scenarios later join them via
    statesync and vote them in with ``val:`` txs."""
    privs = [
        Ed25519PrivKey.from_seed(hashlib.sha256(seed_tag % i).digest())
        for i in range(max(n_vals, n_nodes or 0))
    ]
    gdoc = GenesisDoc(
        chain_id=chain_id,
        genesis_time=Timestamp(0, 0),
        validators=[
            GenesisValidator(p.pub_key(), 10) for p in privs[:n_vals]
        ],
    )
    return privs, gdoc


class HandleProvider:
    """Light-block provider over a live ``NodeHandle`` (the in-process
    analog of ``light.provider.NodeProvider``): a statesync joiner's light
    client reads headers/commits/validator sets straight from a helper
    peer's stores, so snapshot trust verification runs the production
    light-client path on the virtual clock."""

    def __init__(self, handle: "NodeHandle", chain_id: str):
        self.handle = handle
        self._chain_id = chain_id

    def chain_id(self) -> str:
        return self._chain_id

    def id(self) -> str:
        return f"simnode:{self.handle.index}"

    def light_block(self, height: int):
        from cometbft_tpu.light.provider import ErrLightBlockNotFound
        from cometbft_tpu.types.light import LightBlock, SignedHeader

        bs = self.handle.block_store
        h = height or bs.height()
        meta = bs.load_block_meta(h)
        commit = bs.load_block_commit(h) or bs.load_seen_commit(h)
        vals = self.handle.state_store.load_validators(h)
        if meta is None or commit is None or vals is None:
            raise ErrLightBlockNotFound(f"height {h}")
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validator_set=vals,
        )

    def report_evidence(self, ev) -> None:
        self.handle.evidence_pool.add_evidence(ev)

    def consensus_params(self, height: int):
        params = self.handle.state_store.load_consensus_params(height)
        if params is None:
            params = self.handle.cs.state.consensus_params
        return params


def build_node(
    index: int,
    priv: Ed25519PrivKey,
    gdoc: GenesisDoc,
    root,
    config: Optional[ConsensusConfig] = None,
    db=None,
    clock: Optional[Callable[[], float]] = None,
    ticker_factory: Optional[Callable] = None,
    threaded: bool = True,
    app_factory: Optional[Callable] = None,
    mempool_config: Optional[MempoolConfig] = None,
    app=None,
    app_conns=None,
) -> NodeHandle:
    """Assemble one validator under ``root/node{index}``.

    ``db`` defaults to a fresh ``MemKV``; pass the previous instance (plus
    the same ``root``) to model a crash-restart from persisted stores.
    ``app_factory`` overrides the default kvstore app — the tx-flood
    scenario wraps it in ``txingest.SigVerifyingApp`` so signed-envelope
    traffic exercises the batched admission pipeline.  ``app``/``app_conns``
    hand in an ALREADY-RUNNING application (the statesync join path: the
    syncer restored a snapshot into it before the node is assembled, so
    the handshake must see that instance, not a fresh one).
    """
    config = config or sim_consensus_config()
    home = root / f"node{index}"
    home.mkdir(parents=True, exist_ok=True)
    db = db if db is not None else MemKV()
    block_store = BlockStore(db)
    state_store = StateStore(db)

    if app is None:
        app = app_factory() if app_factory is not None else KVStoreApplication()
    if app_conns is None:
        conns = AppConns(local_client_creator(app))
        conns.start()
    else:
        conns = app_conns

    state = state_store.load()
    if state is None:
        state = state_from_genesis(gdoc)

    event_bus = EventBus()
    evidence_pool = EvidencePool(db, state_store, block_store)
    handshaker = Handshaker(
        state_store,
        block_store,
        gdoc,
        event_bus=event_bus,
        evidence_pool=evidence_pool,
    )
    state = handshaker.handshake(state, conns)
    evidence_pool.state = state

    info = conns.query.info()
    mempool = CListMempool(
        mempool_config or MempoolConfig(recheck=False),
        conns.mempool,
        height=state.last_block_height,
        lane_priorities=dict(info.lane_priorities),
        default_lane=info.default_lane,
        envelope_aware=getattr(info, "envelope_sig_verified", False),
    )
    block_exec = BlockExecutor(
        state_store,
        block_store,
        conns.consensus,
        mempool,
        evidence_pool=evidence_pool,
        event_bus=event_bus,
    )
    key_path = str(home / "pv_key.json")
    state_path = str(home / "pv_state.json")
    pv = FilePV.load_or_generate(key_path, state_path)
    if pv.pub_key().address() != priv.pub_key().address():
        # first boot: install the deterministic genesis key (a restart must
        # keep the persisted last-sign state for double-sign protection)
        pv = FilePV(priv, key_path, state_path)
        pv.save()

    wal = WAL(str(home / "cs.wal"))
    cs = ConsensusState(
        config,
        state,
        block_exec,
        block_store,
        mempool,
        priv_validator=pv,
        wal=wal,
        evidence_pool=evidence_pool,
        event_bus=event_bus,
        clock=clock,
        ticker_factory=ticker_factory,
        threaded=threaded,
    )
    return NodeHandle(
        index=index,
        cs=cs,
        app=app,
        app_conns=conns,
        mempool=mempool,
        block_store=block_store,
        state_store=state_store,
        event_bus=event_bus,
        priv_val=pv,
        evidence_pool=evidence_pool,
    )
