"""Virtual time: the deterministic heart of the simulation.

``VirtualClock`` is a seeded-order event heap; time advances only when the
driver pops the next event, so a 10-minute partition scenario runs in
milliseconds of wall time and two runs with the same seed pop events in the
same order.  ``SimTicker`` implements the ``consensus/ticker.py`` seam on
top of it, so ``ConsensusState`` timeouts fire on virtual time.
"""

from __future__ import annotations

import heapq
from typing import Callable, Optional

from cometbft_tpu.consensus.ticker import TimeoutInfo


class SimTimer:
    """Handle for a scheduled callback; ``cancel`` is O(1) (lazy removal)."""

    __slots__ = ("when", "seq", "fn", "label", "cancelled")

    def __init__(self, when: float, seq: int, fn: Callable[[], None], label: str):
        self.when = when
        self.seq = seq
        self.fn = fn
        self.label = label
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: "SimTimer") -> bool:
        return (self.when, self.seq) < (other.when, other.seq)


class VirtualClock:
    """Single-threaded discrete-event clock.

    Events at equal times fire in scheduling order (a monotonically
    increasing sequence number breaks ties), which keeps the pop order a
    pure function of the schedule calls — the determinism proof relies on
    this.  The instance is callable so it can be handed directly to
    ``ConsensusState(clock=...)``.
    """

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._seq = 0
        self._heap: list[SimTimer] = []

    def now(self) -> float:
        return self._now

    def __call__(self) -> float:
        return self._now

    def call_at(self, when: float, fn: Callable[[], None], label: str = "") -> SimTimer:
        # never schedule into the past: clamp to now (still strictly ordered
        # after anything already popped)
        timer = SimTimer(max(when, self._now), self._seq, fn, label)
        self._seq += 1
        heapq.heappush(self._heap, timer)
        return timer

    def call_later(self, delay: float, fn: Callable[[], None], label: str = "") -> SimTimer:
        return self.call_at(self._now + delay, fn, label)

    def pending(self) -> int:
        return sum(1 for t in self._heap if not t.cancelled)

    def advance_to(self, when: float) -> None:
        """Advance idle time WITHOUT firing events.  Callers must ensure no
        pending event is scheduled before ``when`` (the statesync sleeper
        drains those through ``tick`` first); time never moves backwards."""
        nxt = self.next_event_time()
        if nxt is not None and nxt < when:
            raise ValueError(
                f"advance_to({when}) would skip an event at {nxt}"
            )
        self._now = max(self._now, float(when))

    def next_event_time(self) -> Optional[float]:
        self._drop_cancelled()
        return self._heap[0].when if self._heap else None

    def _drop_cancelled(self) -> None:
        while self._heap and self._heap[0].cancelled:
            heapq.heappop(self._heap)

    def tick(self) -> Optional[SimTimer]:
        """Advance to and fire the next event; None when the heap is dry."""
        self._drop_cancelled()
        if not self._heap:
            return None
        timer = heapq.heappop(self._heap)
        self._now = timer.when
        timer.fn()
        return timer


class SimTicker:
    """``TimeoutTicker`` semantics (one pending timeout, later (H,R,S)
    replaces, stale fires dropped) on a ``VirtualClock``.

    Construct via ``ticker_factory=lambda tock: SimTicker(clock, tock,
    name=...)`` when building a ``ConsensusState``.
    """

    def __init__(
        self,
        clock: VirtualClock,
        on_timeout: Callable[[TimeoutInfo], None],
        name: str = "sim",
    ):
        self.clock = clock
        self.on_timeout = on_timeout
        self.name = name
        self._pending: Optional[TimeoutInfo] = None
        self._timer: Optional[SimTimer] = None
        self._running = False

    def start(self) -> None:
        self._running = True

    def stop(self) -> None:
        self._running = False
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._pending = None

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        if self._pending is not None and ti < self._pending:
            return  # stale: never roll the clock back
        if self._timer is not None:
            self._timer.cancel()
        self._pending = ti
        self._timer = self.clock.call_later(
            ti.duration,
            lambda: self._fire(ti),
            label="%s timeout h=%d r=%d s=%d"
            % (self.name, ti.height, ti.round_, ti.step),
        )

    def _fire(self, ti: TimeoutInfo) -> None:
        if self._pending is not ti:
            return  # superseded
        self._pending = None
        self._timer = None
        if self._running:
            self.on_timeout(ti)
