"""In-memory message fabric with seeded fault injection.

Every consensus message a node broadcasts is scheduled for delivery to each
connected peer through a per-link ``LinkConfig``: uniform delay in
``[delay_min, delay_max]``, independent drop / duplicate probabilities, and
a reorder knob that adds extra jitter to a fraction of messages (enough to
invert arrival order against the send order).  All randomness comes from the
single ``random.Random`` the cluster seeds, so the full delivery schedule is
a pure function of (seed, scenario).

Partitions are scripted as node groups: a message crosses the fabric only if
sender and receiver are in the same group *both* when it is sent and when it
would arrive — cutting a link also kills traffic already in flight, like
yanking a cable.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from cometbft_tpu.sim.clock import VirtualClock


@dataclass
class LinkConfig:
    delay_min: float = 0.01
    delay_max: float = 0.05
    drop_rate: float = 0.0
    dup_rate: float = 0.0
    reorder_rate: float = 0.0
    reorder_jitter: float = 0.25  # extra delay ceiling for reordered msgs
    # Serialization rate: a payload of size_bytes adds size/bandwidth to
    # the propagation delay (0 = infinite pipe, the pre-WAN behavior).
    # Only senders that declare a payload size pay it — consensus gossip
    # is small and modeled latency-only; blocksync block responses and
    # statesync chunks are the big-payload callers (docs/sim-design.md).
    bandwidth_bytes_per_s: float = 0.0

    def update(self, **overrides) -> None:
        for k, v in overrides.items():
            if not hasattr(self, k):
                raise AttributeError(f"LinkConfig has no knob {k!r}")
            setattr(self, k, v)

    def transfer_delay(self, size_bytes: int) -> float:
        """Extra serialization delay for a payload of ``size_bytes``."""
        if self.bandwidth_bytes_per_s <= 0.0 or size_bytes <= 0:
            return 0.0
        return size_bytes / self.bandwidth_bytes_per_s


# Geo-cluster latency classes (one-way, seconds): intra-region links stay
# LAN-ish; inter-region links get intercontinental spreads.  Values echo
# the committee-consensus measurement regime (PAPERS.md, arXiv:2302.00418)
# where geo-distribution, not crypto, dominates tail behavior.
GEO_INTRA = {"delay_min": 0.002, "delay_max": 0.010}
GEO_INTER = {"delay_min": 0.060, "delay_max": 0.180}


@dataclass
class NetStats:
    sent: int = 0
    delivered: int = 0
    dropped_rate: int = 0  # lost to drop_rate
    dropped_partition: int = 0
    duplicated: int = 0


class SimNetwork:
    """Fabric between ``n`` nodes; delivery goes through ``deliver_fn(dst,
    src, msg, ctx)`` which the cluster installs (``ctx`` is the optional
    trace context the gossip envelope carried)."""

    def __init__(self, clock: VirtualClock, rng: random.Random, n: int):
        self.clock = clock
        self.rng = rng
        self.n = n
        self.links: dict[tuple[int, int], LinkConfig] = {
            (i, j): LinkConfig()
            for i in range(n)
            for j in range(n)
            if i != j
        }
        self._group_of: Optional[dict[int, int]] = None  # node -> group id
        self._regions: list[list[int]] = []  # set by set_geo_clusters
        self.deliver_fn: Optional[
            Callable[[int, int, object, object], None]
        ] = None
        self.alive_fn: Callable[[int], bool] = lambda _i: True
        self.stats = NetStats()

    # -- topology scripting ------------------------------------------------

    def set_link(self, src: int, dst: int, **overrides) -> None:
        self.links[(src, dst)].update(**overrides)

    def set_all_links(self, **overrides) -> None:
        for cfg in self.links.values():
            cfg.update(**overrides)

    def set_node_links(self, i: int, **overrides) -> None:
        """Apply overrides to every link touching node ``i`` (both
        directions) — the statesync-storm scenario degrades a joiner's
        connectivity without touching the rest of the fabric."""
        for (src, dst), cfg in self.links.items():
            if src == i or dst == i:
                cfg.update(**overrides)

    def set_geo_clusters(
        self,
        regions: "list[list[int]]",
        intra: Optional[dict] = None,
        inter: Optional[dict] = None,
        **extra,
    ) -> None:
        """Shape the fabric as geo-clusters: every link inside one region
        gets the ``intra`` latency class (default ``GEO_INTRA``), every
        cross-region link the ``inter`` class (default ``GEO_INTER``).
        ``extra`` knobs (drop/bandwidth/...) apply to ALL links on top.
        Nodes not named in any region form one implicit remainder region.
        Composes with ``partition``/``geo_partition``: latency classes
        shape live links, partitions cut them."""
        self._regions = [list(r) for r in regions]
        region_of: dict[int, int] = {}
        for rid, region in enumerate(self._regions):
            for i in region:
                region_of[i] = rid
        for i in range(self.n):
            region_of.setdefault(i, len(self._regions))
        intra = dict(GEO_INTRA if intra is None else intra)
        inter = dict(GEO_INTER if inter is None else inter)
        for (src, dst), cfg in self.links.items():
            cls = intra if region_of[src] == region_of[dst] else inter
            cfg.update(**cls)
            if extra:
                cfg.update(**extra)

    def geo_partition(self, *cut_regions: int) -> None:
        """Cut the named regions (indices into the ``set_geo_clusters``
        list) off from the rest of the world — each cut region becomes its
        own partition group; everything else stays one group."""
        if not getattr(self, "_regions", None):
            raise RuntimeError("geo_partition requires set_geo_clusters first")
        self.partition(*[self._regions[r] for r in cut_regions])

    def partition(self, *groups: list[int]) -> None:
        """Split the cluster into the given groups; nodes not named form one
        implicit remainder group.  Replaces any existing partition."""
        group_of: dict[int, int] = {}
        for gid, group in enumerate(groups):
            for i in group:
                group_of[i] = gid
        for i in range(self.n):
            group_of.setdefault(i, len(groups))
        self._group_of = group_of

    def heal(self) -> None:
        self._group_of = None

    def connected(self, i: int, j: int) -> bool:
        if self._group_of is None:
            return True
        return self._group_of[i] == self._group_of[j]

    # -- traffic -----------------------------------------------------------

    def send(self, src: int, msg: object, ctx=None) -> None:
        """Broadcast from ``src`` to every other live node (push gossip,
        mirroring the loopback harness this package grew out of).  ``ctx``
        is an optional encoded trace context riding the envelope
        (docs/observability.md "Cross-node tracing") — delivered to
        ``deliver_fn`` alongside the message, dropped with it."""
        for dst in range(self.n):
            if dst == src:
                continue
            self._schedule(src, dst, msg, ctx)

    def unicast(
        self, src: int, dst: int, msg: object, ctx=None, size_bytes: int = 0
    ) -> None:
        """Point-to-point send through the same faulty link (catchup).
        ``size_bytes`` > 0 adds serialization delay on bandwidth-shaped
        links (``LinkConfig.bandwidth_bytes_per_s``)."""
        self._schedule(src, dst, msg, ctx, size_bytes=size_bytes)

    def _schedule(
        self, src: int, dst: int, msg: object, ctx=None, size_bytes: int = 0
    ) -> None:
        cfg = self.links[(src, dst)]
        self.stats.sent += 1
        if not self.connected(src, dst):
            self.stats.dropped_partition += 1
            return
        if cfg.drop_rate > 0.0 and self.rng.random() < cfg.drop_rate:
            self.stats.dropped_rate += 1
            return
        copies = 1
        if cfg.dup_rate > 0.0 and self.rng.random() < cfg.dup_rate:
            copies = 2
            self.stats.duplicated += 1
        xfer = cfg.transfer_delay(size_bytes)
        for _ in range(copies):
            delay = self.rng.uniform(cfg.delay_min, cfg.delay_max) + xfer
            if cfg.reorder_rate > 0.0 and self.rng.random() < cfg.reorder_rate:
                delay += self.rng.uniform(0.0, cfg.reorder_jitter)
            self.clock.call_later(
                delay,
                lambda s=src, d=dst, m=msg, c=ctx: self._deliver(s, d, m, c),
                label=f"net {src}->{dst}",
            )

    def schedule_transfer(
        self,
        src: int,
        dst: int,
        fn: Callable[[], None],
        label: str = "xfer",
        size_bytes: int = 0,
    ) -> bool:
        """Schedule an arbitrary point-to-point delivery callback through
        the same faulty link as consensus traffic (delay/drop/partition;
        duplication is meaningless for idempotent transfers and skipped).
        Statesync snapshot/chunk responses ride this, so a lossy or
        partitioned link starves a bootstrapping joiner exactly like it
        starves gossip.  Returns False when the transfer was dropped.

        Unlike ``_schedule`` the delivery does NOT consult ``alive_fn`` —
        a joiner mid-bootstrap is not in the cluster's node table yet."""
        cfg = self.links[(src, dst)]
        self.stats.sent += 1
        if not self.connected(src, dst):
            self.stats.dropped_partition += 1
            return False
        if cfg.drop_rate > 0.0 and self.rng.random() < cfg.drop_rate:
            self.stats.dropped_rate += 1
            return False
        delay = self.rng.uniform(cfg.delay_min, cfg.delay_max) + cfg.transfer_delay(
            size_bytes
        )
        if cfg.reorder_rate > 0.0 and self.rng.random() < cfg.reorder_rate:
            delay += self.rng.uniform(0.0, cfg.reorder_jitter)

        def deliver() -> None:
            if not self.connected(src, dst):
                self.stats.dropped_partition += 1
                return
            self.stats.delivered += 1
            fn()

        self.clock.call_later(delay, deliver, label=f"net {label} {src}->{dst}")
        return True

    def _deliver(self, src: int, dst: int, msg: object, ctx=None) -> None:
        if not self.connected(src, dst):
            self.stats.dropped_partition += 1
            return
        if not self.alive_fn(dst) or not self.alive_fn(src):
            return  # crashed endpoints: traffic dies with the process
        self.stats.delivered += 1
        if self.deliver_fn is not None:
            self.deliver_fn(dst, src, msg, ctx)
