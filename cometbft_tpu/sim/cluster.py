"""Single-threaded N-validator cluster on virtual time.

The drive loop is strictly sequential: pop the next virtual-clock event
(message delivery, consensus timeout, scripted fault, catchup tick), let it
enqueue work, then drain every node's receive queue in index order until the
cluster is quiescent, then run the incremental invariant checkers.  No other
thread exists, so the full event trace — and therefore every commit hash and
every failure — is a pure function of (seed, scenario script).

Catchup: push gossip alone cannot rescue a node that missed a commit (its
peers have moved to later heights whose votes it ignores), so the cluster
runs a virtual-time catchup tick modelled on the reactor's
``gossipDataForCatchup``: a lagging node is served the seen-commit votes and
block parts for its current height by the lowest-indexed connected peer that
has them, through the same faulty fabric as everything else.
"""

from __future__ import annotations

import random
from pathlib import Path
from typing import Optional

from cometbft_tpu.consensus.messages import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from cometbft_tpu.types.block import commit_vote as _commit_vote
from cometbft_tpu.sim.clock import SimTicker, VirtualClock
from cometbft_tpu.sim.invariants import InvariantChecker
from cometbft_tpu.sim.network import SimNetwork
from cometbft_tpu.sim.node import (
    NodeHandle,
    build_node,
    make_genesis,
    sim_consensus_config,
)
from cometbft_tpu.state.state import state_from_genesis

SIM_CHAIN_ID = "sim-chain"
CATCHUP_INTERVAL = 0.5  # virtual seconds between catchup scans


def describe_msg(msg) -> str:
    """Deterministic one-line rendering for the event trace; includes a
    signature prefix so the trace is sensitive to byte-level divergence."""
    if isinstance(msg, ProposalMessage):
        p = msg.proposal
        return "Proposal h=%d r=%d blk=%s sig=%s" % (
            p.height,
            p.round_,
            p.block_id.hash.hex()[:12],
            p.signature.hex()[:12],
        )
    if isinstance(msg, BlockPartMessage):
        return "BlockPart h=%d r=%d i=%d" % (msg.height, msg.round_, msg.part.index)
    if isinstance(msg, VoteMessage):
        v = msg.vote
        return "Vote t=%d h=%d r=%d v%d blk=%s sig=%s" % (
            v.type_,
            v.height,
            v.round_,
            v.validator_index,
            v.block_id.hash.hex()[:12],
            v.signature.hex()[:12],
        )
    return type(msg).__name__


class SimCluster:
    def __init__(
        self,
        n_vals: int,
        root,
        seed: int = 0,
        config=None,
        raise_on_violation: bool = True,
        check_wal: bool = True,
        catchup: bool = True,
        app_factory=None,
        mempool_config=None,
    ):
        self.n_vals = n_vals
        self.root = Path(root)
        self.seed = seed
        self.config = config or sim_consensus_config()
        self.app_factory = app_factory
        self.mempool_config = mempool_config
        self.raise_on_violation = raise_on_violation
        self.clock = VirtualClock()
        self.rng = random.Random(seed)
        self.privs, self.gdoc = make_genesis(n_vals, SIM_CHAIN_ID)
        self.net = SimNetwork(self.clock, self.rng, n_vals)
        self.net.deliver_fn = self._on_deliver
        self.net.alive_fn = lambda i: self.nodes[i] is not None
        self.checker = InvariantChecker(
            SIM_CHAIN_ID, state_from_genesis(self.gdoc).validators, check_wal
        )
        self.trace: list[str] = []
        self.events_fired = 0
        # Which node's work is currently executing (set while draining a
        # node's queue) — backend-fault scenarios use it to scope injected
        # device failures to a victim subset; None = cluster-level work
        # (invariant checker, scripted actions).
        self.active_node: Optional[int] = None
        self._dbs: list = [None] * n_vals  # MemKV survives crash-restart
        self.nodes: list[Optional[NodeHandle]] = [
            self._build(i) for i in range(n_vals)
        ]
        self._started = False
        self._catchup = catchup

    # -- assembly ----------------------------------------------------------

    def _build(self, i: int) -> NodeHandle:
        node = build_node(
            i,
            self.privs[i],
            self.gdoc,
            self.root,
            config=self.config,
            db=self._dbs[i],
            clock=self.clock,
            ticker_factory=lambda tock, i=i: SimTicker(
                self.clock, tock, name=f"node{i}"
            ),
            threaded=False,
            app_factory=self.app_factory,
            mempool_config=self.mempool_config,
        )
        self._dbs[i] = node.block_store._db
        node.cs.broadcast_hook = lambda msg, i=i: self.net.send(i, msg)
        return node

    def live_nodes(self) -> list[NodeHandle]:
        return [n for n in self.nodes if n is not None]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for node in self.live_nodes():
            self._log("start node%d" % node.index)
            node.cs.start()
        if self._catchup:
            self.clock.call_later(
                CATCHUP_INTERVAL, self._catchup_tick, label="catchup"
            )
        self._drain_all()

    def stop(self) -> None:
        for node in self.live_nodes():
            node.cs.stop()
            node.app_conns.stop()

    def crash(self, i: int) -> None:
        """Kill node i: its process state vanishes, its stores/WAL/privval
        files survive for ``restart``.  In-flight traffic to it is dropped,
        and the WAL loses its unflushed user-space tail (``WAL.kill``) —
        a graceful stop would fsync it and hide lost-tail replay bugs."""
        node = self.nodes[i]
        if node is None:
            return
        self._log("crash node%d" % i)
        self.nodes[i] = None  # alive_fn now reports dead
        if node.cs.wal is not None:
            node.cs.wal.kill()
        node.cs.stop()
        node.app_conns.stop()

    def restart(self, i: int) -> None:
        """Rebuild node i from its persisted stores: Handshaker replays the
        app, WAL catchup replays unfinished-height consensus inputs."""
        if self.nodes[i] is not None:
            return
        self._log("restart node%d" % i)
        node = self._build(i)
        self.nodes[i] = node
        node.cs.start()
        self._drain_all()
        self.checker.on_restart(self, i)

    # -- event loop --------------------------------------------------------

    def _on_deliver(self, dst: int, src: int, msg) -> None:
        node = self.nodes[dst]
        if node is None or not node.cs.is_running:
            return
        self._log("deliver %d->%d %s" % (src, dst, describe_msg(msg)))
        node.cs.add_peer_message(msg, peer_id=f"node{src}")

    def _drain_all(self) -> None:
        progress = True
        while progress:
            progress = False
            for node in self.nodes:
                if node is not None and node.cs.is_running:
                    self.active_node = node.index
                    try:
                        if node.cs.process_pending():
                            progress = True
                    finally:
                        self.active_node = None

    def step(self) -> bool:
        """Fire one scheduled event + drain + check invariants."""
        timer = self.clock.tick()
        if timer is None:
            return False
        self.events_fired += 1
        if (
            timer.label
            and not timer.label.startswith("net ")
            and timer.label != "catchup"
        ):
            # deliveries log themselves with message detail; catchup ticks
            # are pure scheduling noise
            self._log("fire %s" % timer.label)
        self._drain_all()
        self.trace.extend(self.checker.on_event(self))
        return True

    def run(
        self,
        until_height: Optional[int] = None,
        max_time: float = 600.0,
        max_events: int = 500_000,
    ) -> bool:
        """Drive until every live node has committed ``until_height`` (or
        the virtual-time/event budget runs out).  Returns success."""
        self.start()
        while True:
            if until_height is not None and self.reached(until_height):
                return True
            if self.clock.now() >= max_time or self.events_fired >= max_events:
                return until_height is not None and self.reached(until_height)
            if not self.step():
                return until_height is not None and self.reached(until_height)

    def reached(self, height: int) -> bool:
        """Every validator — crashed ones count as behind — has committed
        ``height``; 'the cluster made it' means no node left behind."""
        return all(
            n is not None and n.block_store.height() >= height
            for n in self.nodes
        )

    def heights(self) -> list[int]:
        return [
            -1 if n is None else n.block_store.height() for n in self.nodes
        ]

    def commit_hash(self, height: int) -> Optional[bytes]:
        for node in self.live_nodes():
            meta = node.block_store.load_block_meta(height)
            if meta is not None:
                return meta.block_id.hash
        return None

    # -- catchup -----------------------------------------------------------

    def _catchup_tick(self) -> None:
        for node in self.live_nodes():
            want = node.cs.rs.height  # first height it has not committed
            helper = next(
                (
                    peer
                    for peer in self.live_nodes()
                    if peer.index != node.index
                    and peer.block_store.height() >= want
                    and self.net.connected(peer.index, node.index)
                ),
                None,
            )
            if helper is None:
                continue
            commit = helper.block_store.load_seen_commit(want)
            meta = helper.block_store.load_block_meta(want)
            if commit is None or meta is None:
                continue
            for idx in range(len(commit.signatures)):
                vote = _commit_vote(commit, idx)
                if vote is not None:
                    self.net.unicast(
                        helper.index, node.index, VoteMessage(vote)
                    )
            for pi in range(meta.block_id.part_set_header.total):
                part = helper.block_store.load_block_part(want, pi)
                if part is not None:
                    self.net.unicast(
                        helper.index,
                        node.index,
                        BlockPartMessage(
                            height=want, round_=commit.round_, part=part
                        ),
                    )
        self.clock.call_later(CATCHUP_INTERVAL, self._catchup_tick, label="catchup")

    # -- trace -------------------------------------------------------------

    def _log(self, line: str) -> None:
        self.trace.append("%.6f %s" % (self.clock.now(), line))
