"""Single-threaded N-validator cluster on virtual time.

The drive loop is strictly sequential: pop the next virtual-clock event
(message delivery, consensus timeout, scripted fault, catchup tick), let it
enqueue work, then drain every node's receive queue in index order until the
cluster is quiescent, then run the incremental invariant checkers.  No other
thread exists, so the full event trace — and therefore every commit hash and
every failure — is a pure function of (seed, scenario script).

Catchup: push gossip alone cannot rescue a node that missed a commit (its
peers have moved to later heights whose votes it ignores), so the cluster
runs a virtual-time catchup tick modelled on the reactor's
``gossipDataForCatchup``: a lagging node is served the seen-commit votes and
block parts for its current height by the lowest-indexed connected peer that
has them, through the same faulty fabric as everything else.

Fleet-scale membership (docs/sim-design.md "Fleet scale"): the cluster can
be built with ``n_spares`` standby nodes beyond the genesis validator set.
A spare either comes online at genesis (``spawn_spare`` — it replays the
chain through consensus catchup) or arrives later via the REAL statesync
path (``join`` — snapshot offer → chunk fetch over the faulty fabric →
blocksync-style catchup tail), all on the virtual clock.  ``leave`` retires
a node gracefully; ``crash``/``restart`` stay the hard-kill path.  The
validator set itself rotates through ``val:`` txs (``add_validator`` /
``remove_validator``), which flow through the production
``validate_validator_updates`` path at FinalizeBlock.
"""

from __future__ import annotations

import random
import shutil
from pathlib import Path
from typing import Optional

from cometbft_tpu.consensus.messages import (
    BlockPartMessage,
    ProposalMessage,
    VoteMessage,
)
from cometbft_tpu.libs import blackbox, tracing
from cometbft_tpu.types.block import commit_vote as _commit_vote
from cometbft_tpu.sim.clock import SimTicker, VirtualClock
from cometbft_tpu.sim.invariants import InvariantChecker
from cometbft_tpu.sim.network import SimNetwork
from cometbft_tpu.sim.node import (
    HandleProvider,
    NodeHandle,
    build_node,
    make_genesis,
    sim_consensus_config,
)
from cometbft_tpu.state.state import state_from_genesis

SIM_CHAIN_ID = "sim-chain"
CATCHUP_INTERVAL = 0.5  # virtual seconds between catchup scans


def describe_msg(msg) -> str:
    """Deterministic one-line rendering for the event trace; includes a
    signature prefix so the trace is sensitive to byte-level divergence."""
    if isinstance(msg, ProposalMessage):
        p = msg.proposal
        return "Proposal h=%d r=%d blk=%s sig=%s" % (
            p.height,
            p.round_,
            p.block_id.hash.hex()[:12],
            p.signature.hex()[:12],
        )
    if isinstance(msg, BlockPartMessage):
        return "BlockPart h=%d r=%d i=%d" % (msg.height, msg.round_, msg.part.index)
    if isinstance(msg, VoteMessage):
        v = msg.vote
        return "Vote t=%d h=%d r=%d v%d blk=%s sig=%s" % (
            v.type_,
            v.height,
            v.round_,
            v.validator_index,
            v.block_id.hash.hex()[:12],
            v.signature.hex()[:12],
        )
    return type(msg).__name__


class SimCluster:
    def __init__(
        self,
        n_vals: int,
        root,
        seed: int = 0,
        config=None,
        raise_on_violation: bool = True,
        check_wal: bool = True,
        catchup: bool = True,
        app_factory=None,
        mempool_config=None,
        n_spares: int = 0,
    ):
        self.n_vals = n_vals
        self.n_spares = n_spares
        self.n_nodes = n_vals + n_spares
        self.root = Path(root)
        self.seed = seed
        self.config = config or sim_consensus_config()
        self.app_factory = app_factory
        self.mempool_config = mempool_config
        self.raise_on_violation = raise_on_violation
        self.clock = VirtualClock()
        self.rng = random.Random(seed)
        self.privs, self.gdoc = make_genesis(
            n_vals, SIM_CHAIN_ID, n_nodes=self.n_nodes
        )
        self.net = SimNetwork(self.clock, self.rng, self.n_nodes)
        self.net.deliver_fn = self._on_deliver
        self.net.alive_fn = lambda i: self.nodes[i] is not None
        self.checker = InvariantChecker(
            SIM_CHAIN_ID, state_from_genesis(self.gdoc), check_wal
        )
        self.trace: list[str] = []
        self.events_fired = 0
        # Which node's work is currently executing (set while draining a
        # node's queue) — backend-fault scenarios use it to scope injected
        # device failures to a victim subset; None = cluster-level work
        # (invariant checker, scripted actions).
        self.active_node: Optional[int] = None
        self._dbs: list = [None] * self.n_nodes  # MemKV survives crash-restart
        # per-node black-box journals (docs/observability.md "Black box"):
        # synchronous mode — one sim thread, so journal bytes are a pure
        # function of the seed — routed from the process-wide tracer by
        # ``active_node``.  ``crash`` kills a journal with the same
        # drop-unflushed-tail discipline as the WAL; ``restart`` decodes
        # the dead journal's postmortem (digest logged into the
        # byte-compared trace) before reopening it.
        self.blackbox: dict = {}
        self.postmortems: list[dict] = []
        # nodes halted by a fail-stop storage failure (StorageFatal from
        # the WAL / privval / state surfaces): they leave ``members`` —
        # 'the cluster made it' means no SURVIVOR left behind; a
        # fail-stopped node is an operator page, not a laggard
        self.fail_stopped: set[int] = set()
        self._bb_enabled = blackbox.enabled()
        self._bb_prev_sinks: Optional[dict] = None
        if self._bb_enabled:
            self._bb_prev_sinks = {
                "span": tracing.set_sink("span", self._bb_span),
                "open": tracing.set_sink("open", self._bb_open),
                "anomaly": tracing.set_sink("anomaly", self._bb_anomaly),
                "event": tracing.set_sink("event", self._bb_event),
            }
        self.nodes: list[Optional[NodeHandle]] = [
            self._build(i) for i in range(n_vals)
        ] + [None] * n_spares
        # membership: nodes expected to be online and at the chain head;
        # ``reached`` waits for exactly these (a crashed member still
        # counts as behind, a left node does not)
        self.members: set[int] = set(range(n_vals))
        self._started = False
        self._catchup = catchup
        self._joining = False  # statesync joins never nest
        self._bsync: dict = {}  # in-flight blocksync joins (sim/blocksync.py)

    # -- assembly ----------------------------------------------------------

    def _build(self, i: int, app=None, app_conns=None) -> NodeHandle:
        node = build_node(
            i,
            self.privs[i],
            self.gdoc,
            self.root,
            config=self.config,
            db=self._dbs[i],
            clock=self.clock,
            ticker_factory=lambda tock, i=i: SimTicker(
                self.clock, tock, name=f"node{i}"
            ),
            threaded=False,
            app_factory=self.app_factory,
            mempool_config=self.mempool_config,
            app=app,
            app_conns=app_conns,
        )
        self._dbs[i] = node.block_store._db
        # gossip envelopes carry the sender's round-trace context so
        # consensus-round spans merge into one causal tree per (height,
        # round) across the cluster (docs/observability.md); the context
        # is read at send time — the anchor may have been adopted since
        node.cs.trace_origin = i
        node.cs.broadcast_hook = lambda msg, i=i: self._broadcast(i, msg)
        node.cs.on_storage_fatal = lambda e, i=i: self._on_storage_fatal(i, e)
        if self._bb_enabled:
            j = blackbox.BlackboxJournal(
                str(self.root / f"node{i}" / "blackbox"),
                threaded=False,  # the one sim thread writes; deterministic
                clock=self.clock.now,
                # no periodic health records in sim: their counter
                # snapshots carry WALL-clock aggregates (verify_seconds,
                # latency sums), which would break the journal's
                # byte-per-seed determinism the soak matrix enforces
                health_every=None,
            )
            j.on_event("boot", {"node": i})
            self.blackbox[i] = j
        # a WAL tail repair at this boot (the crash-consistency scrub,
        # docs/storage-robustness.md) lands in the trace AND the node's
        # fresh journal — the torn-wal-restart scenario asserts both
        repair = (
            node.cs.wal.last_repair if node.cs.wal is not None else None
        )
        if repair is not None:
            self._log(
                "node%d wal_repair: truncated %d torn byte(s) to %d"
                % (i, repair["dropped_bytes"], repair["good_bytes"])
            )
            j = self.blackbox.get(i)
            if j is not None and not j.closed:
                j.on_event("wal_repair", {"node": i, **repair})
        return node

    def _on_storage_fatal(self, i: int, err) -> None:
        """A node hit a fail-stop storage failure: it has already halted
        its consensus state machine (before voting on unpersisted state —
        ``ConsensusState._storage_fatal``); here the cluster retires it
        like a crash whose operator never comes back.  It leaves
        ``members`` so ``reached`` measures the SURVIVORS — the agreement
        invariant still covers everything it committed before the halt."""
        self._log(
            "node%d STORAGE FATAL %s/%s: fail-stop halt (errno=%s)"
            % (i, err.surface, err.op, err.io_errno)
        )
        node = self.nodes[i]
        if node is None:
            return
        self.nodes[i] = None
        self.members.discard(i)
        self.fail_stopped.add(i)
        node.app_conns.stop()
        j = self.blackbox.get(i)
        if j is not None and not j.closed:
            # a fail-stop is a DELIBERATE halt: the process exits through
            # its shutdown path, so the journal gets its clean-close
            # sentinel (the disk_fatal anomaly is already journaled; on a
            # truly full disk the sentinel itself degrades to a counted
            # drop through the blackbox surface guard)
            j.close(clean=True)

    # -- black-box routing -------------------------------------------------
    #
    # One process hosts every sim node but each node keeps its OWN
    # journal, like production: records route by ``active_node`` (set
    # while a node's work executes), falling back to the span's ``node``
    # attr for consensus records emitted outside a drain.  Cluster-level
    # work (the invariant checker) is nobody's black box and is dropped.

    def _bb_target(self, attrs=None):
        i = self.active_node
        if i is None and attrs:
            i = attrs.get("node")
        if i is None:
            return None
        j = self.blackbox.get(i)
        return j if j is not None and not j.closed else None

    def _bb_span(self, sp) -> None:
        j = self._bb_target(sp.attrs)
        if j is not None:
            j.on_span(sp)

    def _bb_open(self, sp) -> None:
        j = self._bb_target(sp.attrs)
        if j is not None:
            j.on_open(sp)

    def _bb_anomaly(self, kind, attrs, t) -> None:
        j = self._bb_target(attrs)
        if j is not None:
            j.on_anomaly(kind, attrs, t)

    def _bb_event(self, kind, attrs) -> None:
        j = self._bb_target(attrs)
        if j is not None:
            j.on_event(kind, attrs)

    def _bb_close_all(self, clean: bool = True) -> None:
        for j in self.blackbox.values():
            if not j.closed:
                j.close(clean=clean)
        if self._bb_prev_sinks is not None:
            ours = (
                self._bb_span,
                self._bb_open,
                self._bb_anomaly,
                self._bb_event,
            )
            for k, fn in self._bb_prev_sinks.items():
                # restore only sinks still ours — a later installer
                # (another cluster, a node) must not be clobbered
                if tracing.get_sink(k) in ours:
                    tracing.set_sink(k, fn)
            self._bb_prev_sinks = None

    def blackbox_stats(self) -> dict:
        """Aggregate journal counters for soak rows: total records/bytes/
        drops/rotations across the cluster, on-disk footprint vs the
        configured segment budget (``budget_ok`` fails a soak row that
        outgrew it), and the postmortem count."""
        import os as _os

        agg = {"records": 0, "bytes": 0, "dropped": 0, "rotations": 0}
        disk = 0
        budget_ok = True
        for j in self.blackbox.values():
            s = j.stats()
            for k in agg:
                agg[k] += s[k]
            node_disk = 0
            for fp in blackbox.segment_files(j.dir):
                try:
                    node_disk += _os.path.getsize(fp)
                except OSError:
                    pass
            disk += node_disk
            # + one frame of slack: rotation triggers on the write that
            # would cross the threshold
            if node_disk > j.segments * j.segment_bytes + blackbox.MAX_REC_SIZE:
                budget_ok = False
        agg["disk_bytes"] = disk
        agg["budget_ok"] = budget_ok
        agg["nodes"] = len(self.blackbox)
        agg["postmortems"] = len(self.postmortems)
        return agg

    def _broadcast(self, i: int, msg) -> None:
        node = self.nodes[i]
        ctx = None
        if node is not None:
            # current_trace_ctx already gates on tracing.xnode_enabled()
            tc = node.cs.current_trace_ctx()
            ctx = tc.encode() if tc is not None else None
        self.net.send(i, msg, ctx=ctx)

    def live_nodes(self) -> list[NodeHandle]:
        return [n for n in self.nodes if n is not None]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for node in self.live_nodes():
            self._log("start node%d" % node.index)
            self._start_cs(node)
        if self._catchup:
            self.clock.call_later(
                CATCHUP_INTERVAL, self._catchup_tick, label="catchup"
            )
        self._drain_all()

    def _start_cs(self, node: NodeHandle) -> None:
        """Start a node's consensus with ``active_node`` set, so the
        round anchor its start opens routes to the node's own journal."""
        self.active_node = node.index
        try:
            node.cs.start()
        finally:
            self.active_node = None

    def stop(self) -> None:
        for h in list(self._bsync.values()):
            h.close()
        self._bsync.clear()
        for node in self.live_nodes():
            node.cs.stop()
            node.app_conns.stop()
        self._bb_close_all(clean=True)

    def crash(self, i: int) -> None:
        """Kill node i: its process state vanishes, its stores/WAL/privval
        files survive for ``restart``.  In-flight traffic to it is dropped,
        and the WAL loses its unflushed user-space tail (``WAL.kill``) —
        a graceful stop would fsync it and hide lost-tail replay bugs."""
        node = self.nodes[i]
        if node is None:
            return
        self._log("crash node%d" % i)
        self.nodes[i] = None  # alive_fn now reports dead
        if node.cs.wal is not None:
            node.cs.wal.kill()
        j = self.blackbox.get(i)
        if j is not None:
            # same discipline as the WAL: the journal's unflushed tail
            # dies with the process, so crash scenarios exercise real
            # torn tails — a graceful close here would hide them
            j.kill()
        node.cs.stop()
        node.app_conns.stop()

    def restart(self, i: int) -> None:
        """Rebuild node i from its persisted stores: Handshaker replays the
        app, WAL catchup replays unfinished-height consensus inputs."""
        if self.nodes[i] is not None:
            return
        self._log("restart node%d" % i)
        if self._bb_enabled:
            # decode the dead journal BEFORE reopening repairs its torn
            # tail — the same order a real node boots in.  The digest
            # lands in the byte-compared trace, so the nightly matrix's
            # same-seed double runs enforce that a killed node's
            # reconstruction is a pure function of the seed.
            rep = blackbox.postmortem_report(
                str(self.root / f"node{i}" / "blackbox")
            )
            self.postmortems.append(
                {"node": i, "t": round(self.clock.now(), 6), "report": rep}
            )
            inf = rep.get("in_flight") or {}
            self._log(
                "restart node%d postmortem: clean=%s records=%d torn=%s "
                "corrupt=%d last_committed=%s inflight=h%s/r%s open_spans=%d"
                % (
                    i,
                    rep["clean_close"],
                    rep["journal"]["records"],
                    rep["journal"]["torn_tail"],
                    rep["journal"]["corrupt_skipped"],
                    rep["last_committed_height"],
                    inf.get("h"),
                    inf.get("r"),
                    len(rep["open_spans"]),
                )
            )
        node = self._build(i)
        self.nodes[i] = node
        self._start_cs(node)
        self._drain_all()
        self.checker.on_restart(self, i)

    # -- churn --------------------------------------------------------------

    def leave(self, i: int) -> None:
        """Graceful departure: the node flushes and stops (WAL intact) and
        stops counting toward ``reached``.  Its stores survive, so a later
        ``restart`` models an operator bringing the same node back, while
        ``join`` models a fresh machine taking over the index."""
        node = self.nodes[i]
        if node is None:
            return
        self._log("leave node%d" % i)
        self.nodes[i] = None
        node.cs.stop()
        node.app_conns.stop()
        j = self.blackbox.get(i)
        if j is not None:
            j.close(clean=True)  # graceful: the sentinel IS the point
        self.members.discard(i)

    def spawn_spare(self, i: int) -> None:
        """Bring standby node ``i`` online from genesis: it replays the
        whole chain through consensus catchup (cheap early in a run).  Late
        arrivals should use ``join`` instead — that's the statesync path."""
        if self.nodes[i] is not None:
            return
        self._log("spawn node%d" % i)
        node = self._build(i)
        self.nodes[i] = node
        self.members.add(i)
        self._start_cs(node)
        self._drain_all()

    def join(self, i: int, helper_index: Optional[int] = None) -> bool:
        """Bring node ``i`` online as a FRESH machine via statesync on the
        virtual clock: discover snapshots from live peers, light-verify the
        target height against a height-1 trust root, stream chunks through
        the faulty fabric, bootstrap the stores, then let the catchup tick
        serve the blocksync tail.  Returns False (and logs) when no viable
        snapshot exists yet — scenarios typically retry a few virtual
        seconds later.  Any previous identity at this index is wiped."""
        if self.nodes[i] is not None or self._joining:
            return False
        helpers = [
            n
            for n in self.live_nodes()
            if helper_index is None or n.index == helper_index
        ]
        if not helpers:
            self._log("join node%d failed: no live peers" % i)
            return False
        self._log("join node%d starting statesync" % i)
        self._joining = True
        try:
            ok = self._statesync_join(i, helpers)
        finally:
            self._joining = False
        return ok

    def _statesync_join(self, i: int, helpers: list[NodeHandle]) -> bool:
        from cometbft_tpu.abci import types as at
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.light.verifier import TrustOptions
        from cometbft_tpu.proxy.multi_app_conn import (
            AppConns,
            local_client_creator,
        )
        from cometbft_tpu.statesync.stateprovider import (
            LightClientStateProvider,
        )
        from cometbft_tpu.statesync.syncer import (
            SnapshotKey,
            StatesyncError,
            Syncer,
        )
        from cometbft_tpu.state.store import StateStore
        from cometbft_tpu.store.block_store import BlockStore
        from cometbft_tpu.store.kv import MemKV

        # fresh machine: no stores, no WAL, no privval history — and no
        # black box (close the old handle before its dir vanishes)
        j = self.blackbox.pop(i, None)
        if j is not None and not j.closed:
            j.close(clean=False)
        shutil.rmtree(self.root / f"node{i}", ignore_errors=True)
        self._dbs[i] = None
        app = (
            self.app_factory() if self.app_factory is not None
            else KVStoreApplication()
        )
        conns = AppConns(local_client_creator(app))
        conns.start()

        trust_meta = helpers[0].block_store.load_block_meta(1)
        if trust_meta is None:
            conns.stop()
            self._log("join node%d failed: no trust root yet" % i)
            return False
        provider = HandleProvider(helpers[0], SIM_CHAIN_ID)
        state_provider = LightClientStateProvider(
            SIM_CHAIN_ID,
            [provider],
            TrustOptions(
                period_s=10**9,
                height=1,
                hash=trust_meta.block_id.hash,
            ),
            genesis_doc=self.gdoc,
            now_fn=self.clock,
        )

        syncer_box: list = []

        def request_chunk(peer_id: str, height: int, fmt: int, idx: int) -> bool:
            src = int(peer_id[len("node"):])

            def respond() -> None:
                peer = self.nodes[src]
                if peer is None:
                    return  # helper died between request and response
                res = peer.app_conns.snapshot.load_snapshot_chunk(
                    at.LoadSnapshotChunkRequest(
                        height=height, format=fmt, chunk=idx
                    )
                )
                if res.chunk and syncer_box:

                    def deliver() -> None:
                        syncer_box[0].add_chunk(height, fmt, idx, res.chunk)

                    self.net.schedule_transfer(
                        src, i, deliver, label="chunk-resp"
                    )

            # request leg and response leg each cross the faulty fabric
            return self.net.schedule_transfer(
                i, src, respond, label="chunk-req"
            )

        syncer = Syncer(
            state_provider,
            conns,
            request_chunk,
            logger=None,
            clock=self.clock,
            sleeper=self._statesync_sleeper,
        )
        syncer_box.append(syncer)

        # snapshot discovery: every live helper advertises its app snapshots
        for helper in helpers:
            res = helper.app_conns.snapshot.list_snapshots(
                at.ListSnapshotsRequest()
            )
            for s in res.snapshots:
                syncer.add_snapshot(
                    f"node{helper.index}",
                    SnapshotKey(
                        height=s.height,
                        format=s.format,
                        hash=s.hash,
                        chunks=s.chunks,
                        metadata=s.metadata,
                    ),
                )

        try:
            state, commit = syncer.sync_any(0.0, is_running=lambda: True)
        except StatesyncError as e:
            conns.stop()
            self._log("join node%d statesync failed: %s" % (i, e))
            return False

        db = MemKV()
        StateStore(db).bootstrap(state)
        BlockStore(db).save_seen_commit(state.last_block_height, commit)
        self._dbs[i] = db
        node = self._build(i, app=app, app_conns=conns)
        self.nodes[i] = node
        self.members.add(i)
        self.checker.on_join(self, i, state.last_block_height)
        self._log(
            "join node%d statesync complete h=%d" % (i, state.last_block_height)
        )
        self._start_cs(node)
        self._drain_all()
        return True

    def blocksync_join(self, i: int, helper_indices=None) -> bool:
        """Bring node ``i`` online as a FRESH machine via BLOCKSYNC on the
        virtual clock: the real ``BlocksyncReactor`` downloads every block
        from live helpers through the faulty, bandwidth-shaped fabric,
        verifies commits through the batch seam (fused-prefetch windows
        included) and applies them; once caught up the cluster assembles a
        full node over the populated stores and starts its consensus.
        Returns False when no live helpers exist.  Non-blocking: the sync
        runs on repeating clock timers inside the normal ``run`` loop
        (``sim/blocksync.py``)."""
        from cometbft_tpu.sim.blocksync import SimBlocksync

        if self.nodes[i] is not None or i in self._bsync:
            return False
        helpers = [
            n.index
            for n in self.live_nodes()
            if helper_indices is None or n.index in helper_indices
        ]
        if not helpers:
            self._log("bsync node%d failed: no live peers" % i)
            return False
        fresh = self._dbs[i] is None
        self._log(
            "bsync node%d starting blocksync (%s) helpers=%s"
            % (i, "fresh" if fresh else "resume", ",".join(map(str, helpers)))
        )
        self._bsync[i] = SimBlocksync(self, i, helpers)
        return True

    def blocksync_crash(self, i: int) -> None:
        """Kill a mid-catchup blocksync joiner (its stores survive for a
        ``blocksync_join`` resume — the crash-restart leg of the storm
        scenario)."""
        h = self._bsync.pop(i, None)
        if h is not None:
            h.crash()

    def blocksync_harness(self, i: int):
        """The live ``SimBlocksync`` for joiner ``i`` (fault-scripting
        handle for scenario actions), or None."""
        return self._bsync.get(i)

    def _finish_blocksync_join(self, harness) -> None:
        """Blocksync caught up: promote the joiner to a full member over
        its populated stores (the blocksync analog of the tail of
        ``_statesync_join``).  Runs inside a clock-timer callback, so the
        surrounding ``step()`` drains and invariant-checks right after."""
        i = harness.index
        self._bsync.pop(i, None)
        node = self._build(i, app=harness.app, app_conns=harness.conns)
        self.nodes[i] = node
        self.members.add(i)
        self.checker.on_join(self, i, node.block_store.height())
        self._log(
            "join node%d blocksync complete h=%d"
            % (i, node.block_store.height())
        )
        self._start_cs(node)

    def _statesync_sleeper(self, timeout: float) -> None:
        """The syncer's wait seam on virtual time: keep the REST of the
        cluster running (consensus timeouts, deliveries, scripted faults,
        chunk responses) while the joiner blocks, exactly like a real
        joiner waiting out the network."""
        deadline = self.clock.now() + timeout
        while True:
            nxt = self.clock.next_event_time()
            if nxt is None or nxt > deadline:
                break
            self.step()
        self.clock.advance_to(deadline)

    # -- validator rotation -------------------------------------------------

    def add_validator(self, i: int, power: int = 10) -> None:
        """Vote node ``i``'s key into the validator set: inject the
        kvstore's ``val:`` tx into every live mempool so whichever node
        proposes next carries the update (validate_validator_updates path,
        effective at +2 heights)."""
        self._inject_val_tx(i, power)

    def remove_validator(self, i: int) -> None:
        """Vote node ``i`` out (power 0 removes; the node keeps running as
        a full node)."""
        self._inject_val_tx(i, 0)

    def _inject_val_tx(self, i: int, power: int) -> None:
        import base64

        pub_b64 = base64.b64encode(self.privs[i].pub_key().bytes()).decode()
        tx = b"val:%s!%d" % (pub_b64.encode(), power)
        self._log("validator update node%d power=%d" % (i, power))
        for node in self.live_nodes():
            try:
                node.mempool.check_tx(tx)
            except Exception:  # noqa: BLE001 — duplicate in cache etc.
                pass

    # -- event loop --------------------------------------------------------

    def _on_deliver(self, dst: int, src: int, msg, ctx=None) -> None:
        node = self.nodes[dst]
        if node is None or not node.cs.is_running:
            return
        self._log("deliver %d->%d %s" % (src, dst, describe_msg(msg)))
        node.cs.add_peer_message(msg, peer_id=f"node{src}", trace_ctx=ctx)

    def _drain_all(self) -> None:
        progress = True
        while progress:
            progress = False
            for node in self.nodes:
                if node is not None and node.cs.is_running:
                    self.active_node = node.index
                    try:
                        if node.cs.process_pending():
                            progress = True
                    finally:
                        self.active_node = None

    def step(self) -> bool:
        """Fire one scheduled event + drain + check invariants."""
        timer = self.clock.tick()
        if timer is None:
            return False
        self.events_fired += 1
        if (
            timer.label
            and not timer.label.startswith("net ")
            and not timer.label.startswith("bsync ")
            and timer.label != "catchup"
        ):
            # deliveries log themselves with message detail; catchup and
            # blocksync scheduler ticks are pure scheduling noise (the
            # harness logs the semantic events itself)
            self._log("fire %s" % timer.label)
        self._drain_all()
        self.trace.extend(self.checker.on_event(self))
        return True

    def run(
        self,
        until_height: Optional[int] = None,
        max_time: float = 600.0,
        max_events: int = 500_000,
    ) -> bool:
        """Drive until every member node has committed ``until_height`` (or
        the virtual-time/event budget runs out).  Returns success."""
        self.start()
        while True:
            if until_height is not None and self.reached(until_height):
                return True
            if self.clock.now() >= max_time or self.events_fired >= max_events:
                return until_height is not None and self.reached(until_height)
            if not self.step():
                return until_height is not None and self.reached(until_height)

    def reached(self, height: int) -> bool:
        """Every member — crashed ones count as behind — has committed
        ``height``; 'the cluster made it' means no member left behind."""
        return all(
            self.nodes[i] is not None
            and self.nodes[i].block_store.height() >= height
            for i in self.members
        )

    def heights(self) -> list[int]:
        return [
            -1 if n is None else n.block_store.height() for n in self.nodes
        ]

    def commit_hash(self, height: int) -> Optional[bytes]:
        for node in self.live_nodes():
            meta = node.block_store.load_block_meta(height)
            if meta is not None:
                return meta.block_id.hash
        return None

    # -- catchup -----------------------------------------------------------

    def _catchup_tick(self) -> None:
        for node in self.live_nodes():
            want = node.cs.rs.height  # first height it has not committed
            helper = next(
                (
                    peer
                    for peer in self.live_nodes()
                    if peer.index != node.index
                    and peer.block_store.height() >= want
                    and self.net.connected(peer.index, node.index)
                ),
                None,
            )
            if helper is None:
                continue
            commit = helper.block_store.load_seen_commit(want)
            meta = helper.block_store.load_block_meta(want)
            if commit is None or meta is None:
                continue
            for idx in range(len(commit.signatures)):
                vote = _commit_vote(commit, idx)
                if vote is not None:
                    self.net.unicast(
                        helper.index, node.index, VoteMessage(vote)
                    )
            for pi in range(meta.block_id.part_set_header.total):
                part = helper.block_store.load_block_part(want, pi)
                if part is not None:
                    self.net.unicast(
                        helper.index,
                        node.index,
                        BlockPartMessage(
                            height=want, round_=commit.round_, part=part
                        ),
                    )
        self.clock.call_later(CATCHUP_INTERVAL, self._catchup_tick, label="catchup")

    # -- trace -------------------------------------------------------------

    def _log(self, line: str) -> None:
        self.trace.append("%.6f %s" % (self.clock.now(), line))
