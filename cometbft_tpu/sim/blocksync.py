"""Deterministic blocksync over the sim fabric.

Drives the REAL ``blocksync.reactor.BlocksyncReactor`` (pool scheduling,
adaptive timeouts/bans/probes, the fused-prefetch verify window) for a
late joiner, with every request and block response riding
``SimNetwork.schedule_transfer`` through the same faulty, bandwidth-shaped
links as gossip — closing ROADMAP 6(b): blocksync was the last reactor
outside the deterministic fault envelope.

Shape (mirrors the statesync join path in ``cluster._statesync_join``,
store-first): the harness assembles the joiner's stores/app/BlockExecutor
standalone, lets the reactor download + verify + apply blocks on the
virtual clock (one ``reactor.tick()`` per repeating clock timer), and only
when the reactor declares itself caught up does the cluster ``_build`` a
full node over the populated db and start its consensus
(``InvariantChecker.on_join`` exempts blocksync-applied heights from the
WAL #ENDHEIGHT check, exactly like statesync-restored ones).

Fault scripting hooks, driven by scenario actions:
  * ``set_mute(src)``    — helper ``src`` goes quiet: block requests to it
    vanish in its NIC (the joiner's adaptive timeout must expire, ban,
    then half-open probe it once unmuted).
  * ``set_tamper(src)``  — helper ``src`` serves blocks whose BODY is
    forged after signing (the header keeps its legitimate commit — only
    ``validate_block`` catches it, taking the redo→ban path).
  * ``crash()``/``cluster.blocksync_restart`` — the joiner process dies
    mid-catchup; its stores survive, and a fresh harness resumes from
    ``block_store.height() + 1`` after an app-replay handshake, the same
    boot a real node does.
"""

from __future__ import annotations

import random
from typing import Optional

from cometbft_tpu.blocksync import stats as bstats
from cometbft_tpu.blocksync.reactor import (
    BLOCKSYNC_CHANNEL,
    _MSG_BLOCK_RESPONSE,
    BlocksyncReactor,
    _enc,
)
from cometbft_tpu.libs import log as liblog

# One reactor scheduler pass per this many virtual seconds (the wall-clock
# loop polls every 20 ms; virtual ticks are free, so a slightly coarser
# cadence keeps the event count down without starving the window).
TICK_INTERVAL = 0.05


class _TraceLogger(liblog.Logger):
    """Routes pool/reactor log lines into the cluster's byte-compared
    trace, so every ban / probe / stall-switch / re-admission is part of
    the determinism contract the soak matrix enforces."""

    def __init__(self, cluster, index: int):
        super().__init__(level=liblog.INFO)
        self._cluster = cluster
        self._index = index

    def _log(self, level: int, msg: str, kv: dict) -> None:
        if level < self.level:
            return
        parts = [msg] + ["%s=%s" % (k, v) for k, v in kv.items()]
        self._cluster._log("bsync node%d %s" % (self._index, " ".join(parts)))


class _JoinerPeer:
    """The joiner as seen by a serving helper: replies ride the fabric
    back (src = helper, dst = joiner), bandwidth-shaped by payload size."""

    def __init__(self, harness, src: int):
        self._h = harness
        self._src = src
        self.id = "joiner%d" % harness.index

    def try_send(self, chan_id: int, msg_bytes: bytes) -> bool:
        h = self._h
        if h.closed:
            return False

        def deliver(m=msg_bytes, s=self._src) -> None:
            if h.closed or h.reactor is None:
                return
            h.reactor.receive(BLOCKSYNC_CHANNEL, h.peers[s], m)

        return h.cluster.net.schedule_transfer(
            self._src,
            h.index,
            deliver,
            label="bsync-resp",
            size_bytes=len(msg_bytes),
        )


class _HelperPeer:
    """A serving helper as seen by the joiner's reactor/pool: requests
    ride the fabric out (src = joiner, dst = helper) and are answered by
    the helper's OWN serving reactor over its live block store."""

    def __init__(self, harness, src: int):
        self._h = harness
        self._src = src
        self.id = "node%d" % src

    def try_send(self, chan_id: int, msg_bytes: bytes) -> bool:
        h = self._h
        src = self._src
        if h.cluster.nodes[src] is None:
            return False  # helper is down: the dial itself fails

        def deliver(m=msg_bytes, s=src) -> None:
            if h.closed:
                return
            node = h.cluster.nodes[s]
            if node is None or h.muted.get(s):
                return  # crashed or wedged helper: the request vanishes
            serve = h.servers.get(s)
            if serve is not None:
                serve.receive(BLOCKSYNC_CHANNEL, h.joiner_views[s], m)

        return h.cluster.net.schedule_transfer(
            h.index,
            src,
            deliver,
            label="bsync-req",
            size_bytes=len(msg_bytes),
        )


class _FakeSwitch:
    """Just enough of ``p2p.Switch`` for the joiner's reactor: peer lookup,
    status broadcast, and the bad-peer disconnect (which the sim logs but
    keeps connected — re-dials are instant here, and keeping the peer is
    what exercises the ban→probe→re-admission arc)."""

    def __init__(self, harness):
        self._h = harness

    @property
    def peers(self) -> dict:
        # id -> peer view, like p2p.Switch.peers (the reactor's status
        # retry enumerates it for range-less peers)
        return {p.id: p for p in self._h.peers.values()}

    def get_peer(self, peer_id: str):
        for p in self._h.peers.values():
            if p.id == peer_id:
                return p
        return None

    def broadcast(self, chan_id: int, msg_bytes: bytes) -> None:
        for p in self._h.peers.values():
            p.try_send(chan_id, msg_bytes)

    def stop_peer_for_error(self, peer, err) -> None:
        self._h.log.info("peer errored", peer=peer.id, err=str(err))


def _tamper_block_response(msg_bytes: bytes) -> bytes:
    """Forge the BODY of a served block after signing: decode, swap the
    txs, re-encode.  The wire-carried header (and its commit in the NEXT
    block) stays legitimately signed, so ``verify_commit_light`` passes
    and only ``validate_block``'s body-vs-header check can catch it —
    the exact attack internal/blocksync/reactor.go:546 defends against."""
    from cometbft_tpu.libs import protoenc as pe
    from cometbft_tpu.types import codec

    kind, body = msg_bytes[0], msg_bytes[1:]
    if kind != _MSG_BLOCK_RESPONSE:
        return msg_bytes
    f = pe.fields_dict(body)
    block = codec.decode_block(f[1][-1])
    block.data.txs = list(block.data.txs) + [b"forged-tx"]
    out = pe.t_message(1, codec.encode_block(block), always=True)
    if 2 in f:
        out += pe.t_message(2, f[2][-1], always=True)
    return _enc(_MSG_BLOCK_RESPONSE, out)


class SimBlocksync:
    """One joiner's blocksync session on the virtual clock."""

    def __init__(self, cluster, index: int, helper_indices: list[int]):
        self.cluster = cluster
        self.index = index
        self.helper_indices = list(helper_indices)
        self.closed = False
        self.muted: dict[int, bool] = {}
        self.tampered: dict[int, bool] = {}
        self.log = _TraceLogger(cluster, index)
        self._timer = None
        # A real joiner is a fresh process with a COLD signature cache —
        # in-process the global cache is pre-warmed by the validators' own
        # gossip verification, which would mask every fused-prefetch
        # dispatch the catchup path owes.  Clearing is deterministic: the
        # validators re-warm it on their next verifies, all on the
        # virtual clock.
        from cometbft_tpu.crypto import sigcache as _sigcache

        _sigcache.get_cache().clear()
        self._dispatches_at_start = self._dispatch_count()
        self._build_joiner_side()
        self._build_serving_side()
        self._schedule_tick()

    # -- assembly ----------------------------------------------------------

    def _build_joiner_side(self) -> None:
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.config.config import MempoolConfig
        from cometbft_tpu.consensus.replay import Handshaker
        from cometbft_tpu.evidence.pool import EvidencePool
        from cometbft_tpu.mempool.clist_mempool import CListMempool
        from cometbft_tpu.proxy.multi_app_conn import (
            AppConns,
            local_client_creator,
        )
        from cometbft_tpu.state.execution import BlockExecutor
        from cometbft_tpu.state.state import state_from_genesis
        from cometbft_tpu.state.store import StateStore
        from cometbft_tpu.store.block_store import BlockStore
        from cometbft_tpu.store.kv import MemKV
        from cometbft_tpu.types.events import EventBus

        c = self.cluster
        db = c._dbs[self.index]
        if db is None:
            db = MemKV()
            c._dbs[self.index] = db
        self.app = (
            c.app_factory() if c.app_factory is not None
            else KVStoreApplication()
        )
        self.conns = AppConns(local_client_creator(self.app))
        self.conns.start()
        state_store = StateStore(db)
        block_store = BlockStore(db)
        event_bus = EventBus()
        evidence_pool = EvidencePool(db, state_store, block_store)
        state = state_store.load()
        if state is None:
            state = state_from_genesis(c.gdoc)
        handshaker = Handshaker(
            state_store,
            block_store,
            c.gdoc,
            event_bus=event_bus,
            evidence_pool=evidence_pool,
        )
        # fresh joiner: InitChain; crash-restart resume: app replay up to
        # the store height — the same boot path a real node takes
        state = handshaker.handshake(state, self.conns)
        evidence_pool.state = state
        info = self.conns.query.info()
        mempool = CListMempool(
            c.mempool_config or MempoolConfig(recheck=False),
            self.conns.mempool,
            height=state.last_block_height,
            lane_priorities=dict(info.lane_priorities),
            default_lane=info.default_lane,
        )
        block_exec = BlockExecutor(
            state_store,
            block_store,
            self.conns.consensus,
            mempool,
            evidence_pool=evidence_pool,
            event_bus=event_bus,
        )
        self.reactor = BlocksyncReactor(
            state,
            block_exec,
            block_store,
            enabled=True,
            logger=self.log,
            clock=self.cluster.clock,
            # private stream: a join must not perturb the fabric's rng
            rng=random.Random((self.cluster.seed << 16) ^ (0xB5 + self.index)),
        )
        self.reactor.switch = _FakeSwitch(self)
        self.block_store = block_store

    def _build_serving_side(self) -> None:
        c = self.cluster
        self.peers: dict[int, _HelperPeer] = {}
        self.joiner_views: dict[int, "_TamperingJoinerPeer | _JoinerPeer"] = {}
        self.servers: dict[int, BlocksyncReactor] = {}
        for src in self.helper_indices:
            node = c.nodes[src]
            if node is None:
                continue
            self.peers[src] = _HelperPeer(self, src)
            self.joiner_views[src] = _TamperingJoinerPeer(self, src)
            # a serving-only reactor over the helper's live stores: never
            # started, never syncing — only its receive() serve path runs
            self.servers[src] = BlocksyncReactor(
                node.cs.state,
                None,
                node.block_store,
                enabled=False,
                clock=c.clock,
            )
        for p in self.peers.values():
            # announce the joiner and ask for ranges, like Switch.add_peer
            self.reactor.add_peer(p)

    # -- fault scripting ---------------------------------------------------

    def set_mute(self, src: int, on: bool = True) -> None:
        self.muted[src] = on
        self.cluster._log(
            "bsync node%d helper node%d %s"
            % (self.index, src, "muted" if on else "unmuted")
        )

    def set_tamper(self, src: int, on: bool = True) -> None:
        self.tampered[src] = on
        self.cluster._log(
            "bsync node%d helper node%d tamper=%s" % (self.index, src, on)
        )

    # -- the drive loop ----------------------------------------------------

    def _schedule_tick(self) -> None:
        self._timer = self.cluster.clock.call_later(
            TICK_INTERVAL, self._tick, label="bsync node%d" % self.index
        )

    @staticmethod
    def _dispatch_count() -> int:
        from cometbft_tpu.ops import dispatch_stats

        return int(dispatch_stats.snapshot().get("dispatches", 0))

    def _tick(self) -> None:
        if self.closed:
            return
        r = self.reactor
        try:
            progressed = r.tick()
            # drain the received window in this tick: block application is
            # host work, not fabric time
            while r.syncing and progressed:
                progressed = r._process_blocks()
        except Exception as e:  # noqa: BLE001 — surface, don't wedge the sim
            self.log.error("blocksync tick failed", err=repr(e))
        if not r.syncing:
            self._complete()
            return
        self._schedule_tick()

    def _complete(self) -> None:
        s = bstats.snapshot()
        self.cluster._log(
            "bsync node%d complete h=%d dispatches=%d reqs=%d timeouts=%d "
            "bans=%d probes=%d readmits=%d stalls=%d redos=%d"
            % (
                self.index,
                self.block_store.height(),
                self._dispatch_count() - self._dispatches_at_start,
                s["requests"],
                s["timeouts"],
                s["bans"],
                s["probes"],
                s["probe_passes"],
                s["stall_switches"],
                s["redos"],
            )
        )
        self.closed = True
        self._timer = None
        self.cluster._finish_blocksync_join(self)

    def close(self) -> None:
        """Quiet teardown (cluster.stop with a sync still in flight)."""
        if self.closed:
            return
        self.closed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self.conns.stop()

    def crash(self) -> None:
        """Kill the joiner mid-catchup: harness state dies, stores (the
        MemKV in ``cluster._dbs``) survive for a restart."""
        if self.closed:
            return
        h = self.block_store.height()
        self.close()
        self.cluster._log(
            "bsync node%d crashed mid-catchup h=%d" % (self.index, h)
        )


class _TamperingJoinerPeer(_JoinerPeer):
    """Joiner view handed to a helper's serving reactor: applies the
    scripted body-forgery before the response enters the fabric."""

    def try_send(self, chan_id: int, msg_bytes: bytes) -> bool:
        if self._h.tampered.get(self._src):
            msg_bytes = _tamper_block_response(msg_bytes)
        return super().try_send(chan_id, msg_bytes)
