"""Minimal protobuf (proto3) wire-format encoding helpers.

The framework defines its wire messages in code with these primitives instead
of a codegen pipeline: deterministic, dependency-free, and sufficient for
canonical sign-bytes (reference: types/canonical.go:57 — votes/proposals are
signed over a deterministic protobuf encoding, so byte-stable encoding is
consensus-critical).

proto3 semantics: scalar fields equal to their zero value are omitted.
"""

from __future__ import annotations

# Wire types
VARINT = 0
FIXED64 = 1
BYTES = 2
FIXED32 = 5


def uvarint(n: int) -> bytes:
    if n < 0:
        raise ValueError("uvarint requires n >= 0")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_uvarint(data: bytes, pos: int = 0) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def tag(field: int, wire: int) -> bytes:
    return uvarint((field << 3) | wire)


def t_varint(field: int, value: int) -> bytes:
    """int64/uint64 varint field; omitted when zero.  Negative values use the
    proto3 int64 two's-complement 10-byte encoding."""
    if value == 0:
        return b""
    if value < 0:
        value &= (1 << 64) - 1
    return tag(field, VARINT) + uvarint(value)


def t_sfixed64(field: int, value: int) -> bytes:
    if value == 0:
        return b""
    return tag(field, FIXED64) + (value & ((1 << 64) - 1)).to_bytes(8, "little")


def t_bytes(field: int, value: bytes) -> bytes:
    if not value:
        return b""
    return tag(field, BYTES) + uvarint(len(value)) + value


def t_string(field: int, value: str) -> bytes:
    return t_bytes(field, value.encode())


def t_message(field: int, encoded: bytes, *, always: bool = False) -> bytes:
    """Embedded message; omitted when empty unless ``always`` (present-but-
    empty submessages are meaningful in canonical encodings)."""
    if not encoded and not always:
        return b""
    return tag(field, BYTES) + uvarint(len(encoded)) + encoded


def length_prefixed(encoded: bytes) -> bytes:
    """protoio delimited framing: uvarint length prefix (reference:
    libs/protoio — sign bytes are the delimited encoding)."""
    return uvarint(len(encoded)) + encoded


def iter_fields(data: bytes):
    """Yield (field_number, wire_type, value) over a proto message body.

    value is: int for VARINT, bytes for BYTES, 8-byte little-endian int for
    FIXED64, 4-byte for FIXED32.
    """
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = decode_uvarint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == VARINT:
            value, pos = decode_uvarint(data, pos)
        elif wire == BYTES:
            ln, pos = decode_uvarint(data, pos)
            if pos + ln > n:
                raise ValueError("truncated bytes field")
            value = data[pos : pos + ln]
            pos += ln
        elif wire == FIXED64:
            if pos + 8 > n:
                raise ValueError("truncated fixed64")
            value = int.from_bytes(data[pos : pos + 8], "little")
            pos += 8
        elif wire == FIXED32:
            if pos + 4 > n:
                raise ValueError("truncated fixed32")
            value = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def fields_dict(data: bytes) -> dict[int, list]:
    out: dict[int, list] = {}
    for field, _, value in iter_fields(data):
        out.setdefault(field, []).append(value)
    return out


def to_int64(v: int) -> int:
    """Interpret a varint as a signed int64 (two's complement)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def sfixed64_to_int(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v
