"""Process-wide disk-fault supervisor: the durable-IO seam.

The supervisor chain (docs/backend-supervisor.md) guarantees that COMPUTE
infrastructure failures never become wrong verdicts; this module extends
the same invariant to the STORAGE plane.  Every durable write / fsync /
rename in the codebase — the consensus WAL, the privval last-sign state,
the SqliteKV chain store, the black-box journal, the exec cache, the
indexer, chip-watch status files — routes through one guarded seam that
applies:

  * a deterministic IO fault injector (``FaultPlan``: ENOSPC / EIO /
    torn-write-then-crash / slow-disk latency, count-windowed rules the
    sim scripts drive on the virtual clock — same-seed runs consume the
    same rule windows on the same operations, byte-deterministically);

  * an explicit per-surface durability policy:

      - **fail-stop** surfaces (``wal``, ``privval``, ``state``): an IO
        failure raises a typed ``StorageFatal`` that halts the node
        BEFORE it can vote or commit on unpersisted state — equivocation
        is the one fault BFT cannot forgive, so a validator that cannot
        persist its sign-state or WAL must stop, not guess.  The failure
        is journaled as a ``disk_fatal`` anomaly with surface / op /
        errno attribution.

      - **degradable** surfaces (``blackbox``, ``exec_cache``,
        ``indexer``, ``status``): an IO failure degrades to counted
        drops — transient errors (EIO and friends) get a bounded
        exponential-backoff retry first — and never touches consensus.
        The original ``OSError`` is re-raised after the retry budget so
        each surface's existing local degrade handler (the blackbox
        writer's drop counter, the exec cache's ``unwritable`` status)
        keeps working; the guard adds injection, retries, per-surface
        stats (``libs/storage_stats``) and a ``disk_fault`` anomaly.

Kill switch: ``COMETBFT_TPU_DISKGUARD=0`` makes every guard a direct
pass-through (no injection, no retries, no stats, no boot-time WAL tail
repair) — current behavior restored bit-for-bit.

Deliberately jax-free, like ``libs/tracing``: the storage plane must
keep its safety argument exactly when the accelerator stack is the thing
that fell over.  docs/storage-robustness.md is the design note;
``scripts/check_diskpolicy.py`` lints that new durable-IO call sites use
this seam instead of raw ``open``/``os.fsync``/``os.replace``.
"""

from __future__ import annotations

import errno as _errno
import os
import tempfile
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Optional

from cometbft_tpu.libs import storage_stats

# -- surfaces & policy --------------------------------------------------------

FAIL_STOP = "fail-stop"
DEGRADE = "degrade"

#: surface -> durability policy (docs/storage-robustness.md).  Unknown
#: surfaces default to DEGRADE: a new subsystem must opt IN to halting
#: the node, never get it by accident.
POLICIES: dict = {
    "wal": FAIL_STOP,       # consensus replay correctness
    "privval": FAIL_STOP,   # double-sign protection
    "state": FAIL_STOP,     # block/state store (commit durability)
    "blackbox": DEGRADE,    # forensics must never be a second failure
    "exec_cache": DEGRADE,  # losing the cache loses an optimization
    "indexer": DEGRADE,     # query-side convenience, not consensus
    "status": DEGRADE,      # chip-watch / operator status files
    "light": DEGRADE,       # light-client trust cache (re-verifiable)
}

#: errnos treated as transient on degradable surfaces (retried with
#: exponential backoff before the op degrades to a counted drop).
#: ENOSPC is deliberately absent — a full disk does not heal in
#: milliseconds, retrying it only burns the budget.
TRANSIENT_ERRNOS = frozenset(
    (_errno.EIO, _errno.EAGAIN, _errno.EINTR, _errno.EBUSY)
)

DEFAULT_RETRIES = 3
DEFAULT_BACKOFF_MS = 1.0
DEFAULT_BACKOFF_MAX_MS = 50.0


class StorageFatal(OSError):
    """An IO failure on a fail-stop surface.  Whoever catches this must
    HALT the node — the persistent state backing consensus safety can no
    longer be trusted to advance."""

    def __init__(self, surface: str, op: str, err: "BaseException | str"):
        self.surface = surface
        self.op = op
        self.err = err
        self.io_errno = getattr(err, "errno", None)
        super().__init__(
            f"storage fatal on {surface}/{op}: {err!r}"
        )


def enabled() -> bool:
    """``COMETBFT_TPU_DISKGUARD=0`` is the kill switch; default on.
    With it off every guard is a direct pass-through — no injection, no
    retry, no stats, no boot-time repair — bit-for-bit the pre-diskguard
    behavior."""
    return os.environ.get("COMETBFT_TPU_DISKGUARD", "1") != "0"


def policy(surface: str) -> str:
    return POLICIES.get(surface, DEGRADE)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def retries() -> int:
    return max(int(_env_float("COMETBFT_TPU_DISKGUARD_RETRIES", DEFAULT_RETRIES)), 0)


def _backoff_s(attempt: int) -> float:
    base = _env_float("COMETBFT_TPU_DISKGUARD_BACKOFF_MS", DEFAULT_BACKOFF_MS)
    cap = _env_float(
        "COMETBFT_TPU_DISKGUARD_BACKOFF_MAX_MS", DEFAULT_BACKOFF_MAX_MS
    )
    return min(base * (2.0 ** attempt), cap) / 1000.0


# the retry backoff sleeper: wall sleep by default; the sim swaps in a
# no-op so retries stay a pure function of the injector's count windows
# instead of coupling virtual time to wall time
_SLEEPER: "list[Callable[[float], None]]" = [_time.sleep]


def set_sleeper(fn: Optional[Callable[[float], None]]) -> None:
    _SLEEPER[0] = fn if fn is not None else _time.sleep


def sleep_backoff(attempt: int) -> None:
    """One step of the seam's bounded exponential backoff, through the
    sim-swappable sleeper — for surface-local retry loops (e.g. sqlite
    lock contention) that back off like the guard does."""
    _SLEEPER[0](_backoff_s(attempt))


# -- deterministic fault injection -------------------------------------------

KIND_ERRNO = "errno"
KIND_TORN = "torn"
KIND_LATENCY = "latency"


@dataclass
class FaultRule:
    """One scripted fault window.  The rule counts every guarded
    operation matching (surface, op, path_substr); it FIRES while the
    match ordinal lies in ``[begin, begin + count)``.  Count-windowed
    matching is what makes injection deterministic: the sequence of
    guarded operations is a pure function of the seed, so the same runs
    trip the same faults regardless of wall-clock scheduling."""

    surface: Optional[str] = None      # None matches every surface
    op: Optional[str] = None           # None matches every op
    path_substr: Optional[str] = None  # substring of the target path
    kind: str = KIND_ERRNO
    err: int = _errno.EIO
    begin: int = 0
    count: float = float("inf")
    latency_s: float = 0.0
    torn_keep: int = 8                 # bytes of the payload that land
    seen: int = field(default=0, compare=False)

    def matches(self, surface: str, op: str, path: Optional[str]) -> bool:
        if self.surface is not None and self.surface != surface:
            return False
        if self.op is not None and self.op != op:
            return False
        if self.path_substr is not None and (
            path is None or self.path_substr not in path
        ):
            return False
        return True


class FaultPlan:
    """A live set of fault rules.  Thread-safe; scenario actions add and
    remove rules at scripted virtual times."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: "list[FaultRule]" = []

    def add(self, **kw) -> FaultRule:
        rule = FaultRule(**kw)
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove(self, rule: FaultRule) -> None:
        with self._lock:
            try:
                self._rules.remove(rule)
            except ValueError:
                pass

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()

    def check(
        self, surface: str, op: str, path: Optional[str]
    ) -> Optional[FaultRule]:
        """Advance every matching rule's ordinal; return the first rule
        whose window covers this operation (or None)."""
        with self._lock:
            fired = None
            for rule in self._rules:
                if not rule.matches(surface, op, path):
                    continue
                idx = rule.seen
                rule.seen += 1
                if fired is None and rule.begin <= idx < rule.begin + rule.count:
                    fired = rule
            return fired


_PLAN: "list[Optional[FaultPlan]]" = [None]


def set_fault_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    prev = _PLAN[0]
    _PLAN[0] = plan
    return prev


def get_fault_plan() -> Optional[FaultPlan]:
    return _PLAN[0]


def clear_fault_plan() -> None:
    _PLAN[0] = None


# -- anomaly reporting (re-entrancy latched) ----------------------------------

_TLS = threading.local()


def _anomaly(kind: str, **attrs) -> None:
    """Record a flight-recorder anomaly WITHOUT recursing: the black-box
    journal persists anomalies through this very seam, so a blackbox
    write failure's anomaly must not re-enter the guard's anomaly path
    when the journal write for it fails too."""
    if getattr(_TLS, "in_anomaly", False):
        return
    _TLS.in_anomaly = True
    try:
        from cometbft_tpu.libs import tracing

        tracing.record_anomaly(kind, **attrs)
    except Exception:  # noqa: BLE001 — reporting must never add a failure
        pass
    finally:
        _TLS.in_anomaly = False


def _err_attrs(err: BaseException) -> dict:
    code = getattr(err, "errno", None)
    return {
        "errno": code if code is not None else -1,
        "error": type(err).__name__,
    }


# -- the guard ----------------------------------------------------------------


def guard(
    surface: str,
    op: str,
    thunk: Callable[[], object],
    path: Optional[str] = None,
    exc_types: tuple = (OSError,),
    tear: Optional[Callable[[FaultRule], None]] = None,
):
    """Run one durable-IO operation under the disk-fault policy.

    Fail-stop surfaces raise ``StorageFatal`` on the first failure;
    degradable surfaces retry transient errors with bounded exponential
    backoff, then record a counted drop + ``disk_fault`` anomaly and
    re-raise the original error for the caller's local degrade handler.
    ``tear`` lets byte-writers land a torn prefix before the injected
    crash (``file_write`` wires it; thunk-level callers skip it)."""
    if not enabled():
        return thunk()
    degrade = policy(surface) == DEGRADE
    budget = retries() if degrade else 0
    attempt = 0
    while True:
        err: Optional[BaseException] = None
        torn = False
        plan = _PLAN[0]
        rule = plan.check(surface, op, path) if plan is not None else None
        if rule is not None:
            if rule.kind == KIND_LATENCY:
                storage_stats.record_injected(surface)
                _SLEEPER[0](rule.latency_s)
                rule = None  # slow, not broken: the op itself proceeds
            elif rule.kind == KIND_TORN:
                storage_stats.record_injected(surface)
                if tear is not None:
                    try:
                        tear(rule)
                    except OSError:
                        pass
                # a torn write models a CRASH, not a transient error: it
                # is never retried — a retry would land the full payload
                # after the flushed torn prefix (mid-stream garbage no
                # real crash leaves behind)
                torn = True
                err = OSError(rule.err, "injected torn write")
            else:
                storage_stats.record_injected(surface)
                err = OSError(rule.err, "injected " + os.strerror(rule.err))
        if err is None:
            try:
                result = thunk()
            except StorageFatal:
                raise
            except exc_types as e:
                err = e
            else:
                storage_stats.record_op(surface, op)
                return result
        if not degrade:
            storage_stats.record_fatal(surface)
            _anomaly(
                "disk_fatal", surface=surface, op=op, **_err_attrs(err)
            )
            raise StorageFatal(surface, op, err) from err
        code = getattr(err, "errno", None)
        if attempt < budget and code in TRANSIENT_ERRNOS and not torn:
            attempt += 1
            storage_stats.record_retry(surface)
            _SLEEPER[0](_backoff_s(attempt - 1))
            continue
        storage_stats.record_drop(surface)
        _anomaly("disk_fault", surface=surface, op=op, **_err_attrs(err))
        raise err


def file_write(
    surface: str, f, data: bytes, op: str = "write", path: Optional[str] = None
) -> None:
    """Guarded ``f.write(data)`` — the byte-level write seam.  Supports
    torn-write injection: a ``torn`` rule lands ``torn_keep`` bytes of
    the payload (flushed, so they are really on disk) before raising —
    exactly the mid-frame tail a crashed process leaves behind."""

    def tear(rule: FaultRule) -> None:
        keep = max(min(rule.torn_keep, len(data) - 1), 0)
        if keep:
            f.write(data[:keep])
        f.flush()

    guard(surface, op, lambda: f.write(data), path=path, tear=tear)


def fsync(surface: str, f, path: Optional[str] = None) -> None:
    """Guarded ``os.fsync(f.fileno())``."""
    guard(surface, "fsync", lambda: os.fsync(f.fileno()), path=path)


def flush(surface: str, f, path: Optional[str] = None) -> None:
    """Guarded ``f.flush()``."""
    guard(surface, "flush", f.flush, path=path)


def replace(surface: str, src: str, dst: str) -> None:
    """Guarded ``os.replace(src, dst)`` (atomic publish)."""
    guard(surface, "replace", lambda: os.replace(src, dst), path=dst)


def atomic_write(
    surface: str, path: str, data: bytes, do_fsync: bool = True
) -> None:
    """Write-temp / (flush+fsync) / rename-into-place, each step guarded.
    Readers only ever see the old file or the complete new one; a torn
    or failed write leaves only an unlinked temp behind."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    # the ".tmp" suffix marks abandoned temps (a killed writer) for the
    # surfaces' own GC sweeps (e.g. aot_cache.evict_stale)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            file_write(surface, f, data, op="write", path=path)
            flush(surface, f, path=path)
            if do_fsync:
                fsync(surface, f, path=path)
        replace(surface, tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise
