"""Event pubsub server with a query language (reference: libs/pubsub).

Subscribers register a ``Query`` (same grammar as the reference's
``libs/pubsub/query``: ``tm.event='NewBlock' AND tx.height > 5``); published
messages carry a tag map ``{key: [values...]}`` and are delivered to every
subscription whose query matches.  Delivery is via per-subscription bounded
queues drained by the subscriber (reference: pubsub.Server, out channels).

Query grammar (libs/pubsub/query/syntax):
  condition  := tag op operand
  op         := '=' | '<' | '<=' | '>' | '>=' | CONTAINS | EXISTS
  operand    := 'string' | number | TIME t | DATE d
  query      := condition (AND condition)*
"""

from __future__ import annotations

import queue
import re
import threading

from cometbft_tpu.libs import sync as libsync
from dataclasses import dataclass, field
from typing import Any, Optional


class QueryError(Exception):
    pass


_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<and>AND\b)
      | (?P<contains>CONTAINS\b)
      | (?P<exists>EXISTS\b)
      | (?P<op><=|>=|=|<|>)
      | (?P<str>'(?:[^'\\]|\\.)*')
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<tag>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)


@dataclass(frozen=True)
class Condition:
    tag: str
    op: str  # '=', '<', '<=', '>', '>=', 'CONTAINS', 'EXISTS'
    operand: Any = None

    def matches(self, tags: dict[str, list[str]]) -> bool:
        vals = tags.get(self.tag)
        if vals is None:
            return False
        if self.op == "EXISTS":
            return True
        for v in vals:
            if self.op == "=":
                if isinstance(self.operand, (int, float)):
                    try:
                        if float(v) == float(self.operand):
                            return True
                    except ValueError:
                        pass
                elif v == self.operand:
                    return True
            elif self.op == "CONTAINS":
                if str(self.operand) in v:
                    return True
            else:
                try:
                    fv, fo = float(v), float(self.operand)
                except (ValueError, TypeError):
                    continue
                if (
                    (self.op == "<" and fv < fo)
                    or (self.op == "<=" and fv <= fo)
                    or (self.op == ">" and fv > fo)
                    or (self.op == ">=" and fv >= fo)
                ):
                    return True
        return False


class Query:
    """Conjunction of conditions (the reference grammar has no OR)."""

    def __init__(self, conditions: list[Condition], source: str = ""):
        self.conditions = conditions
        self.source = source

    @staticmethod
    def parse(s: str) -> "Query":
        tokens: list[tuple[str, str]] = []
        pos = 0
        while pos < len(s):
            m = _TOKEN_RE.match(s, pos)
            if not m or m.end() == pos:
                if s[pos:].strip():
                    raise QueryError(f"syntax error at {s[pos:]!r}")
                break
            pos = m.end()
            kind = m.lastgroup
            tokens.append((kind, m.group(kind)))

        conds: list[Condition] = []
        i = 0
        while i < len(tokens):
            if conds:
                if tokens[i][0] != "and":
                    raise QueryError(f"expected AND, got {tokens[i][1]!r}")
                i += 1
            if i >= len(tokens) or tokens[i][0] != "tag":
                raise QueryError("expected tag name")
            tag = tokens[i][1]
            i += 1
            if i >= len(tokens):
                raise QueryError("expected operator")
            kind, val = tokens[i]
            i += 1
            if kind == "exists":
                conds.append(Condition(tag, "EXISTS"))
                continue
            if kind == "contains":
                if i >= len(tokens) or tokens[i][0] != "str":
                    raise QueryError("CONTAINS requires a string")
                conds.append(Condition(tag, "CONTAINS", _unquote(tokens[i][1])))
                i += 1
                continue
            if kind != "op":
                raise QueryError(f"expected operator, got {val!r}")
            if i >= len(tokens):
                raise QueryError("expected operand")
            okind, oval = tokens[i]
            i += 1
            if okind == "str":
                operand: Any = _unquote(oval)
            elif okind == "num":
                operand = float(oval) if "." in oval else int(oval)
            else:
                raise QueryError(f"bad operand {oval!r}")
            conds.append(Condition(tag, val, operand))
        if not conds:
            raise QueryError("empty query")
        return Query(conds, source=s)

    def matches(self, tags: dict[str, list[str]]) -> bool:
        return all(c.matches(tags) for c in self.conditions)

    def __str__(self) -> str:
        return self.source

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self.conditions == other.conditions

    def __hash__(self) -> int:
        return hash(tuple(self.conditions))


def _unquote(s: str) -> str:
    return s[1:-1].replace("\\'", "'")


EMPTY_QUERY = Query([Condition("", "EXISTS")])
EMPTY_QUERY.matches = lambda tags: True  # type: ignore[method-assign]


@dataclass
class Message:
    data: Any
    tags: dict[str, list[str]] = field(default_factory=dict)


class Subscription:
    """A bounded delivery queue; ``canceled`` is set on unsubscribe with the
    reason (reference: pubsub.Subscription.Canceled)."""

    def __init__(self, query: Query, capacity: int = 100):
        self.query = query
        self.out: queue.Queue[Message] = queue.Queue(maxsize=capacity)
        self.canceled = threading.Event()
        self.cancel_reason: str = ""

    def next(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            return self.out.get(timeout=timeout)
        except queue.Empty:
            return None


class PubSubServer:
    """Reference: libs/pubsub/pubsub.go Server."""

    def __init__(self):
        self._subs: dict[tuple[str, str], Subscription] = {}
        self._mtx = libsync.rlock("pubsub")

    def subscribe(
        self, subscriber: str, query: Query, capacity: int = 100
    ) -> Subscription:
        key = (subscriber, str(query))
        with self._mtx:
            if key in self._subs:
                raise ValueError("already subscribed")
            sub = Subscription(query, capacity)
            self._subs[key] = sub
            return sub

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        self._cancel((subscriber, str(query)), "unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            keys = [k for k in self._subs if k[0] == subscriber]
        for k in keys:
            self._cancel(k, "unsubscribed")

    def _cancel(self, key: tuple[str, str], reason: str) -> None:
        with self._mtx:
            sub = self._subs.pop(key, None)
        if sub is not None:
            sub.cancel_reason = reason
            sub.canceled.set()

    def publish(self, data: Any, tags: Optional[dict[str, list[str]]] = None):
        tags = tags or {}
        with self._mtx:
            subs = list(self._subs.items())
        for key, sub in subs:
            if sub.query.matches(tags):
                try:
                    sub.out.put_nowait(Message(data, tags))
                except queue.Full:
                    # Slow subscriber: cancel it (reference drops/cancels
                    # depending on config; cancel is the safe default).
                    self._cancel(key, "client was not pulling messages fast enough")

    def num_clients(self) -> int:
        with self._mtx:
            return len({k[0] for k in self._subs})

    def num_subscriptions(self) -> int:
        with self._mtx:
            return len(self._subs)
