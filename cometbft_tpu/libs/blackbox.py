"""Crash-persistent black box: a durable journal under the flight recorder.

The flight recorder (libs/tracing.py) is a RAM ring: spans, anomaly
counters and breaker history all die with the process, and a SIGKILL'd or
wedged node — the exact failure the supervisor chain and the sim's
crash-restart scenarios were built for — leaves zero forensic record of
what it was doing.  This module closes that gap with an append-only,
CRC32+length-framed journal (the same framing discipline as
``consensus/wal.py``) fed from the tracer's sinks:

  * every COMPLETED span (batched, buffered writes — the hot path never
    waits on disk),
  * every explicit-span OPEN (``Tracer.begin`` — one per consensus round;
    flushed, so the in-flight round anchor survives a crash),
  * EVERY anomaly (not just the RAM recorder's first-per-kind; fsync'd),
  * breaker state transitions and other low-rate events (quorum arrivals
    on the in-flight round, device-probe up/down transitions; flushed),
  * periodic health snapshots (sched / ingest / dispatch / warmboot
    counters, every ``health_every`` records — count-based so the sim's
    journal bytes stay a pure function of the seed),
  * a clean-close sentinel, written (and fsync'd) only by a graceful
    shutdown — its absence at boot IS the unclean-shutdown detector.

Size discipline: the head segment rotates at ``segment_bytes`` and only
the newest ``segments`` files are kept, so a journal can never exceed
``segments * segment_bytes`` (+ one frame).  In threaded mode a bounded
queue feeds a background writer; when the queue is full the record is
DROPPED AND COUNTED — the verify hot path never blocks on the black box.

Decode is torn-tail tolerant by design: a truncated final record is a
normal crash artifact (``torn_tail``), not corruption; a mid-stream CRC
or length failure is skipped and counted (``corrupt_skipped``) and never
raises past the postmortem boundary.  ``postmortem_report`` reconstructs
a dead node's final timeline from the records: last committed height, the
in-flight ``consensus.round`` anchor with its step spans and quorum
arrivals, open spans at death, the last ``verify.dispatch`` attribution
triple, recent anomalies and last-known breaker states.

Kill switch: ``COMETBFT_TPU_BLACKBOX=0`` disables the journal entirely —
no sinks installed, the RAM-only recorder restored bit-for-bit.

Deliberately jax-free, like ``libs/tracing``: the postmortem CLI and the
boot-time decode must work exactly when the accelerator is the thing that
killed the node.
"""

from __future__ import annotations

import io
import json
import logging
import os
import struct
import threading
import zlib
from collections import deque
from typing import Callable, Iterator, Optional

logger = logging.getLogger("cometbft_tpu.blackbox")

# record kinds (frame body = kind byte + compact sort_keys JSON payload)
REC_SPAN = 1         # a completed span (Span.to_dict())
REC_OPEN = 2         # an explicit span's begin (unfinished at write time)
REC_ANOMALY = 3      # one anomaly occurrence (every one, fsync'd)
REC_EVENT = 4        # low-rate event: breaker transition, quorum, probe…
REC_HEALTH = 5       # periodic pipeline-health counter snapshot
REC_CLEAN_CLOSE = 6  # graceful-shutdown sentinel (absence = unclean)

KIND_NAMES = {
    REC_SPAN: "span",
    REC_OPEN: "open",
    REC_ANOMALY: "anomaly",
    REC_EVENT: "event",
    REC_HEALTH: "health",
    REC_CLEAN_CLOSE: "clean_close",
}

MAX_REC_SIZE = 1 << 20  # 1 MB per record, like the WAL
HEAD_NAME = "blackbox.journal"

DEFAULT_SEGMENTS = 4
DEFAULT_SEGMENT_BYTES = 1 << 20
DEFAULT_QUEUE = 1024
DEFAULT_FLUSH_EVERY = 64
DEFAULT_HEALTH_EVERY = 512


def enabled() -> bool:
    """``COMETBFT_TPU_BLACKBOX=0`` is the kill switch; default on.  With
    it off nothing installs tracer sinks, so the RAM-only recorder
    behaves bit-for-bit as before this module existed."""
    return os.environ.get("COMETBFT_TPU_BLACKBOX", "1") != "0"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def default_segments() -> int:
    return max(_env_int("COMETBFT_TPU_BLACKBOX_SEGMENTS", DEFAULT_SEGMENTS), 1)


def default_segment_bytes() -> int:
    return max(
        _env_int("COMETBFT_TPU_BLACKBOX_SEGMENT_BYTES", DEFAULT_SEGMENT_BYTES),
        4096,
    )


def _frame(kind: int, payload: dict) -> bytes:
    body = bytes([kind]) + json.dumps(
        payload, sort_keys=True, separators=(",", ":")
    ).encode()
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">II", crc, len(body)) + body


class BlackboxJournal:
    """One node's black box.  Thread-safe; ``append`` never blocks on IO
    in threaded mode (full queue → counted drop) and never raises."""

    def __init__(
        self,
        dir_: str,
        segment_bytes: Optional[int] = None,
        segments: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
        threaded: bool = True,
        queue_max: int = DEFAULT_QUEUE,
        flush_every: int = DEFAULT_FLUSH_EVERY,
        health_every: Optional[int] = DEFAULT_HEALTH_EVERY,
        repair: bool = True,
    ):
        import time as _time

        self.dir = str(dir_)
        self.segment_bytes = segment_bytes or default_segment_bytes()
        self.segments = segments or default_segments()
        self.clock: Callable[[], float] = clock or _time.perf_counter
        self.flush_every = max(int(flush_every), 1)
        self.health_every = health_every
        self.queue_max = max(int(queue_max), 1)
        # _qlock guards the queue + drop counter (the only lock the hot
        # path ever takes in threaded mode); _iolock guards the file — the
        # writer thread does its IO under _iolock alone, so a caller can
        # never block behind a disk write.  REENTRANT: a failed journal
        # write's own ``disk_fault`` anomaly (diskguard) sinks back into
        # this journal on the same thread — with a plain Lock that is a
        # self-deadlock; the diskguard anomaly latch bounds the nesting
        # at one level
        self._qlock = threading.Lock()
        self._iolock = threading.RLock()
        self._wake = threading.Condition(self._qlock)
        self._f: Optional[io.BufferedWriter] = None
        self._unflushed = 0
        self.closed = False
        # counters (introspection + soak rows)
        self.records = 0
        self.dropped = 0
        self.bytes_written = 0
        self.rotations = 0
        self._since_health = 0
        os.makedirs(self.dir, exist_ok=True)
        if repair:
            # a previous unclean run may have left a torn tail on the head
            # segment; appending after it would desync every later frame,
            # so truncate back to the last valid frame boundary first (the
            # caller is expected to have read its postmortem already)
            self._repair_head()
        self._open_head()
        self._queue: "deque[tuple[bytes, int]]" = deque()
        self._writer: Optional[threading.Thread] = None
        if threaded:
            self._writer = threading.Thread(
                target=self._writer_loop, name="blackbox-writer", daemon=True
            )
            self._writer.start()

    # -- file management ---------------------------------------------------

    @property
    def head_path(self) -> str:
        return os.path.join(self.dir, HEAD_NAME)

    def _open_head(self) -> None:
        self._f = open(self.head_path, "ab")

    def _repair_head(self) -> None:
        """Truncate ONLY the torn tail past the last valid frame before
        appending (new frames after torn bytes would be swallowed by the
        torn header's bogus length).  Mid-stream corruption that is
        FOLLOWED by valid frames is evidence, not a tail — it stays on
        disk for the decoder's skip-and-count resync."""
        path = self.head_path
        if not os.path.exists(path):
            return
        good = _last_valid_end(path)
        if good < os.path.getsize(path):
            try:
                os.truncate(path, good)
            except OSError:
                pass

    def _rotate_locked(self, incoming: int) -> None:
        if self._f is None or self._f.tell() + incoming <= self.segment_bytes:
            return
        if self._f.tell() == 0:
            return  # oversized single record: let it land alone
        self._f.flush()
        self._f.close()
        base = self.head_path
        # monotonically increasing index — NEVER the lowest free slot: once
        # pruning removes .0000, reusing it would make every newly rolled
        # segment sort as the oldest and be pruned immediately, silently
        # keeping stale history instead of the recent window
        rolled = _rolled_files(self.dir)
        idx = (
            int(rolled[-1][len(base) + 1 :]) + 1 if rolled else 0
        )
        os.rename(base, f"{base}.{idx:04d}")
        self.rotations += 1
        self._open_head()
        self._unflushed = 0
        # hard budget: keep the newest (segments - 1) rolled files
        rolled = _rolled_files(self.dir)
        excess = len(rolled) - (self.segments - 1)
        for fp in rolled[:max(excess, 0)]:
            try:
                os.unlink(fp)
            except OSError:
                pass

    # -- append ------------------------------------------------------------

    # durability classes for the ``sync`` argument
    SYNC_NONE = 0   # buffered; flushed every flush_every records
    SYNC_FLUSH = 1  # flushed to the kernel immediately (survives kill -9)
    SYNC_FSYNC = 2  # flushed + fsync'd (survives power loss)

    def append(self, kind: int, payload: dict, sync: int = SYNC_NONE) -> None:
        """Journal one record.  Encoding happens on the caller's thread
        (cheap, deterministic); IO happens here (sync mode) or on the
        writer thread (threaded mode, never blocking the caller)."""
        if self.closed:
            return
        try:
            frame = _frame(kind, payload)
        except (TypeError, ValueError) as e:
            # an unserializable attr must never break the caller
            logger.warning("blackbox: unserializable record dropped: %r", e)
            with self._qlock:
                self.dropped += 1
            return
        if len(frame) > MAX_REC_SIZE + 9:
            # the decoder rejects bodies past MAX_REC_SIZE as garbage
            # headers; writing one anyway would journal a record no
            # postmortem can read — drop it, counted, like the WAL's cap
            logger.warning(
                "blackbox: %d-byte record exceeds the %d cap; dropped",
                len(frame),
                MAX_REC_SIZE,
            )
            with self._qlock:
                self.dropped += 1
            return
        if self._writer is None:
            with self._iolock:
                self._write_io(frame, sync)
        else:
            with self._qlock:
                if self.closed or len(self._queue) >= self.queue_max:
                    self.dropped += 1
                    return
                self._queue.append((frame, sync))
                self._wake.notify()
            if sync >= self.SYNC_FSYNC:
                # the fsync promise must not wait for the writer thread —
                # a SIGKILL microseconds after a watchdog_fire is exactly
                # the moment the record matters.  The CALLER drains the
                # queue through its own record (anomalies are rare; this
                # is the one journal path allowed to pay IO).  A batch the
                # writer already popped may land just after ours — decode
                # is order-tolerant and the postmortem folds by timestamp.
                with self._iolock:
                    with self._qlock:
                        batch = list(self._queue)
                        self._queue.clear()
                    # our record is the batch's tail; its own SYNC_FSYNC
                    # flushes + fsyncs everything written before it
                    for bframe, bsync in batch:
                        self._write_io(bframe, bsync)
        self._maybe_health()

    def _write_io(self, frame: bytes, sync: int) -> None:
        """One frame to the head segment; caller holds ``_iolock``.

        Write / flush / fsync route through the diskguard seam (surface
        ``blackbox``, DEGRADABLE): transient EIO gets a bounded
        exponential-backoff retry, an exhausted fault degrades to the
        counted drop below plus a ``disk_fault`` anomaly — the writer
        thread survives and later records keep landing."""
        from cometbft_tpu.libs import diskguard as _dg

        if self._f is None:
            self.dropped += 1
            return
        written = False
        try:
            self._rotate_locked(len(frame))
            _dg.file_write(
                "blackbox", self._f, frame, op="write", path=self.head_path
            )
            self.records += 1
            self.bytes_written += len(frame)
            written = True
            self._unflushed += 1
            if sync >= self.SYNC_FLUSH or self._unflushed >= self.flush_every:
                _dg.flush("blackbox", self._f, path=self.head_path)
                self._unflushed = 0
            if sync >= self.SYNC_FSYNC:
                _dg.fsync("blackbox", self._f, path=self.head_path)
        except OSError as e:  # forensics must never become a second failure
            logger.warning("blackbox write failed: %r", e)
            # only a failed WRITE drops the frame; a failed flush/fsync
            # leaves the bytes buffered (a later flush may still land
            # them) and is already counted by the guard's surface stats —
            # records + dropped must never exceed frames submitted
            if not written:
                self.dropped += 1

    def _writer_loop(self) -> None:
        while True:
            with self._qlock:
                while not self._queue and not self.closed:
                    self._wake.wait(timeout=0.5)
                if self.closed:
                    return  # close()/kill() handle whatever remains queued
                batch = list(self._queue)
                self._queue.clear()
            with self._iolock:
                for frame, sync in batch:
                    self._write_io(frame, sync)

    def _maybe_health(self) -> None:
        if not self.health_every:
            return
        with self._qlock:
            self._since_health += 1
            if self._since_health < self.health_every:
                return
            self._since_health = 0
        self.append(REC_HEALTH, health_snapshot(self.clock()))

    # -- tracer sinks ------------------------------------------------------

    def on_span(self, sp) -> None:
        self.append(REC_SPAN, sp.to_dict())

    def on_open(self, sp) -> None:
        d = {
            "stage": sp.stage,
            "span": sp.span_id,
            "trace": sp.trace_id,
            "t0": round(sp.t_start, 9),
        }
        if sp.attrs:
            d["attrs"] = dict(sp.attrs)
        self.append(REC_OPEN, d, sync=self.SYNC_FLUSH)

    def on_anomaly(self, kind: str, attrs: dict, t: float) -> None:
        self.append(
            REC_ANOMALY,
            {"kind": kind, "t": round(t, 9), "attrs": dict(attrs)},
            sync=self.SYNC_FSYNC,
        )

    def on_event(self, kind: str, attrs: dict) -> None:
        self.append(
            REC_EVENT,
            {"kind": kind, "t": round(self.clock(), 9), "attrs": dict(attrs)},
            sync=self.SYNC_FLUSH,
        )

    # -- lifecycle ---------------------------------------------------------

    def close(self, clean: bool = True) -> None:
        """Graceful close.  ``clean=True`` drains the queue and appends
        the fsync'd clean-close sentinel — the record whose absence at the
        next boot means the process died uncleanly."""
        with self._qlock:
            if self.closed:
                return
            self.closed = True
            batch = list(self._queue)
            self._queue.clear()
            self._wake.notify_all()
        if self._writer is not None and self._writer.is_alive():
            self._writer.join(timeout=2.0)
        with self._iolock:
            if clean:
                for frame, sync in batch:
                    self._write_io(frame, sync)
                self._write_io(
                    _frame(REC_CLEAN_CLOSE, {"t": round(self.clock(), 9)}),
                    self.SYNC_FSYNC,
                )
            elif batch:
                with self._qlock:
                    self.dropped += len(batch)
            if self._f is not None:
                try:
                    self._f.flush()
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    def kill(self) -> None:
        """Simulate abrupt process death, the same drop-unflushed-tail
        discipline as ``WAL.kill``: only bytes the kernel already has at
        kill time survive; queued records and the user-space buffered tail
        are lost (a graceful close would flush them and hide real torn
        tails).  The head file is truncated back to its pre-close on-disk
        size, which may cut mid-frame — exactly the torn tail the tolerant
        decoder exists for."""
        with self._qlock:
            if self.closed:
                return
            self.closed = True
            self.dropped += len(self._queue)
            self._queue.clear()
            self._wake.notify_all()
        if self._writer is not None and self._writer.is_alive():
            self._writer.join(timeout=2.0)
        path = self.head_path
        with self._iolock:
            size = os.path.getsize(path) if os.path.exists(path) else 0
            if self._f is not None:
                try:
                    self._f.flush()
                    self._f.close()
                except OSError:
                    pass
                self._f = None
            if os.path.exists(path) and os.path.getsize(path) > size:
                try:
                    os.truncate(path, size)
                except OSError:
                    pass

    def stats(self) -> dict:
        head = 0
        try:
            head = os.path.getsize(self.head_path)
        except OSError:
            pass
        with self._qlock:
            queued, dropped = len(self._queue), self.dropped
        return {
            "records": self.records,
            "bytes": self.bytes_written,
            "dropped": dropped,
            "rotations": self.rotations,
            "segments": len(_rolled_files(self.dir)) + 1,
            "head_bytes": head,
            "queued": queued,
            "closed": self.closed,
        }


def health_snapshot(t: float) -> dict:
    """Pipeline-health counters for a HEALTH record: scheduler, tx-ingest,
    dispatch and warm-boot snapshots — all jax-free stats modules.  A
    section that fails to import reports its error instead of sinking the
    record (same discipline as ``tracing.trace_document``)."""
    doc: dict = {"t": round(t, 9)}

    def section(name, fn):
        try:
            doc[name] = fn()
        except Exception as e:  # noqa: BLE001
            doc[name] = {"error": repr(e)}

    def _sched():
        from cometbft_tpu.verifysched import stats as sstats

        return sstats.snapshot()

    def _ingest():
        from cometbft_tpu.txingest import stats as istats

        return istats.snapshot()

    def _dispatch():
        from cometbft_tpu.ops import dispatch_stats

        return dispatch_stats.snapshot()

    def _warmboot():
        from cometbft_tpu.ops import warm_stats

        return warm_stats.snapshot()

    section("sched", _sched)
    section("ingest", _ingest)
    section("dispatch", _dispatch)
    section("warmboot", _warmboot)
    return doc


# -- decode -------------------------------------------------------------------


def _rolled_files(dir_: str) -> "list[str]":
    """Rolled segment paths, oldest first."""
    try:
        names = os.listdir(dir_)
    except OSError:
        return []
    rolled = sorted(
        (
            n
            for n in names
            if n.startswith(HEAD_NAME + ".") and n[len(HEAD_NAME) + 1 :].isdigit()
        ),
        key=lambda n: int(n[len(HEAD_NAME) + 1 :]),
    )
    return [os.path.join(dir_, n) for n in rolled]


def segment_files(dir_: str) -> "list[str]":
    """All journal segments, oldest first, head last."""
    out = _rolled_files(dir_)
    head = os.path.join(dir_, HEAD_NAME)
    if os.path.exists(head):
        out.append(head)
    return out


def _last_valid_end(path: str) -> int:
    """End offset of the LAST verifiable frame in a segment, walking
    with the same tolerance as decode (skip corrupt frames, resync on
    garbage headers).  Everything past it is a torn tail."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return 0
    end, pos, n = 0, 0, len(data)
    while pos + 8 <= n:
        crc, length = struct.unpack_from(">II", data, pos)
        if length > MAX_REC_SIZE + 1:
            nxt = _resync(data, pos + 1)
            if nxt is None:
                break
            pos = nxt
            continue
        if pos + 8 + length > n:
            break
        body = data[pos + 8 : pos + 8 + length]
        pos += 8 + length
        if zlib.crc32(body) & 0xFFFFFFFF == crc:
            end = pos
    return end


def _iter_file(data: bytes, last_segment: bool, stats: dict) -> Iterator[tuple]:
    """Yield (kind, payload) frames from one segment's bytes.

    Torn-tail semantics: an incomplete frame at the END of the LAST
    segment is a normal crash artifact (``torn_tail``); everywhere else
    it counts as corruption.  A CRC failure skips one frame (the length
    field still brackets it); an implausible length resyncs by scanning
    forward for the next verifiable frame — skip-and-count, never raise.
    """
    pos, n = 0, len(data)
    while pos + 8 <= n:
        crc, length = struct.unpack_from(">II", data, pos)
        if length > MAX_REC_SIZE + 1:
            # header is garbage (corrupted length): resync forward
            nxt = _resync(data, pos + 1)
            stats["corrupt_skipped"] += 1
            if nxt is None:
                return
            pos = nxt
            continue
        if pos + 8 + length > n:
            if last_segment:
                stats["torn_tail"] = True
            else:
                stats["corrupt_skipped"] += 1
            return
        body = data[pos + 8 : pos + 8 + length]
        pos += 8 + length
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            stats["corrupt_skipped"] += 1
            continue
        try:
            payload = json.loads(body[1:])
        except ValueError:
            stats["corrupt_skipped"] += 1
            continue
        yield body[0], payload
    if 0 < n - pos < 8:
        if last_segment:
            stats["torn_tail"] = True
        else:
            stats["corrupt_skipped"] += 1


def _resync(data: bytes, start: int) -> Optional[int]:
    """Scan forward for the next offset holding a verifiable frame."""
    n = len(data)
    for pos in range(start, n - 8):
        crc, length = struct.unpack_from(">II", data, pos)
        if length > MAX_REC_SIZE + 1 or pos + 8 + length > n:
            continue
        body = data[pos + 8 : pos + 8 + length]
        if zlib.crc32(body) & 0xFFFFFFFF == crc:
            return pos
    return None


def decode_dir(dir_: str) -> "tuple[list[tuple[int, dict]], dict]":
    """Decode a journal directory into ``(records, stats)``.  Never
    raises on damaged input — damage lands in the stats instead."""
    stats = {
        "segments": 0,
        "bytes": 0,
        "records": 0,
        "corrupt_skipped": 0,
        "torn_tail": False,
    }
    records: "list[tuple[int, dict]]" = []
    files = segment_files(dir_)
    for i, fp in enumerate(files):
        try:
            with open(fp, "rb") as f:
                data = f.read()
        except OSError:
            continue
        stats["segments"] += 1
        stats["bytes"] += len(data)
        for rec in _iter_file(data, i == len(files) - 1, stats):
            records.append(rec)
    stats["records"] = len(records)
    return records, stats


# -- postmortem reconstruction ------------------------------------------------


def _fold_breaker(breakers: dict, backend: str, entry: dict) -> None:
    """Last-known-state fold by TIMESTAMP, not record order: the caller-
    drain fast path for fsync'd anomalies can land a breaker_open a hair
    before spans the writer thread had already popped, so on-disk order
    between breaker records is not authoritative — their ``t`` is."""
    prev = breakers.get(backend)
    if prev is not None:
        t_prev, t_new = prev.get("t"), entry.get("t")
        if (
            isinstance(t_prev, (int, float))
            and isinstance(t_new, (int, float))
            and t_new < t_prev
        ):
            return
    breakers[backend] = entry


def resolve_dir(dir_: str) -> Optional[str]:
    """A node home (or its data dir) is accepted anywhere a journal dir
    is: the first of ``dir_``, ``dir_/blackbox``, ``dir_/data/blackbox``
    holding journal segments.  None when no journal exists anywhere —
    the single resolution rule the CLI, the boot path and the report all
    share."""
    for cand in (
        dir_,
        os.path.join(dir_, "blackbox"),
        os.path.join(dir_, "data", "blackbox"),
    ):
        if segment_files(cand):
            return cand
    return None


def postmortem_report(dir_: str, recent: int = 16) -> dict:
    """Reconstruct a node's final timeline from its journal: a pure
    function of the journal bytes, serialized deterministically
    (``sort_keys`` JSON of this dict byte-compares across same-seed sim
    runs).  Tolerates any damage ``decode_dir`` tolerates."""
    dir_ = resolve_dir(dir_) or dir_
    records, stats = decode_dir(dir_)

    clean_close = bool(records) and records[-1][0] == REC_CLEAN_CLOSE
    last_committed: Optional[int] = None
    opens: dict = {}  # span id -> OPEN payload (unmatched so far)
    last_dispatch: Optional[dict] = None
    anomalies: "list[dict]" = []
    anomaly_counts: dict = {}
    breakers: dict = {}
    health: Optional[dict] = None
    quorum_events: "list[dict]" = []
    device_events: "list[dict]" = []
    mesh_events: "list[dict]" = []
    mesh_width = None  # elastic mesh width at death (last reconfig)
    spans_total = 0

    step_spans: "list[dict]" = []  # last incarnation's consensus.step spans
    for kind, p in records:
        if kind == REC_OPEN:
            opens[p.get("span")] = p
        elif kind == REC_SPAN:
            spans_total += 1
            opens.pop(p.get("span"), None)
            stage = p.get("stage")
            attrs = p.get("attrs") or {}
            if stage == "consensus.round" and attrs.get("committed"):
                h = attrs.get("h")
                if isinstance(h, int):
                    last_committed = max(last_committed or 0, h)
            elif stage == "consensus.step":
                step_spans.append(p)
            elif stage == "verify.dispatch":
                last_dispatch = {
                    "tier": attrs.get("tier"),
                    "lanes": attrs.get("lanes"),
                    "n": attrs.get("n"),
                    "dispatch": attrs.get("dispatch"),
                    "t1": p.get("t1"),
                }
                if attrs.get("mesh") is not None:
                    # mesh width the dispatch targeted (single-chip
                    # dispatches carry no key) -- "which fleet shape was
                    # live when it died" is a first-class question
                    last_dispatch["mesh"] = attrs.get("mesh")
        elif kind == REC_ANOMALY:
            k = p.get("kind", "?")
            anomaly_counts[k] = anomaly_counts.get(k, 0) + 1
            anomalies.append(p)
            a = p.get("attrs") or {}
            if k.startswith("breaker_open") and a.get("backend"):
                _fold_breaker(
                    breakers,
                    a["backend"],
                    {
                        "state": "open",
                        "t": p.get("t"),
                        "error": a.get("error", ""),
                    },
                )
        elif kind == REC_EVENT:
            k = p.get("kind")
            a = p.get("attrs") or {}
            if k == "boot":
                # a new incarnation: the previous process's unfinished
                # opens can never complete, and its step/quorum history
                # must not masquerade as the new process's progress (a
                # restarted node re-enters the SAME (h, r)) — "at death"
                # means the death of the LAST process, not an ancestor's
                opens.clear()
                quorum_events.clear()
                step_spans.clear()
                mesh_events.clear()
                mesh_width = None
            elif k == "breaker_close" and a.get("backend"):
                _fold_breaker(
                    breakers,
                    a["backend"],
                    {"state": "closed", "t": p.get("t")},
                )
            elif k == "quorum":
                quorum_events.append(p)
            elif k == "device_probe":
                device_events.append(p)
            elif k == "mesh.reconfig":
                mesh_events.append(p)
                if a.get("width") is not None:
                    mesh_width = a.get("width")
        elif kind == REC_HEALTH:
            health = p

    # the in-flight consensus round: the newest unmatched round OPEN
    in_flight: Optional[dict] = None
    round_opens = [
        p for p in opens.values() if p.get("stage") == "consensus.round"
    ]
    if round_opens:
        p = round_opens[-1]
        attrs = p.get("attrs") or {}
        h, r = attrs.get("h"), attrs.get("r")
        steps = {}
        for sp in step_spans:  # last incarnation only, like quorum/opens
            a = sp.get("attrs") or {}
            if a.get("h") == h and a.get("r") == r:
                steps[a.get("step", "?")] = sp.get("dur_ms")
        quorum = {}
        for ev in quorum_events:
            a = ev.get("attrs") or {}
            if a.get("h") == h and a.get("r") == r and a.get("key"):
                quorum[a["key"]] = a.get("ms")
        in_flight = {
            "h": h,
            "r": r,
            "node": attrs.get("node"),
            "t0": p.get("t0"),
            "steps": steps,
            "quorum": quorum,
        }

    open_spans = [
        {
            "stage": p.get("stage"),
            "span": p.get("span"),
            "t0": p.get("t0"),
            "attrs": p.get("attrs") or {},
        }
        for p in sorted(opens.values(), key=lambda p: p.get("span") or 0)
    ]

    return {
        "journal": stats,
        "clean_close": clean_close,
        # a journal that EXISTS without ending in the sentinel is an
        # unclean shutdown — even an empty head file means a process
        # opened a black box and never got to close it
        "unclean_shutdown": stats["segments"] > 0 and not clean_close,
        "last_committed_height": last_committed,
        "in_flight": in_flight,
        "open_spans": open_spans,
        "last_dispatch": last_dispatch,
        # elastic mesh state at death: the last reconfiguration's width
        # plus the recent membership events (shrinks, probe exclusions,
        # restores) of the final incarnation
        "mesh": {
            "width": mesh_width,
            "events": mesh_events[-recent:],
        },
        "spans_total": spans_total,
        "anomaly_counts": anomaly_counts,
        "anomalies": anomalies[-recent:],
        "breakers": breakers,
        "device_events": device_events[-recent:],
        "health": health,
    }


def boot_report(dir_: str) -> Optional[dict]:
    """Boot-time unclean-shutdown check: None when no journal exists yet
    (first boot), else the previous run's postmortem report."""
    if not segment_files(dir_):
        return None
    return postmortem_report(dir_)


# -- process-wide journal (the real node's black box) -------------------------

_JOURNAL: Optional[BlackboxJournal] = None
_JOURNAL_LOCK = threading.Lock()


def open_journal(dir_: str, **kw) -> Optional[BlackboxJournal]:
    """Open the process-wide journal and install the tracer sinks.  A
    previously installed journal is NOT closed — it stops receiving
    records (the sinks repoint) but stays open so its owner (another
    in-process Node, a test fixture) can still write its clean-close
    sentinel at its own graceful stop; only a journal nobody closes
    reads as an unclean shutdown.  No-op (None) when the kill switch is
    set."""
    global _JOURNAL
    if not enabled():
        return None
    from cometbft_tpu.libs import tracing

    with _JOURNAL_LOCK:
        j = BlackboxJournal(dir_, **kw)
        _JOURNAL = j
    tracing.set_sink("span", j.on_span)
    tracing.set_sink("open", j.on_open)
    tracing.set_sink("anomaly", j.on_anomaly)
    tracing.set_sink("event", j.on_event)
    return j


def close_journal(clean: bool = True) -> None:
    global _JOURNAL
    from cometbft_tpu.libs import tracing

    with _JOURNAL_LOCK:
        j = _JOURNAL
        _JOURNAL = None
    if j is None:
        return
    for kind in ("span", "open", "anomaly", "event"):
        tracing.set_sink(kind, None)
    j.close(clean=clean)


def get_journal() -> Optional[BlackboxJournal]:
    return _JOURNAL


def journal_stats() -> Optional[dict]:
    j = _JOURNAL
    return j.stats() if j is not None else None


# -- on-demand GC (scripts/exec_cache_gc.py --blackbox) -----------------------


def gc_dir(
    root: str,
    max_segments: Optional[int] = None,
    ttl_days: Optional[float] = None,
    now: Optional[float] = None,
    dry_run: bool = False,
) -> "tuple[int, int]":
    """Prune dead-node journals under ``root``: every directory holding a
    ``blackbox.journal`` keeps its newest ``max_segments`` segments, and
    (with ``ttl_days``) loses rolled segments older than the TTL.  The
    head segment is never removed.  Returns (files_removed, bytes)."""
    import time as _time

    max_segments = max_segments or default_segments()
    now = now if now is not None else _time.time()
    removed = freed = 0
    for dirpath, _dirnames, filenames in os.walk(root):
        if HEAD_NAME not in filenames:
            continue
        rolled = _rolled_files(dirpath)
        victims = list(rolled[: max(len(rolled) - (max_segments - 1), 0)])
        if ttl_days is not None:
            cutoff = now - ttl_days * 86400.0
            for fp in rolled:
                if fp not in victims:
                    try:
                        if os.path.getmtime(fp) < cutoff:
                            victims.append(fp)
                    except OSError:
                        pass
        for fp in victims:
            try:
                size = os.path.getsize(fp)
            except OSError:
                continue
            removed += 1
            freed += size
            if not dry_run:
                try:
                    os.unlink(fp)
                except OSError:
                    removed -= 1
                    freed -= size
    return removed, freed
