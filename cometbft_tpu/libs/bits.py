"""Thread-safe bit array (reference: internal/bits/bit_array.go).

Gossiped in proto form between peers to advertise which votes / block parts a
peer already has; ``pick_random`` selects a set bit for gossip, ``sub`` and
``not_`` compute what a peer is missing.
"""

from __future__ import annotations

import random
import threading
from typing import Optional


class BitArray:
    def __init__(self, bits: int):
        self.bits = bits
        self._elems = bytearray((bits + 7) // 8)
        self._mtx = threading.Lock()

    @staticmethod
    def from_bools(bools: list[bool]) -> "BitArray":
        ba = BitArray(len(bools))
        for i, b in enumerate(bools):
            if b:
                ba.set_index(i, True)
        return ba

    def size(self) -> int:
        return self.bits

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.bits:
            return False
        with self._mtx:
            return bool(self._elems[i // 8] >> (i % 8) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if i < 0 or i >= self.bits:
            return False
        with self._mtx:
            if v:
                self._elems[i // 8] |= 1 << (i % 8)
            else:
                self._elems[i // 8] &= ~(1 << (i % 8))
            return True

    def copy(self) -> "BitArray":
        out = BitArray(self.bits)
        with self._mtx:
            out._elems = bytearray(self._elems)
        return out

    def or_(self, other: "BitArray") -> "BitArray":
        out = BitArray(max(self.bits, other.bits))
        with self._mtx:
            for i, b in enumerate(self._elems):
                out._elems[i] |= b
        with other._mtx:
            for i, b in enumerate(other._elems):
                out._elems[i] |= b
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self.bits, other.bits))
        with self._mtx, other._mtx:
            for i in range(len(out._elems)):
                out._elems[i] = self._elems[i] & other._elems[i]
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.bits)
        with self._mtx:
            for i in range(len(self._elems)):
                out._elems[i] = ~self._elems[i] & 0xFF
        # mask tail bits beyond self.bits
        extra = len(out._elems) * 8 - self.bits
        if extra and out._elems:
            out._elems[-1] &= 0xFF >> extra
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (what `other` is missing)."""
        out = self.copy()
        with other._mtx:
            for i in range(min(len(out._elems), len(other._elems))):
                out._elems[i] &= ~other._elems[i] & 0xFF
        return out

    def is_empty(self) -> bool:
        with self._mtx:
            return not any(self._elems)

    def is_full(self) -> bool:
        with self._mtx:
            if self.bits == 0:
                return True
            full, extra = divmod(self.bits, 8)
            if any(b != 0xFF for b in self._elems[:full]):
                return False
            if extra:
                return self._elems[full] == (1 << extra) - 1
            return True

    def pick_random(self) -> Optional[int]:
        indices = self.true_indices()
        if not indices:
            return None
        return random.choice(indices)

    def true_indices(self) -> list[int]:
        with self._mtx:
            return [
                i
                for i in range(self.bits)
                if self._elems[i // 8] >> (i % 8) & 1
            ]

    def update(self, other: "BitArray") -> None:
        """Overwrite with other's contents (same size assumed)."""
        with self._mtx, other._mtx:
            n = min(len(self._elems), len(other._elems))
            self._elems[:n] = other._elems[:n]

    def to_bools(self) -> list[bool]:
        with self._mtx:
            return [
                bool(self._elems[i // 8] >> (i % 8) & 1) for i in range(self.bits)
            ]

    def __str__(self) -> str:
        return "".join("x" if b else "_" for b in self.to_bools())
