"""Verify-pipeline flight recorder: span tracing + anomaly forensics.

The paper's headline claim is a LATENCY claim (<5 ms for a 10k-validator
commit), but counters can only say that something was slow on average —
not WHICH dispatch, at WHAT shape, on WHICH supervisor tier.  This module
is the jax-free tracing half of the observability layer
(docs/observability.md): a thread-safe span tracer over a bounded
in-memory ring buffer (the *flight recorder*), threaded through the whole
verify journey — txingest admission → sigcache probe → verifysched
submit/queue-wait/flush → ``ops/verify`` bucket dispatch → supervisor tier
(watchdog fires, degradations, bisect quarantines) → verdict resolution —
plus the consensus/blocksync/light spans above it.

Span model: ``(trace_id, span_id, parent_id, stage, t_start, t_end,
attrs)``.  Trace propagation is ambient (a thread-local stack): a span
opened while another is live becomes its child and inherits its trace id,
so a commit verification's device dispatches attribute to the commit
without any API threading.  The clock is injectable (``set_clock``) so
the deterministic simulator traces on its VirtualClock and two same-seed
runs produce byte-identical span streams.

Stage taxonomy (dotted, coarse on the hot path — one span per batch or
per dispatch, never per signature):

  * ``txingest.flush`` / ``txingest.shed_sync``  — batched tx admission
  * ``sched.flush`` / ``sched.shed_fallback``    — verify scheduler
  * ``verify.commit``                            — commit verification
    (consensus apply, blocksync frontier, light client)
  * ``verify.batch`` / ``verify.dispatch``       — bucket dispatch (the
    dispatch span carries bucket lanes + tier + dispatch seq: the triple
    an anomaly dump attributes a watchdog fire to)
  * ``supervisor.host_fallback`` / ``supervisor.bisect``
  * ``consensus.vote`` / ``consensus.proposal`` / ``consensus.vote_ext``
    (per height-round)
  * ``blocksync.prefetch`` / ``light.chain``     — speculative windows
  * ``warmboot.shape`` / ``warmboot.run``        — warm-boot progress

Anomaly forensics: ``record_anomaly(kind, **attrs)`` counts every anomaly
(watchdog_fire, breaker_open, queue_shed, ingest_shed, quarantine,
exec_cache_stale) and — on the FIRST occurrence of each kind since the
last reset (``COMETBFT_TPU_TRACE_DUMP_ALL=1`` dumps every occurrence) —
writes the last ``COMETBFT_TPU_TRACE_DUMP_SPANS`` (256) spans as JSONL to
``COMETBFT_TPU_TRACE_DIR`` for postmortem.  The dump's first line names
the anomaly and its attributes; dump bytes are a pure function of the
span stream, so a sim scenario's dump replays byte-identically per seed.

Kill switch: ``COMETBFT_TPU_TRACE=0`` compiles spans down to no-ops (a
shared null context manager; one env read per span site) — bench.py
``--obs`` pins the disabled overhead at ≤1% of the sched bench.

Cross-node correlation (docs/observability.md "Cross-node tracing"): a
``TraceContext`` is the compact (trace_id, span_id, origin-node) triple a
gossip envelope carries so consensus-round spans on different nodes form
ONE causal tree per (height, round) — the proposer's ``consensus.round``
span is the root, every receiver's round span adopts its trace id, and a
commit's verify spans on node B link back to the proposal that originated
on node A through nothing but the shared trace id.  Event-driven stages
that outlive any ``with`` block (a consensus round spans many receive-loop
events) use the explicit ``begin``/``finish`` API; ``under`` temporarily
makes such an unfinished span the ambient parent so the verify pipeline
underneath it inherits the round's trace.  ``COMETBFT_TPU_TRACE_XNODE=0``
turns off context propagation (spans still record, per-node only).
``rounds_report`` merges the ring into per-(height, round) timelines —
tolerant of orphan parents (a crashed proposer's root span never records;
the group still renders with ``origin=None``) and of ring-bound drops.

Deliberately free of jax imports, like ``ops/dispatch_stats``: the
``/metrics`` scrape, the ``/debug/verify_trace`` RPC and the
``cometbft-tpu trace`` CLI all read this module, and none of them may be
the thing that initializes an accelerator backend.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

logger = logging.getLogger("cometbft_tpu.tracing")

DEFAULT_RING = 4096
DEFAULT_DUMP_SPANS = 256
# anomaly kinds with a dump trigger (docs/observability.md).  Breaker
# opens are per-taxonomy-kind: the ed25519 device tiers share
# "breaker_open", while the single-tier secp256k1/BLS breakers get their
# own kinds — each kind's FIRST open dumps, so an ed25519 brownout can no
# longer eat the one dump a simultaneous secp_device failure deserved.
ANOMALY_KINDS = (
    "watchdog_fire",
    "breaker_open",
    "breaker_open_secp_device",
    "breaker_open_bls_g1",
    "queue_shed",
    "ingest_shed",
    "quarantine",
    "exec_cache_stale",
    # elastic mesh supervision (parallel/elastic, docs/backend-supervisor
    # "Fault isolation"): a shard abandoned past the watchdog, a device
    # removed from mesh membership (shard failure or proactive
    # probe-down), and a device re-admitted by a passing half-open probe.
    # Per-ordinal breaker opens additionally get their own
    # ``breaker_open_mesh_dev{N}`` kinds via backend_health.
    "shard_watchdog_fire",
    "mesh_shrink",
    "mesh_restore",
)


def enabled() -> bool:
    """``COMETBFT_TPU_TRACE=0`` is the kill switch; default on.  One dict
    lookup — the only cost a disabled span site pays besides the null
    context manager."""
    return os.environ.get("COMETBFT_TPU_TRACE", "1") != "0"


# -- durable sinks (libs/blackbox.py) -----------------------------------------
#
# The black-box journal subscribes here; with no sink installed (the
# default, and always under COMETBFT_TPU_BLACKBOX=0) every hook is a
# single None check — the RAM-only recorder is bit-for-bit unchanged.
#   span(sp)              — every COMPLETED span, as it lands in the ring
#   open(sp)              — every explicit begin() span (round anchors)
#   anomaly(kind, attrs, t) — EVERY anomaly occurrence (the RAM dump
#                           latch stays first-per-kind; the journal does not)
#   event(kind, attrs)    — low-rate journal-only events (breaker
#                           transitions, quorum arrivals, device probes)

_SINKS: dict = {"span": None, "open": None, "anomaly": None, "event": None}


def set_sink(kind: str, fn):
    """Install (or, with None, remove) a durable sink; returns the sink
    it replaced so callers can restore it.  Sink errors are swallowed at
    the call sites — forensics must never become a second failure."""
    prev = _SINKS[kind]
    _SINKS[kind] = fn
    return prev


def get_sink(kind: str):
    return _SINKS[kind]


def note_event(kind: str, **attrs) -> None:
    """Journal-only event: recorded by the black box when one is
    installed, invisible to the RAM ring.  For low-rate state transitions
    (breaker close, device probe flips, quorum arrivals on the in-flight
    round) whose loss at crash time would blind a postmortem."""
    fn = _SINKS["event"]
    if fn is None:
        return
    try:
        fn(kind, attrs)
    except Exception:  # noqa: BLE001
        pass


def trace_dir() -> Optional[str]:
    return os.environ.get("COMETBFT_TPU_TRACE_DIR") or None


def xnode_enabled() -> bool:
    """Whether gossip envelopes carry trace contexts
    (``COMETBFT_TPU_TRACE_XNODE=0`` disables propagation while keeping
    per-node spans).  Implies the recorder itself being on."""
    return (
        enabled()
        and os.environ.get("COMETBFT_TPU_TRACE_XNODE", "1") != "0"
    )


class TraceContext:
    """The compact trace context a gossip envelope propagates: the
    sender's round-trace id, the span to parent under, and the origin
    node.  Encodes to a short ASCII token so any transport (sim fabric
    today, a p2p envelope field tomorrow) can carry it opaquely."""

    __slots__ = ("trace_id", "span_id", "origin")

    def __init__(self, trace_id: int, span_id: int, origin=None):
        self.trace_id = int(trace_id)
        self.span_id = int(span_id)
        self.origin = origin

    def encode(self) -> str:
        o = "" if self.origin is None else str(int(self.origin))
        return f"{self.trace_id:x}.{self.span_id:x}.{o}"

    @classmethod
    def decode(cls, token) -> "Optional[TraceContext]":
        """Tolerant decode: garbage, truncation or a foreign format yield
        None (a malformed context must never fail message handling)."""
        if isinstance(token, TraceContext):
            return token
        if not isinstance(token, str):
            return None
        parts = token.split(".")
        if len(parts) != 3:
            return None
        try:
            trace_id = int(parts[0], 16)
            span_id = int(parts[1], 16)
            origin = int(parts[2]) if parts[2] else None
        except ValueError:
            return None
        if trace_id <= 0 or span_id <= 0:
            return None
        return cls(trace_id, span_id, origin)

    def __repr__(self) -> str:  # debugging/trace logs
        return f"TraceContext({self.encode()!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, TraceContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
            and self.origin == other.origin
        )


class Span:
    """One recorded stage interval.  ``attrs`` values must be
    JSON-serializable (dump files are byte-compared across sim runs)."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "stage", "t_start", "t_end",
        "attrs",
    )

    def __init__(self, trace_id, span_id, parent_id, stage, t_start, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.stage = stage
        self.t_start = t_start
        self.t_end = None
        self.attrs = attrs

    def set(self, **attrs) -> "Span":
        """Attach attributes mid-span (e.g. the outcome, known at exit)."""
        self.attrs.update(attrs)
        return self

    @property
    def duration(self) -> float:
        return (self.t_end or self.t_start) - self.t_start

    def to_dict(self) -> dict:
        # fixed rounding so float formatting can never vary a dump byte
        d = {
            "trace": self.trace_id,
            "span": self.span_id,
            "stage": self.stage,
            "t0": round(self.t_start, 9),
            "t1": round(self.t_end, 9) if self.t_end is not None else None,
            "dur_ms": (
                round((self.t_end - self.t_start) * 1e3, 6)
                if self.t_end is not None
                else None
            ),
        }
        if self.parent_id is not None:
            d["parent"] = self.parent_id
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class _NullSpan:
    """The disabled-tracer span: a shared, allocation-free no-op that
    still satisfies the ``with tracer.span(...) as sp: sp.set(...)``
    calling convention."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    __slots__ = ("tracer", "sp")

    def __init__(self, tracer: "Tracer", sp: Span):
        self.tracer = tracer
        self.sp = sp

    def __enter__(self) -> Span:
        stack = self.tracer._stack()
        stack.append(self.sp)
        return self.sp

    def __exit__(self, etype, evalue, tb) -> bool:
        sp = self.sp
        sp.t_end = self.tracer._clock()
        if etype is not None:
            sp.attrs.setdefault("error", etype.__name__)
        stack = self.tracer._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        elif sp in stack:  # mis-nested exit (exception unwound past us)
            stack.remove(sp)
        self.tracer._append(sp)
        return False


class _UnderCtx:
    """Pushes an unfinished explicit span as the ambient parent for the
    duration of a block; pops by identity so nested/rotated anchors can
    never unbalance the stack."""

    __slots__ = ("tracer", "sp")

    def __init__(self, tracer: "Tracer", sp: Span):
        self.tracer = tracer
        self.sp = sp

    def __enter__(self) -> Span:
        self.tracer._stack().append(self.sp)
        return self.sp

    def __exit__(self, *exc) -> bool:
        stack = self.tracer._stack()
        if stack and stack[-1] is self.sp:
            stack.pop()
        elif self.sp in stack:
            stack.remove(self.sp)
        return False

    def set(self, **attrs):  # parity with _NullSpan for disabled callers
        self.sp.set(**attrs)
        return self


class Tracer:
    """Bounded flight recorder; all methods are thread-safe.

    Spans land in the ring ON COMPLETION (the append is the caller
    thread's, so a worker abandoned by the dispatch watchdog never races
    a span into a deterministic sim's record)."""

    def __init__(
        self,
        ring_size: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        if ring_size is None:
            try:
                ring_size = int(
                    os.environ.get("COMETBFT_TPU_TRACE_RING", "")
                    or DEFAULT_RING
                )
            except ValueError:
                ring_size = DEFAULT_RING
        self._lock = threading.Lock()
        self._ring: "deque[Span]" = deque(maxlen=max(int(ring_size), 16))
        self._clock: Callable[[], float] = clock or time.perf_counter
        self._tls = threading.local()
        self._next_id = 1
        self._recorded = 0
        self._dropped = 0
        self._anomalies: dict = {}
        self._dumped_kinds: set = set()
        self._dump_seq = 0
        self._dumps: "list[str]" = []
        self._overhead_s = 0.0
        # process-LIFETIME aggregates: reset() (sim per-run hygiene) does
        # not clear these, so the tier1-trace summary line still reports
        # the whole test run's span volume and recorder overhead
        self._life_recorded = 0
        self._life_dropped = 0
        self._life_anomalies = 0
        self._life_dumps = 0
        self._life_overhead_s = 0.0

    # -- span API ----------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(self, stage: str, **attrs):
        """Context manager recording one stage interval.  Nested spans
        (same thread) become children; the root span's id is the trace id.
        Disabled tracer → the shared no-op span."""
        if not enabled():
            return _NULL_SPAN
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        stack = self._stack()
        parent = stack[-1] if stack else None
        sp = Span(
            trace_id=parent.trace_id if parent is not None else sid,
            span_id=sid,
            parent_id=parent.span_id if parent is not None else None,
            stage=stage,
            t_start=self._clock(),
            attrs=attrs,
        )
        return _SpanCtx(self, sp)

    def current_trace(self) -> Optional[int]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1].trace_id if stack else None

    def time(self) -> float:
        """The tracer's clock (virtual in sim).  Event-driven callers use
        it for retroactive ``record_span`` timestamps so span times always
        share one time base with the rest of the ring."""
        return self._clock()

    # -- explicit span API (event-driven stages) ---------------------------
    #
    # A consensus round outlives any single receive-loop event, so no
    # ``with`` block can bracket it: ``begin`` allocates an UNFINISHED span
    # (id + start time), the state machine mutates/adopts it across events,
    # and ``finish`` stamps the end time and lands it in the ring — still
    # on completion, still from the owning thread.

    def begin(
        self,
        stage: str,
        parent: Optional[Span] = None,
        ctx: Optional[TraceContext] = None,
        **attrs,
    ) -> Optional[Span]:
        """Allocate an unfinished span.  ``parent`` (a local span) or
        ``ctx`` (a remote trace context) seed the trace; with neither the
        span is a trace root.  Returns None when tracing is disabled —
        every other explicit-API call accepts None as a no-op."""
        if not enabled():
            return None
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif ctx is not None:
            trace_id, parent_id = ctx.trace_id, ctx.span_id
            if ctx.origin is not None:
                attrs.setdefault("xnode", ctx.origin)
        else:
            trace_id, parent_id = sid, None
        sp = Span(trace_id, sid, parent_id, stage, self._clock(), attrs)
        sink = _SINKS["open"]
        if sink is not None:
            # the journal's OPEN record: an explicit span (a consensus
            # round anchor) exists from this moment, so a crash before
            # finish() still leaves the in-flight round reconstructable
            try:
                sink(sp)
            except Exception:  # noqa: BLE001
                pass
        return sp

    def finish(self, sp: Optional[Span], **attrs) -> None:
        """Stamp the end time and record an explicit span.  Idempotent on
        an already-finished span; None is a no-op."""
        if sp is None or sp.t_end is not None:
            return
        if attrs:
            sp.attrs.update(attrs)
        sp.t_end = self._clock()
        self._append(sp)

    def adopt(self, sp: Optional[Span], ctx: Optional[TraceContext]) -> bool:
        """Re-parent a still-rootless unfinished span under a remote
        context — how a receiver's ``consensus.round`` span joins the
        originating proposal's trace.  No-op (False) once the span has a
        parent or has finished: first adoption wins."""
        if (
            sp is None
            or ctx is None
            or sp.parent_id is not None
            or sp.t_end is not None
        ):
            return False
        sp.trace_id = ctx.trace_id
        sp.parent_id = ctx.span_id
        if ctx.origin is not None:
            sp.attrs.setdefault("xnode", ctx.origin)
        return True

    def record_span(
        self,
        stage: str,
        t_start: float,
        t_end: float,
        parent: Optional[Span] = None,
        **attrs,
    ) -> Optional[Span]:
        """Manufacture a COMPLETED span with explicit timestamps (taken
        from ``time()``) — retroactive step timing: the consensus state
        machine only knows a step's duration once the next step begins."""
        if not enabled():
            return None
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = sid, None
        sp = Span(trace_id, sid, parent_id, stage, t_start, attrs)
        sp.t_end = t_end
        self._append(sp)
        return sp

    def under(self, sp: Optional[Span]):
        """Context manager making an UNFINISHED explicit span the ambient
        parent, so ``span()`` sites underneath (verify.commit, dispatches)
        inherit its trace — the linkage that lets a commit's verify spans
        resolve to the originating proposal.  ``under(None)`` is a shared
        no-op."""
        if sp is None or not enabled():
            return _NULL_SPAN
        return _UnderCtx(self, sp)

    def ctx_for(self, sp: Optional[Span], origin=None) -> Optional[TraceContext]:
        """A propagatable context pointing at an explicit span."""
        if sp is None:
            return None
        return TraceContext(sp.trace_id, sp.span_id, origin)

    def _append(self, sp: Span) -> None:
        t0 = time.perf_counter()
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
                self._life_dropped += 1
            self._ring.append(sp)
            self._recorded += 1
            self._life_recorded += 1
            # exit-path cost only (the enter path is of the same order):
            # an approximate but honestly *measured* recorder overhead the
            # tier1-trace summary line reports as a share of wall time
            dt = time.perf_counter() - t0
            self._overhead_s += dt
            self._life_overhead_s += dt
        sink = _SINKS["span"]
        if sink is not None:
            # outside the ring lock: the journal enqueue has its own lock
            # and never blocks on IO (bounded queue, counted drops)
            try:
                sink(sp)
            except Exception:  # noqa: BLE001
                pass

    # -- anomaly forensics -------------------------------------------------

    def record_anomaly(self, kind: str, **attrs) -> Optional[str]:
        """Count an anomaly; dump the ring tail as JSONL on the first
        occurrence of ``kind`` since the last reset (all occurrences with
        ``COMETBFT_TPU_TRACE_DUMP_ALL=1``).  Returns the dump path, or
        None when no dump was written.  Never raises — forensics must not
        become a second failure."""
        dump_all = os.environ.get("COMETBFT_TPU_TRACE_DUMP_ALL") == "1"
        sink = _SINKS["anomaly"]
        if sink is not None:
            # the durable journal records EVERY occurrence (and fsyncs);
            # the RAM dump below stays latched first-per-kind
            try:
                sink(kind, attrs, self._clock())
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            self._anomalies[kind] = self._anomalies.get(kind, 0) + 1
            self._life_anomalies += 1
            want_dump = (
                enabled()
                and trace_dir() is not None
                and (dump_all or kind not in self._dumped_kinds)
            )
            if not want_dump:
                return None
            self._dumped_kinds.add(kind)
            self._dump_seq += 1
            seq = self._dump_seq
            tail = self._dump_tail_locked()
            now = self._clock()
        try:
            return self._write_dump(kind, seq, now, attrs, tail)
        except Exception as e:  # noqa: BLE001 — forensics is best-effort
            logger.warning("flight-recorder dump failed: %r", e)
            return None

    def _dump_tail_locked(self) -> "list[Span]":
        try:
            n = int(
                os.environ.get("COMETBFT_TPU_TRACE_DUMP_SPANS", "")
                or DEFAULT_DUMP_SPANS
            )
        except ValueError:
            n = DEFAULT_DUMP_SPANS
        ring = list(self._ring)
        return ring[-n:] if n > 0 else ring

    def _write_dump(self, kind, seq, now, attrs, tail) -> str:
        d = trace_dir()
        os.makedirs(d, exist_ok=True)
        name = f"trace-{seq:03d}-{kind}.jsonl"
        path = os.path.join(d, name)
        lines = [
            json.dumps(
                {
                    "anomaly": kind,
                    "seq": seq,
                    "t": round(now, 9),
                    "attrs": attrs,
                    "spans": len(tail),
                },
                sort_keys=True,
            )
        ]
        lines.extend(json.dumps(sp.to_dict(), sort_keys=True) for sp in tail)
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        with self._lock:
            self._dumps.append(name)
            del self._dumps[:-32]  # keep the last 32 names
            self._life_dumps += 1
        logger.warning(
            "flight recorder: anomaly %s -> dumped %d spans to %s",
            kind,
            len(tail),
            path,
        )
        return path

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": enabled(),
                "ring_size": self._ring.maxlen,
                "ring_len": len(self._ring),
                "spans_recorded": self._recorded,
                "spans_dropped": self._dropped,
                "anomalies": dict(self._anomalies),
                "anomalies_total": sum(self._anomalies.values()),
                "dumps": list(self._dumps),
                "dump_count": self._dump_seq,
                "overhead_seconds": self._overhead_s,
                "lifetime": {
                    "spans_recorded": self._life_recorded,
                    "spans_dropped": self._life_dropped,
                    "anomalies": self._life_anomalies,
                    "dumps": self._life_dumps,
                    "overhead_seconds": self._life_overhead_s,
                },
            }

    def tail(self, n: int = DEFAULT_DUMP_SPANS) -> "list[dict]":
        with self._lock:
            ring = list(self._ring)
        return [sp.to_dict() for sp in (ring[-n:] if n > 0 else ring)]

    def stage_summary(self) -> dict:
        """Per-stage count / total / p50 / p99 over the spans currently in
        the ring (bounded by the ring, so the percentiles describe the
        recent window — exactly what a regression hunt wants)."""
        with self._lock:
            ring = list(self._ring)
        by_stage: dict = {}
        for sp in ring:
            if sp.t_end is None:
                continue
            by_stage.setdefault(sp.stage, []).append(sp.t_end - sp.t_start)
        out = {}
        for stage, durs in sorted(by_stage.items()):
            durs.sort()
            n = len(durs)
            out[stage] = {
                "count": n,
                "total_ms": round(sum(durs) * 1e3, 3),
                "p50_ms": round(durs[n // 2] * 1e3, 3),
                "p99_ms": round(durs[min(n - 1, (n * 99) // 100)] * 1e3, 3),
                "max_ms": round(durs[-1] * 1e3, 3),
            }
        return out

    def rounds_report(self, last_k: Optional[int] = None) -> dict:
        """Merged cross-node round timelines over the spans currently in
        the ring: one group per (height, round), carrying every node's
        ``consensus.round`` span (duration, committed flag, quorum-arrival
        times, per-step durations) plus the count of ``verify.commit``
        spans that link to the group's trace — the proof that a commit's
        verification attributes to the proposal that originated it.

        Orphan tolerance by construction: a group whose root span never
        recorded (crashed proposer, ring-bound drop) still renders, with
        ``origin=None``; a step or commit span whose parent fell off the
        ring still aggregates by its own (h, r)/trace attrs.  The report
        is a pure function of the span stream, so two same-seed sim runs
        serialize byte-identically (sort_keys JSON)."""
        with self._lock:
            ring = list(self._ring)
        groups: dict = {}  # (h, r) -> group dict
        step_agg: dict = {}  # step name -> [durations]
        quorum_agg: dict = {"prevote_ms": [], "precommit_ms": []}
        commit_traces: dict = {}  # trace_id -> verify.commit span count
        commits_total = 0
        commits_standalone = 0

        def group(h, r) -> dict:
            g = groups.get((h, r))
            if g is None:
                g = groups[(h, r)] = {
                    "h": h,
                    "r": r,
                    "trace": None,
                    "origin": None,
                    "nodes": {},
                    "traces": set(),
                }
            return g

        def node_entry(g, node) -> dict:
            e = g["nodes"].get(node)
            if e is None:
                e = g["nodes"][node] = {"node": node, "steps": {}}
            return e

        for sp in ring:
            if sp.t_end is None:
                continue
            a = sp.attrs
            if sp.stage == "consensus.round":
                g = group(a.get("h"), a.get("r"))
                g["traces"].add(sp.trace_id)
                e = node_entry(g, a.get("node"))
                e["dur_ms"] = round(sp.duration * 1e3, 6)
                e["committed"] = bool(a.get("committed"))
                e["adopted"] = sp.parent_id is not None
                for k, agg in (
                    ("q_prevote_ms", "prevote_ms"),
                    ("q_precommit_ms", "precommit_ms"),
                ):
                    if k in a:
                        e[k] = a[k]
                        quorum_agg[agg].append(a[k])
                if sp.parent_id is None and a.get("proposer"):
                    # the trace root: the PROPOSER's round span.  A merely
                    # rootless span (a node that never adopted — partition,
                    # or propagation off) must not claim the round's origin
                    g["trace"] = sp.trace_id
                    g["origin"] = a.get("node")
            elif sp.stage == "consensus.step":
                g = group(a.get("h"), a.get("r"))
                e = node_entry(g, a.get("node"))
                dur = round(sp.duration * 1e3, 6)
                e["steps"][a.get("step", "?")] = dur
                step_agg.setdefault(a.get("step", "?"), []).append(
                    sp.duration
                )
            elif sp.stage == "verify.commit":
                if sp.parent_id is None:
                    # a standalone verification (light client, statesync
                    # trust check, the sim's invariant checker): its own
                    # trace root by construction — not a linkage failure
                    commits_standalone += 1
                    continue
                commits_total += 1
                commit_traces[sp.trace_id] = (
                    commit_traces.get(sp.trace_id, 0) + 1
                )

        all_traces: set = set()
        rounds = []
        for (h, r) in sorted(
            groups, key=lambda k: (k[0] is None, k[0] or 0, k[1] or 0)
        ):
            g = groups[(h, r)]
            all_traces |= g["traces"]
            n_commits = sum(
                commit_traces.get(t, 0) for t in sorted(g["traces"])
            )
            if g["trace"] is None and len(g["traces"]) == 1:
                # orphan root: the trace id is still known from the
                # adopted members, only the proposer's span is missing
                g["trace"] = next(iter(g["traces"]))
            rounds.append(
                {
                    "h": h,
                    "r": r,
                    "trace": g["trace"],
                    "origin": g["origin"],
                    "commits": n_commits,
                    "nodes": [
                        g["nodes"][k]
                        for k in sorted(
                            g["nodes"], key=lambda n: (n is None, n)
                        )
                    ],
                }
            )

        def pctls(durs: list) -> dict:
            if not durs:
                return {"count": 0}
            durs = sorted(durs)
            n = len(durs)
            return {
                "count": n,
                "p50_ms": round(durs[n // 2] * 1e3, 6),
                "p99_ms": round(
                    durs[min(n - 1, (n * 99) // 100)] * 1e3, 6
                ),
                "max_ms": round(durs[-1] * 1e3, 6),
            }

        linked = sum(commit_traces.get(t, 0) for t in all_traces)
        return {
            "rounds_seen": len(rounds),
            "rounds": rounds[-last_k:] if last_k else rounds,
            "steps": {k: pctls(v) for k, v in sorted(step_agg.items())},
            "quorum": {
                # already in ms — scale back for the shared helper
                k: pctls([x / 1e3 for x in v])
                for k, v in sorted(quorum_agg.items())
            },
            "commits_linked": linked,
            "commits_unlinked": commits_total - linked,
            "commits_standalone": commits_standalone,
        }

    # -- lifecycle ---------------------------------------------------------

    def set_clock(self, clock: Optional[Callable[[], float]]) -> None:
        """Swap the time source (the sim pins its VirtualClock here so
        span times are virtual and deterministic); None restores
        ``time.perf_counter``."""
        self._clock = clock or time.perf_counter

    def dump_state(self) -> dict:
        """Snapshot of the anomaly-dump latch (first-per-kind set, dump
        sequence, dump names).  Scenario setup hooks save this so their
        teardown can restore it — composed scenarios' setup/teardown must
        not leak dump-latch state into the run (or each other) any more
        than they leak env knobs."""
        with self._lock:
            return {
                "dumped_kinds": set(self._dumped_kinds),
                "dump_seq": self._dump_seq,
                "dumps": list(self._dumps),
            }

    def restore_dump_state(self, state: dict) -> None:
        with self._lock:
            self._dumped_kinds = set(state.get("dumped_kinds", ()))
            self._dump_seq = int(state.get("dump_seq", 0))
            self._dumps = list(state.get("dumps", ()))

    def reset(self) -> None:
        """Fresh recorder state: empty ring, zeroed counters/ids, dump
        latch cleared.  The sim calls this per scenario run so span ids
        (and therefore dump bytes) are a pure function of the seed."""
        with self._lock:
            self._ring.clear()
            self._next_id = 1
            self._recorded = 0
            self._dropped = 0
            self._anomalies = {}
            self._dumped_kinds = set()
            self._dump_seq = 0
            self._dumps = []
            self._overhead_s = 0.0


_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The process-wide flight recorder (every pipeline stage writes to
    one ring — cross-stage attribution IS the feature)."""
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = Tracer()
    return _TRACER


def reset_tracer() -> None:
    """Drop the process-wide tracer (tests/sim; re-reads the ring-size
    env on next use)."""
    global _TRACER
    with _TRACER_LOCK:
        _TRACER = None


# module-level conveniences — the spelling the pipeline call sites use
def span(stage: str, **attrs):
    return get_tracer().span(stage, **attrs)


def record_anomaly(kind: str, **attrs) -> Optional[str]:
    return get_tracer().record_anomaly(kind, **attrs)


def summary_line() -> str:
    """One parseable line for test logs (scripts/check_tier1_budget.py
    reads the span count and recorder overhead share from it).  Reports
    the process-LIFETIME aggregates: per-run ``reset()`` calls (the sim)
    must not hide the suite's true recorder traffic."""
    life = get_tracer().snapshot()["lifetime"]
    return (
        "tier1-trace: spans=%d dropped=%d anomalies=%d dumps=%d "
        "overhead_s=%.3f"
        % (
            life["spans_recorded"],
            life["spans_dropped"],
            life["anomalies"],
            life["dumps"],
            life["overhead_seconds"],
        )
    )


DEFAULT_ROUND_K = 8


def trace_document(
    max_spans: int = DEFAULT_DUMP_SPANS, rounds: int = DEFAULT_ROUND_K
) -> dict:
    """The one-call forensic snapshot behind the ``/debug/verify_trace``
    RPC and the ``cometbft-tpu trace`` CLI: ring tail + per-stage latency
    summary + pipeline health (breaker states, cache hit rates, scheduler
    queue, warm-boot progress) as a single JSON-serializable document.

    Every read is lazy and jax-free; a section that fails to import
    reports its error instead of sinking the document."""
    tracer = get_tracer()
    doc = {
        "tracing": tracer.snapshot(),
        "stages": tracer.stage_summary(),
        # last-K merged consensus-round timelines (cross-node when the
        # fabric propagates contexts); rounds <= 0 skips the section body
        "rounds": tracer.rounds_report(last_k=max(0, int(rounds)) or None)
        if rounds > 0
        else {},
        # max_spans <= 0 really means "health only, no span payload" —
        # tail()'s 0-means-all convention is for the dump path, not here
        "spans": tracer.tail(max_spans) if max_spans > 0 else [],
    }

    def section(name, fn):
        try:
            doc[name] = fn()
        except Exception as e:  # noqa: BLE001 — one bad section must not
            # sink the whole forensic document
            doc[name] = {"error": repr(e)}

    def _backend():
        from cometbft_tpu.crypto import backend_health

        return backend_health.snapshot()

    def _sigcache():
        from cometbft_tpu.crypto import sigcache

        return sigcache.get_cache().stats()

    def _dispatch():
        from cometbft_tpu.ops import dispatch_stats

        return dispatch_stats.snapshot()

    def _sched():
        from cometbft_tpu.verifysched import stats as sstats

        return sstats.snapshot()

    def _warmboot():
        from cometbft_tpu.ops import warm_stats

        return warm_stats.snapshot()

    def _ingest():
        from cometbft_tpu.txingest import stats as istats

        return istats.snapshot()

    def _device():
        from cometbft_tpu.ops import device_health

        return device_health.snapshot()

    def _blackbox():
        from cometbft_tpu.libs import blackbox

        return blackbox.journal_stats() or {"enabled": blackbox.enabled()}

    def _storage():
        from cometbft_tpu.libs import storage_stats

        return storage_stats.snapshot()

    def _proofserve():
        from cometbft_tpu.proofserve import stats as pstats

        return pstats.snapshot()

    def _blocksync():
        from cometbft_tpu.blocksync import stats as bstats

        return bstats.snapshot()

    section("backend", _backend)
    section("sigcache", _sigcache)
    section("dispatch", _dispatch)
    section("sched", _sched)
    section("warmboot", _warmboot)
    section("ingest", _ingest)
    section("device", _device)
    section("blackbox", _blackbox)
    section("storage", _storage)
    section("proofserve", _proofserve)
    section("blocksync", _blocksync)
    return doc
