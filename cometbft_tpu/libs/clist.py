"""Concurrent doubly-linked list with waitable next-element.

Reference: internal/clist/clist.go — the mempool and evidence pool iterate a
shared list while writers append/remove concurrently; a reader at the tail
blocks until a new element arrives (``wait_chan`` in the reference; a
condition variable here).  Removed elements stay traversable (``next`` of a
removed element keeps working) so iterators never see a torn list.
"""

from __future__ import annotations

import threading

from cometbft_tpu.libs import sync as libsync
from typing import Any, Iterator, Optional


class CElement:
    __slots__ = ("value", "_next", "_prev", "removed", "_list")

    def __init__(self, value: Any, list_: "CList"):
        self.value = value
        self._next: Optional[CElement] = None
        self._prev: Optional[CElement] = None
        self.removed = False
        self._list = list_

    def next(self) -> Optional["CElement"]:
        with self._list._mtx:
            return self._next

    def prev(self) -> Optional["CElement"]:
        with self._list._mtx:
            return self._prev

    def next_wait(self, timeout: Optional[float] = None) -> Optional["CElement"]:
        """Block until this element has a next, it is removed, or timeout."""
        with self._list._mtx:
            deadline = None
            if timeout is not None:
                import time

                deadline = time.monotonic() + timeout
            while self._next is None and not self.removed:
                if deadline is None:
                    self._list._cond.wait()
                else:
                    import time

                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._list._cond.wait(remaining):
                        if self._next is None and not self.removed:
                            return None
            return self._next


class CList:
    def __init__(self):
        self._mtx = libsync.rlock("clist")
        self._cond = libsync.condition(self._mtx)
        self._head: Optional[CElement] = None
        self._tail: Optional[CElement] = None
        self._len = 0

    def __len__(self) -> int:
        with self._mtx:
            return self._len

    def front(self) -> Optional[CElement]:
        with self._mtx:
            return self._head

    def back(self) -> Optional[CElement]:
        with self._mtx:
            return self._tail

    def front_wait(self, timeout: Optional[float] = None) -> Optional[CElement]:
        """Block until the list is non-empty (reference: WaitChan on root)."""
        with self._mtx:
            if self._head is not None:
                return self._head
            self._cond.wait(timeout)
            return self._head

    def push_back(self, value: Any) -> CElement:
        el = CElement(value, self)
        with self._mtx:
            if self._tail is None:
                self._head = self._tail = el
            else:
                el._prev = self._tail
                self._tail._next = el
                self._tail = el
            self._len += 1
            self._cond.notify_all()
        return el

    def remove(self, el: CElement) -> Any:
        with self._mtx:
            if el.removed:
                return el.value
            el.removed = True
            if el._prev is not None:
                el._prev._next = el._next
            else:
                self._head = el._next
            if el._next is not None:
                el._next._prev = el._prev
            else:
                self._tail = el._prev
            # keep el._next so in-flight iterators can continue
            el._prev = None
            self._len -= 1
            self._cond.notify_all()
            return el.value

    def __iter__(self) -> Iterator[CElement]:
        el = self.front()
        while el is not None:
            if not el.removed:
                yield el
            el = el.next()
