"""Flow-rate monitoring and limiting (reference: internal/flowrate).

Sliding-window rate monitor used by MConnection channels (send-rate
limiting) and blocksync peers (timeout detection).
"""

from __future__ import annotations

import threading
import time


class Monitor:
    """Tracks transfer rate over an exponentially-weighted window
    (reference: internal/flowrate/flowrate.go Monitor)."""

    def __init__(self, sample_period: float = 0.1, window: float = 1.0):
        self._lock = threading.Lock()
        self.sample_period = sample_period
        self.window = window
        self.start = time.monotonic()
        self.total = 0
        self._rate = 0.0  # EWMA bytes/sec
        self._acc = 0  # bytes since last sample
        self._last_sample = self.start

    def update(self, n: int) -> None:
        with self._lock:
            self.total += n
            self._acc += n
            self._maybe_sample()

    def _maybe_sample(self) -> None:
        now = time.monotonic()
        elapsed = now - self._last_sample
        if elapsed < self.sample_period:
            return
        inst = self._acc / elapsed
        alpha = 1.0 - pow(0.5, elapsed / self.window)
        self._rate += alpha * (inst - self._rate)
        self._acc = 0
        self._last_sample = now

    def rate(self) -> float:
        """Current bytes/sec estimate."""
        with self._lock:
            self._maybe_sample()
            return self._rate

    def avg_rate(self) -> float:
        with self._lock:
            dt = time.monotonic() - self.start
            return self.total / dt if dt > 0 else 0.0

    def limit(self, want: int, max_rate: int) -> int:
        """How many of ``want`` bytes may be sent now to stay under
        ``max_rate`` bytes/sec; sleeps briefly when over budget
        (reference: flowrate.go Limit)."""
        if max_rate <= 0:
            return want
        while self.rate() > max_rate:
            time.sleep(self.sample_period / 2)
        return want
