"""Minimal jax-free latency histogram — the shared data shape between the
hot-path stats modules (``verifysched/stats``, ``ops/dispatch_stats``) and
the Prometheus renderers in ``libs/metrics`` (``CallbackHistogram`` /
``LabeledCallbackHistogram``).

``Histo.observe`` is a linear bucket scan (the bound lists are ~a dozen
entries; a binary search would cost more in constant factor), guarded by
the CALLER's lock — the stats modules already serialize their counters
behind one lock each, so this class carries none of its own.
"""

from __future__ import annotations

# Submit->verdict / queue-wait style latencies: sub-millisecond coalescing
# up through multi-second degraded-host tails.
LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 10.0,
)

# Device dispatch wall times: ~ms kernel launches up through cold-compile
# and watchdog-deadline territory.
DISPATCH_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 30.0,
)


class Histo:
    """Fixed-bound histogram: per-bucket counts + sum + count.

    NOT thread-safe by itself — callers observe under their own stats
    lock (one lock acquisition covers the histogram AND the adjacent
    counters, instead of paying two)."""

    __slots__ = ("bounds", "counts", "sum", "n")

    def __init__(self, bounds=LATENCY_BUCKETS_S):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.sum += v
        self.n += 1
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def to_dict(self) -> dict:
        """The wire shape ``CallbackHistogram`` renders: non-cumulative
        per-bucket counts aligned with ``bounds`` (+1 overflow), sum and
        count, plus approximate p50/p99 (bucket upper bounds — good
        enough for soak rows and trend lines)."""
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.n,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
        }

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile (0 when
        empty; the overflow bucket reports the largest finite bound)."""
        if self.n == 0:
            return 0.0
        target = max(1, int(q * self.n))
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                return self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
        return self.bounds[-1]
