"""Service lifecycle (reference: libs/service/service.go).

Every long-lived object embeds a ``BaseService``: Start/Stop are idempotent
state transitions guarded by atomic flags; ``on_start``/``on_stop`` hooks do
the real work; ``wait`` blocks until stopped.  Unlike the reference's
goroutine-per-service model, threads are created only by services that need
them — the lifecycle contract is the shared part.
"""

from __future__ import annotations

import threading


class ServiceError(Exception):
    pass


class AlreadyStartedError(ServiceError):
    pass


class AlreadyStoppedError(ServiceError):
    pass


class BaseService:
    """Reference: libs/service/service.go BaseService."""

    def __init__(self, name: str = ""):
        self.name = name or type(self).__name__
        self._started = False
        self._stopped = False
        self._quit = threading.Event()
        self._lifecycle_mtx = threading.Lock()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        with self._lifecycle_mtx:
            if self._stopped:
                raise AlreadyStoppedError(self.name)
            if self._started:
                raise AlreadyStartedError(self.name)
            self._started = True
        self.on_start()

    def stop(self) -> None:
        with self._lifecycle_mtx:
            if self._stopped:
                return
            self._stopped = True
        self._quit.set()
        self.on_stop()

    def reset(self) -> None:
        with self._lifecycle_mtx:
            if not self._stopped:
                raise ServiceError(f"cannot reset running service {self.name}")
            self._started = False
            self._stopped = False
            self._quit = threading.Event()
        self.on_reset()

    # -- hooks ------------------------------------------------------------

    def on_start(self) -> None:  # pragma: no cover - default no-op
        pass

    def on_stop(self) -> None:  # pragma: no cover - default no-op
        pass

    def on_reset(self) -> None:  # pragma: no cover - default no-op
        pass

    # -- state ------------------------------------------------------------

    @property
    def is_running(self) -> bool:
        return self._started and not self._stopped

    def quit_event(self) -> threading.Event:
        return self._quit

    def wait(self, timeout: float | None = None) -> bool:
        return self._quit.wait(timeout)
