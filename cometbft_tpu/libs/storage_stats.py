"""Process-wide counters for the disk-fault supervisor (libs/diskguard).

Deliberately free of jax imports, like ``ops/dispatch_stats``: the
``cometbft_storage_*`` metrics on /metrics and the ``storage`` section of
``tracing.trace_document()`` read these through callback gauges, and a
scrape must never be the thing that initializes an accelerator backend.
``libs/diskguard.py`` (and the WAL's boot-time tail repair) write them.

Per surface (wal / privval / state / blackbox / exec_cache / indexer /
status — docs/storage-robustness.md):
  * ``writes``   — guarded durable write/replace/batch operations
  * ``fsyncs``   — guarded fsync/flush-to-disk operations
  * ``retries``  — degraded-surface retry attempts after transient IO errors
  * ``drops``    — degraded-surface operations abandoned after retries
  * ``fatals``   — fail-stop surface IO failures (each one halted a node)
  * ``injected`` — faults the deterministic injector fired (sim/bench only)
  * ``repairs`` / ``repaired_bytes`` — boot-time crash-consistency scrub
    actions (WAL corrupt-tail truncation)
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()

_KEYS = (
    "writes",
    "fsyncs",
    "retries",
    "drops",
    "fatals",
    "injected",
    "repairs",
    "repaired_bytes",
)


def _zero() -> dict:
    return {"surfaces": {}}


_STATS = _zero()


def _surface(name: str) -> dict:
    s = _STATS["surfaces"].get(name)
    if s is None:
        s = {k: 0 for k in _KEYS}
        _STATS["surfaces"][name] = s
    return s


def record_op(surface: str, op: str) -> None:
    with _LOCK:
        s = _surface(surface)
        if op in ("fsync", "flush"):
            s["fsyncs"] += 1
        else:
            s["writes"] += 1


def record_retry(surface: str) -> None:
    with _LOCK:
        _surface(surface)["retries"] += 1


def record_drop(surface: str) -> None:
    with _LOCK:
        _surface(surface)["drops"] += 1


def record_fatal(surface: str) -> None:
    with _LOCK:
        _surface(surface)["fatals"] += 1


def record_injected(surface: str) -> None:
    with _LOCK:
        _surface(surface)["injected"] += 1


def record_repair(surface: str, dropped_bytes: int) -> None:
    with _LOCK:
        s = _surface(surface)
        s["repairs"] += 1
        s["repaired_bytes"] += int(dropped_bytes)


def per_surface(key: str) -> dict:
    """{surface: value} for one counter — the shape
    ``metrics.LabeledCallbackGauge`` reads at scrape time."""
    with _LOCK:
        return {
            name: s[key] for name, s in _STATS["surfaces"].items()
        }


def snapshot() -> dict:
    with _LOCK:
        surfaces = {
            name: dict(s) for name, s in _STATS["surfaces"].items()
        }
    totals = {k: sum(s[k] for s in surfaces.values()) for k in _KEYS}
    totals["fatal"] = totals["fatals"] > 0
    return {"surfaces": surfaces, "totals": totals}


def faulted() -> bool:
    """True when any surface saw injector or real-IO trouble this process
    (retries, drops, fatals, injections, repairs) — the gate for
    attaching a ``storage`` block to sim soak rows."""
    snap = snapshot()["totals"]
    return bool(
        snap["retries"]
        or snap["drops"]
        or snap["fatals"]
        or snap["injected"]
        or snap["repairs"]
    )


def reset() -> None:
    global _STATS
    with _LOCK:
        _STATS = _zero()
