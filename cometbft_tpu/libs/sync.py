"""Lock factories with an opt-in deadlock-detecting mode.

Reference: libs/sync/deadlock.go — the ``deadlock`` build tag swaps
sync.Mutex/RWMutex for go-deadlock's watchdog variants in every package
that imports libs/sync.  Here the swap is environmental:

    COMETBFT_TPU_DEADLOCK=1            enable watchdog locks
    COMETBFT_TPU_DEADLOCK_TIMEOUT=30   seconds before declaring deadlock

When enabled, ``lock()``/``rlock()`` return wrappers whose blocking
acquire gives up after the timeout, dumps every live thread's stack (the
evidence needed to find the cycle), and raises ``DeadlockError`` —
turning a silent hang into a diagnosable failure, exactly what
go-deadlock does for the reference's race CI.
"""

from __future__ import annotations

import io
import os
import sys
import threading
import traceback


class DeadlockError(RuntimeError):
    pass


def _enabled() -> bool:
    return os.environ.get("COMETBFT_TPU_DEADLOCK", "") not in ("", "0")


def _timeout() -> float:
    return float(os.environ.get("COMETBFT_TPU_DEADLOCK_TIMEOUT", "30"))


def _all_stacks() -> str:
    out = io.StringIO()
    threads = {t.ident: t for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        t = threads.get(ident)
        out.write(f"\n--- {(t.name if t else ident)} ---\n")
        out.write("".join(traceback.format_stack(frame)))
    return out.getvalue()


class _WatchdogLock:
    """Wraps a Lock/RLock; blocking acquires time out loudly."""

    def __init__(self, inner, name: str = ""):
        self._inner = inner
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not blocking:
            return self._inner.acquire(False)
        limit = _timeout() if timeout in (-1, None) else min(timeout, _timeout())
        if self._inner.acquire(True, limit):
            return True
        if timeout not in (-1, None) and timeout <= _timeout():
            return False  # caller asked for a shorter timeout; not a deadlock
        raise DeadlockError(
            f"lock {self._name or repr(self._inner)} not acquired within "
            f"{limit}s — likely deadlock.  All thread stacks:{_all_stacks()}"
        )

    def release(self) -> None:
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    # RLock introspection passthroughs some callers use
    def _is_owned(self):
        return self._inner._is_owned()


def lock(name: str = ""):
    """A mutex; watchdog-wrapped when COMETBFT_TPU_DEADLOCK is set."""
    inner = threading.Lock()
    return _WatchdogLock(inner, name) if _enabled() else inner


def rlock(name: str = ""):
    """A re-entrant mutex; watchdog-wrapped when enabled."""
    inner = threading.RLock()
    return _WatchdogLock(inner, name) if _enabled() else inner


def condition(lk=None):
    """A Condition over a (possibly watchdog) lock.  Conditions need the
    raw primitive, so watchdog mode unwraps transparently."""
    if isinstance(lk, _WatchdogLock):
        lk = lk._inner
    return threading.Condition(lk)
