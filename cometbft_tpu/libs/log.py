"""Structured key-value logger (reference: libs/log).

TMFmt-style lines: ``LEVEL[time] message key=value ...`` with a module label.
Lazy values: pass a zero-arg callable and it is only rendered when the line is
actually emitted (reference: log.NewLazyBlockHash, state.go:1866).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, Optional, TextIO

DEBUG, INFO, WARN, ERROR, NONE = 0, 1, 2, 3, 4
_NAMES = {DEBUG: "DBUG", INFO: "INFO", WARN: "WARN", ERROR: "ERRO"}
_LEVELS = {"debug": DEBUG, "info": INFO, "warn": WARN, "error": ERROR, "none": NONE}

_write_lock = threading.Lock()


def parse_level(s: str) -> int:
    return _LEVELS.get(s.lower(), INFO)


def _render(v: Any) -> str:
    if callable(v):
        v = v()
    if isinstance(v, bytes):
        v = v.hex()[:16].upper()
    s = str(v)
    if " " in s:
        return repr(s)
    return s


class Logger:
    def __init__(
        self,
        level: int = INFO,
        out: Optional[TextIO] = None,
        module: str = "",
        **bound: Any,
    ):
        self.level = level
        self.out = out if out is not None else sys.stderr
        self.module = module
        self.bound = bound

    def with_(self, module: str = "", **kv: Any) -> "Logger":
        return Logger(
            self.level,
            self.out,
            module or self.module,
            **{**self.bound, **kv},
        )

    def _log(self, level: int, msg: str, kv: dict[str, Any]) -> None:
        if level < self.level:
            return
        ts = time.strftime("%H:%M:%S", time.localtime())
        parts = [f"{_NAMES[level]}[{ts}] {msg}"]
        if self.module:
            parts.append(f"module={self.module}")
        for k, v in {**self.bound, **kv}.items():
            parts.append(f"{k}={_render(v)}")
        with _write_lock:
            try:
                print(" ".join(parts), file=self.out)
            except ValueError:
                # daemon threads may log during interpreter shutdown after
                # the sink (pytest capture, a closed pipe) is gone; dropping
                # the line beats a traceback storm on teardown
                pass

    def debug(self, msg: str, **kv: Any) -> None:
        self._log(DEBUG, msg, kv)

    def info(self, msg: str, **kv: Any) -> None:
        self._log(INFO, msg, kv)

    def warn(self, msg: str, **kv: Any) -> None:
        self._log(WARN, msg, kv)

    def error(self, msg: str, **kv: Any) -> None:
        self._log(ERROR, msg, kv)


def nop_logger() -> Logger:
    return Logger(level=NONE)


def test_logger() -> Logger:
    return Logger(level=_LEVELS.get("info", INFO), out=sys.stdout)
