"""Version constants (reference: version/version.go:1-18)."""

# Semantic version of this framework.
__version__ = "0.1.0"
CMT_SEMVER = __version__

# Protocol versions. Block/P2P protocol numbers track the reference so that
# genesis docs and headers carry comparable version metadata.
BLOCK_PROTOCOL = 11
P2P_PROTOCOL = 9
ABCI_SEMVER = "2.2.0"
