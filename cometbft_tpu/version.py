"""Version constants (reference: version/version.go:1-18)."""

import os

# Semantic version of this framework.  The env override is the e2e
# binary-upgrade analog: the reference swaps docker images
# (test/e2e/runner/perturb.go:88-131); here the restarted OS process
# reports — and handshakes as — the upgraded version.
__version__ = os.environ.get("COMETBFT_TPU_SEMVER", "0.1.0")
CMT_SEMVER = __version__

# Protocol versions. Block/P2P protocol numbers track the reference so that
# genesis docs and headers carry comparable version metadata.
BLOCK_PROTOCOL = 11
P2P_PROTOCOL = 9
ABCI_SEMVER = "2.2.0"
