from cometbft_tpu.proxy.multi_app_conn import (
    AppConns,
    ClientCreator,
    local_client_creator,
    remote_client_creator,
    new_multi_app_conn,
)
