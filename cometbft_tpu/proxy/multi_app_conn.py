"""Multi-app-conn proxy: 4 named ABCI connections.

Reference: proxy/multi_app_conn.go:19-160 — the node talks to the app over
four logical connections (consensus, mempool, query, snapshot) so a slow
query can never head-of-line-block consensus.  For the builtin (in-process)
app all four share one lock (reference local client semantics); for a socket
app each is a separate TCP/unix connection.

The mempool connection additionally carries the batched-CheckTx surface
(``Client.check_txs``, docs/tx-ingest.md): the ingest coalescer admits a
whole gossip burst in one round trip, with a per-tx loop fallback for
clients/apps that predate the batch method.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from cometbft_tpu.abci.application import Application
from cometbft_tpu.abci.client import Client, LocalClient, SocketClient

ClientCreator = Callable[[], Client]


def local_client_creator(app: Application) -> ClientCreator:
    """All connections share one mutex (reference: proxy/client.go
    NewLocalClientCreator)."""
    lock = threading.Lock()

    def create() -> Client:
        return LocalClient(app, lock)

    return create


def remote_client_creator(address: str, transport: str = "socket") -> ClientCreator:
    """Socket (length-prefixed framing) or gRPC flavor, per the reference's
    abci config key (abci/client: socketClient vs grpcClient)."""

    def create() -> Client:
        if transport == "grpc" or address.startswith("grpc://"):
            from cometbft_tpu.abci.grpc_abci import GRPCClient

            return GRPCClient(address)
        return SocketClient(address)

    return create


class AppConns:
    """Holds the 4 connections; start() performs the Echo handshake on each."""

    def __init__(self, creator: ClientCreator):
        self._creator = creator
        self.consensus: Optional[Client] = None
        self.mempool: Optional[Client] = None
        self.query: Optional[Client] = None
        self.snapshot: Optional[Client] = None

    def start(self) -> None:
        self.query = self._creator()
        self.snapshot = self._creator()
        self.mempool = self._creator()
        self.consensus = self._creator()
        for c in (self.query, self.snapshot, self.mempool, self.consensus):
            c.echo("multi_app_conn-handshake")

    def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c is not None:
                c.close()


def new_multi_app_conn(creator: ClientCreator) -> AppConns:
    return AppConns(creator)
