"""Bounded model checker for Tendermint voting safety.

The reference ships Ivy proofs of (accountable) safety
(spec/ivy-proofs/{classic_safety,accountable_safety_1,accountable_safety_2}.ivy).
This module is the runnable counterpart: an exhaustive exploration of the
voting rules for small configurations, machine-checking

  1. **Agreement** — with f < n/3 byzantine validators, no two
     conflicting commit certificates (+2/3 precommits for different
     values, any rounds) are ever reachable (classic_safety.ivy).
  2. **Quorum accountability** — any two +2/3 certificates share at
     least f+1 validators, so conflicting decisions always expose f+1
     misbehaving signers (accountable_safety lemmas).

Soundness of the abstraction: the adversary controls every source of
nondeterminism *upward* — proposal values (per-receiver when the
proposer is byzantine), which rule-allowed action each honest validator
takes (modeling arbitrary message asynchrony: any subset-visibility
schedule yields one of the enumerated rule-allowed actions; "didn't see
it" is always among them), and the byzantine validators' votes, which
EQUIVocate (counted toward every value simultaneously — the supremum of
per-receiver equivocation).  Every real execution of the modeled
configuration maps to one explored branch, so a property verified here
holds for every real execution at this configuration size.

Voting rules modeled (arXiv:1807.04938 pseudocode lines 22-27/36-43;
implementation: cometbft_tpu/consensus/state.py enter_prevote /
enter_precommit / try_finalize_commit):

  * prevote(v) at round r: allowed iff not locked, or locked on v, or a
    proof-of-lock POL(v, vr) exists with locked_round <= vr < r (the
    unlock rule); nil always allowed.
  * precommit(v) at round r: allowed iff POL(v, r) exists (a +2/3
    prevote quorum this round, byzantine equivocation included); sets
    locked = (v, r).
  * commit certificate (v, r): +2/3 precommits for v in round r; a
    validator decides on seeing any certificate, from any round.
"""

from __future__ import annotations

from itertools import product

NIL = "nil"
EQUIV = "equiv"  # byzantine equivocation: counts for every value


def quorum(n: int) -> int:
    """Smallest integer strictly greater than 2n/3."""
    return (2 * n) // 3 + 1


class ModelConfig:
    def __init__(self, n=4, byz=(3,), rounds=2, values=("A", "B")):
        self.n = n
        self.byz = frozenset(byz)
        self.honest = tuple(i for i in range(n) if i not in self.byz)
        self.rounds = rounds
        self.values = values
        self.q = quorum(n)


def _count(votes, v) -> int:
    """Votes for v, counting byzantine EQUIV toward every value."""
    return sum(1 for x in votes if x == v or x == EQUIV)


class SafetyViolation(AssertionError):
    pass


def explore(cfg: ModelConfig):
    """Exhaustive BFS over rounds with state memoization.

    State: (locks, pols, certs) — per-honest (locked_value,
    locked_round); the set of proof-of-lock (value, round) pairs that
    actually existed; the set of commit-certificate values reached so
    far.  Raises SafetyViolation if conflicting certificates become
    reachable.  Yields (round, states) after each round.
    """
    init = (tuple((None, -1) for _ in cfg.honest), frozenset(), frozenset())
    states = {init}
    byz_choices = (NIL, EQUIV)  # EQUIV dominates any single-value vote

    for rnd in range(cfg.rounds):
        proposer_byz = (rnd % cfg.n) in cfg.byz
        next_states = set()
        for locks, pols, certs in states:
            # proposal values seen by each honest validator: one shared
            # value for an honest proposer, per-receiver for a byzantine
            if proposer_byz:
                proposal_assignments = product(
                    cfg.values, repeat=len(cfg.honest)
                )
            else:
                proposal_assignments = (
                    (v,) * len(cfg.honest) for v in cfg.values
                )
            for proposals in proposal_assignments:
                pv_options = []
                for i, _h in enumerate(cfg.honest):
                    lv, lr = locks[i]
                    proposal = proposals[i]
                    opts = [NIL]
                    if lv is None or lv == proposal:
                        opts.append(proposal)
                    elif any(
                        pv == proposal and lr <= vr < rnd
                        for (pv, vr) in pols
                    ):
                        opts.append(proposal)  # unlock via real POL
                    pv_options.append(opts)
                for byz_pv in product(byz_choices, repeat=len(cfg.byz)):
                    for honest_pv in product(*pv_options):
                        prevotes = list(honest_pv) + list(byz_pv)
                        new_pols = frozenset(
                            {
                                (v, rnd)
                                for v in cfg.values
                                if _count(prevotes, v) >= cfg.q
                            }
                        ) | pols
                        round_pols = [
                            v for (v, r) in new_pols if r == rnd
                        ]
                        pc_options = [
                            [NIL] + round_pols for _ in cfg.honest
                        ]
                        for byz_pc in product(
                            byz_choices, repeat=len(cfg.byz)
                        ):
                            for honest_pc in product(*pc_options):
                                precommits = list(honest_pc) + list(byz_pc)
                                new_locks = tuple(
                                    (pc, rnd) if pc != NIL else locks[i]
                                    for i, pc in enumerate(honest_pc)
                                )
                                new_certs = certs | {
                                    v
                                    for v in cfg.values
                                    if _count(precommits, v) >= cfg.q
                                }
                                if len(new_certs) > 1:
                                    raise SafetyViolation(
                                        f"conflicting commit certificates "
                                        f"{sorted(new_certs)} reachable by "
                                        f"round {rnd} (locks={locks}, "
                                        f"pols={sorted(pols)})"
                                    )
                                next_states.add(
                                    (new_locks, new_pols, new_certs)
                                )
        states = next_states
        yield rnd, states


def check_agreement(cfg: ModelConfig | None = None) -> int:
    """Run the exploration to completion; returns #reachable states.
    Raises SafetyViolation if conflicting certificates are reachable."""
    cfg = cfg or ModelConfig()
    total = 0
    for _, states in explore(cfg):
        total = len(states)
    return total


def check_quorum_accountability(n: int = 4) -> None:
    """Any two +2/3 quorums of n validators intersect in >= f+1 members
    (f = max byzantine with 3f < n): conflicting commit certificates
    always expose at least f+1 double-signers.  Exhaustive over all
    quorum pairs (accountable_safety_1.ivy's core lemma)."""
    from itertools import combinations

    q = quorum(n)
    f = (n - 1) // 3
    members = range(n)
    for a_size in range(q, n + 1):
        for b_size in range(q, n + 1):
            for qa in combinations(members, a_size):
                for qb in combinations(members, b_size):
                    inter = set(qa) & set(qb)
                    assert len(inter) >= f + 1, (
                        f"quorums {qa} and {qb} intersect in only "
                        f"{len(inter)} < f+1 = {f+1} members"
                    )


def check_agreement_violated_with_excess_byzantine() -> bool:
    """Sanity check of the checker itself: with 2 byzantine of 4
    (f >= n/3) including the round-0 proposer (whose equivocating
    proposals split the honest prevotes), agreement MUST be violable —
    the checker must find it."""
    cfg = ModelConfig(n=4, byz=(0, 3), rounds=1)
    try:
        check_agreement(cfg)
    except SafetyViolation:
        return True
    return False


def check_unlock_rule_necessity() -> bool:
    """Drop the lock discipline (validators may always prevote the
    proposal) and the checker must find a violation — demonstrating the
    POL/lock rules are what carries safety, not the quorum size alone."""
    cfg = ModelConfig(n=4, byz=(3,), rounds=2)
    # re-run the exploration with every validator treated as never
    # locked (prevoting the proposal is always allowed)
    init = (tuple((None, -1) for _ in cfg.honest), frozenset(), frozenset())
    states = {init}
    byz_choices = (NIL, EQUIV)
    try:
        for rnd in range(cfg.rounds):
            next_states = set()
            for locks, pols, certs in states:
                for proposal in cfg.values:
                    pv_opts = [[NIL, proposal] for _ in cfg.honest]
                    for byz_pv in product(byz_choices, repeat=len(cfg.byz)):
                        for honest_pv in product(*pv_opts):
                            prevotes = list(honest_pv) + list(byz_pv)
                            new_pols = pols | {
                                (v, rnd)
                                for v in cfg.values
                                if _count(prevotes, v) >= cfg.q
                            }
                            round_pols = [v for (v, r) in new_pols if r == rnd]
                            pc_opts = [[NIL] + round_pols for _ in cfg.honest]
                            for byz_pc in product(
                                byz_choices, repeat=len(cfg.byz)
                            ):
                                for honest_pc in product(*pc_opts):
                                    precommits = list(honest_pc) + list(byz_pc)
                                    new_certs = certs | {
                                        v
                                        for v in cfg.values
                                        if _count(precommits, v) >= cfg.q
                                    }
                                    if len(new_certs) > 1:
                                        raise SafetyViolation("no-lock")
                                    next_states.add(
                                        (locks, frozenset(new_pols), new_certs)
                                    )
            states = next_states
    except SafetyViolation:
        return True
    return False
