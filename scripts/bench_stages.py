"""Per-stage profile of the verify kernel on the live chip.

Times decompress (2 sqrt chains), table build, the 64-position ladder, and
the final cofactor/identity check separately, to direct optimization work
(VERDICT r2 #4: profile per-stage first)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/.cache/jax")

import numpy as np
import jax
import jax.numpy as jnp

from cometbft_tpu.crypto import ed25519_ref as ref
from cometbft_tpu.ops import fe25519 as fe, ed25519_point as ep, verify as ov


def timed(fn, args, label, reps=5):
    out = jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        # force a device->host copy of one leaf: axon's block_until_ready
        # can return early for repeat executions, host transfer cannot
        leaf = jax.tree_util.tree_leaves(r)[0]
        np.asarray(leaf)
        ts.append(time.perf_counter() - t0)
    print(f"{label:28s} {min(ts)*1e3:9.2f} ms")
    return out


def main():
    n = int(os.environ.get("BENCH_BATCH", "8192"))
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = i.to_bytes(4, "little") * 8
        pubs.append(ref.pubkey_from_seed(seed))
        msgs.append(b"bench-%d" % i)
        sigs.append(ref.sign(seed, b"bench-%d" % i))
    arrays, _, _ = ov.prepare_batch(pubs, msgs, sigs)
    dev = {k: jnp.asarray(v) for k, v in arrays.items()}
    print(f"batch={dev['a_bytes'].shape[0]} platform={jax.devices()[0].platform}")

    @jax.jit
    def stage_unpack(a_bytes, r_bytes, s_bytes, m_bytes):
        ya, sa = fe.unpack255(a_bytes)
        yr, sr = fe.unpack255(r_bytes)
        return ya.v, sa, yr.v, sr, fe.signed_digits_msb_first(s_bytes), fe.signed_digits_msb_first(m_bytes)

    @jax.jit
    def stage_decompress(a_bytes):
        ya, sa = fe.unpack255(a_bytes)
        ok, p = ep.decompress(ya, sa)
        return ok, p.x.v, p.y.v, p.t.v

    @jax.jit
    def stage_table(a_bytes):
        ya, sa = fe.unpack255(a_bytes)
        _, a = ep.decompress(ya, sa)
        return ep.build_table_a(a)

    @jax.jit
    def stage_ladder(a_bytes, s_bytes, m_bytes):
        ya, sa = fe.unpack255(a_bytes)
        _, a = ep.decompress(ya, sa)
        p = ep.double_base_scalar_mul(
            fe.signed_digits_msb_first(s_bytes), fe.signed_digits_msb_first(m_bytes), a
        )
        return p.x.v, p.y.v, p.z.v

    @jax.jit
    def full(a_bytes, r_bytes, s_bytes, m_bytes, s_ok):
        return ov.verify_core(a_bytes, r_bytes, s_bytes, m_bytes, s_ok)

    timed(stage_unpack, (dev["a_bytes"], dev["r_bytes"], dev["s_bytes"], dev["m_bytes"]), "unpack+digits")
    timed(stage_decompress, (dev["a_bytes"],), "decompress A (1x sqrt)")
    timed(stage_table, (dev["a_bytes"],), "decompress+table16 A")
    timed(stage_ladder, (dev["a_bytes"], dev["s_bytes"], dev["m_bytes"]), "decompress+table+ladder")
    out = timed(full, tuple(dev[k] for k in ("a_bytes", "r_bytes", "s_bytes", "m_bytes", "s_ok")), "full verify_core")
    acc = np.asarray(out)
    print("accept:", int(acc.sum()), "/", n)


if __name__ == "__main__":
    main()
