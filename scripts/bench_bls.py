"""BLS12-381 benchmark: RLC batch verification + G1 MSM (BASELINE.json's
"BLS12-381 aggregate" tracked config).

Prints one JSON line per stage and a final summary line:
  {"metric": "bls_batch_verify", "value": sigs/s, ...}

Stages (each stands alone so a hang leaves the completed ones on stdout):
  * host RLC batch verify at n=16 and n=64 (the consensus seam path —
    crypto/batch.BlsBatchVerifier; pairings on the host oracle)
  * single-verify baseline (what the seam replaces: 2 pairings/signature)
  * G1 batch scalar-mul on the device (ops/bls_g1) vs host, when a
    non-CPU platform is up — the TPU piece of the RLC path

CPU smoke: COMETBFT_TPU_JAX_PLATFORM=cpu python scripts/bench_bls.py
(device stage reports platform=cpu and skips the kernel).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(metric, value, unit, **extra):
    rec = {"metric": metric, "value": round(value, 2), "unit": unit}
    rec.update(extra)
    print(json.dumps(rec), flush=True)
    return rec


def _fixture(n):
    from cometbft_tpu.crypto.keys import Bls12381PrivKey

    privs = [Bls12381PrivKey.from_secret(b"bench-%d" % i) for i in range(n)]
    pubs = [p.pub_key().bytes() for p in privs]
    msgs = [b"bls bench %d" % i for i in range(n)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    return pubs, msgs, sigs


def main() -> None:
    import jax

    plat = os.environ.get("COMETBFT_TPU_JAX_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)

    from cometbft_tpu.crypto import batch as cbatch
    from cometbft_tpu.crypto import bls12381 as bls

    results = {}
    # impl: native C++ pairing (the blst analog) or pure-Python oracle
    impl = "native" if bls._nat() is not None else "python"
    results["impl"] = impl

    # single-verify baseline
    pubs, msgs, sigs = _fixture(4)
    t0 = time.perf_counter()
    for p, m, s in zip(pubs, msgs, sigs):
        assert bls.verify(p, m, s)
    single_s = (time.perf_counter() - t0) / 4
    results["single_verify_ms"] = round(single_s * 1e3, 1)
    _emit("bls_single_verify", 1.0 / single_s, "verifies/s")

    # 100-sig aggregate verify (VERDICT r4 #4's named milestone; the
    # reference's blst path does this in single-digit ms — key_bls12381.go)
    n = 100
    pubs, msgs, sigs = _fixture(n)
    agg = bls.aggregate_signatures(sigs)
    assert agg is not None
    t0 = time.perf_counter()
    ok = bls.aggregate_verify(pubs, msgs, agg)
    agg_s = time.perf_counter() - t0
    assert ok
    results["aggregate100_ms"] = round(agg_s * 1e3, 1)
    _emit(
        "bls_aggregate_verify", n / agg_s, "verifies/s", batch=n,
        total_ms=round(agg_s * 1e3, 1), impl=impl,
    )

    # RLC batch verify through the consensus seam
    for n in (16, 64):
        pubs, msgs, sigs = _fixture(n)
        bv = cbatch.BlsBatchVerifier()
        for p, m, s in zip(pubs, msgs, sigs):
            bv.add(p, m, s)
        t0 = time.perf_counter()
        ok, bits = bv.verify()
        dt = time.perf_counter() - t0
        assert ok and all(bits)
        results[f"batch{n}_s"] = round(dt, 3)
        results[f"batch{n}_vps"] = round(n / dt, 2)
        _emit(
            "bls_batch_verify", n / dt, "verifies/s", batch=n,
            speedup_vs_single=round(single_s * n / dt, 2),
        )

    # device G1 batch scalar-mul (the TPU half of the RLC path)
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "unavailable"
    if platform != "cpu" and platform != "unavailable":
        import secrets

        from cometbft_tpu.ops import bls_g1 as g1

        n = int(os.environ.get("BENCH_BLS_MSM", "256"))
        gen = bls.E1.affine(bls.G1_GEN)
        pts = [gen] * n
        rs = [secrets.randbits(128) | 1 for _ in range(n)]
        t0 = time.perf_counter()
        out = g1.batch_scalar_mul(pts, rs, nbits=128)
        compile_and_run = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = g1.batch_scalar_mul(pts, rs, nbits=128)
        dev_s = time.perf_counter() - t0
        assert len(out) == n
        # host comparison on a small slice
        t0 = time.perf_counter()
        for r in rs[:8]:
            bls.E1.mul_scalar(bls.G1_GEN, r)
        host_s = (time.perf_counter() - t0) / 8 * n
        results["g1_mul_device_s"] = round(dev_s, 3)
        results["g1_mul_host_est_s"] = round(host_s, 3)
        _emit(
            "bls_g1_batch_scalar_mul", n / dev_s, "points/s", batch=n,
            platform=platform, compile_s=round(compile_and_run, 1),
            host_points_per_s=round(n / host_s, 2),
        )

    final = {
        "metric": "bls_batch_verify",
        "value": results.get("batch64_vps", 0.0),
        "unit": "verifies/s",
        "platform": platform,
    }
    final.update(results)
    print(json.dumps(final), flush=True)
    out_path = os.environ.get("BENCH_BLS_OUT")
    if out_path:
        with open(out_path, "w") as f:
            f.write(json.dumps(final) + "\n")


if __name__ == "__main__":
    main()
