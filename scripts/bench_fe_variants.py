"""Microbenchmark of field-mul implementation variants on the live chip.

Times k chained batched GF(2^255-19) multiplications per variant to pick
the design for the round-2 kernel rewrite.  Not part of the test suite.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/.cache/jax")

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

B = int(os.environ.get("B", "8192"))
K = int(os.environ.get("K", "64"))  # chained muls per timed call

P_INT = 2**255 - 19


def timeit(fn, *args, reps=5):
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
        ts.append(time.perf_counter() - t0)
    return min(ts)


# --- variant 1: current repo mul (13-bit x 20, dot_general + scans) -------
from cometbft_tpu.ops import fe25519 as fe_old


@jax.jit
def chain_old(a, b):
    def body(c, _):
        return fe_old.mul(c, b), None

    c, _ = lax.scan(body, a, None, length=K)
    return c


# --- variant 2: 11-bit x 24 limbs, unrolled columns + parallel carry ------
N2, W2 = 24, 11
M2 = (1 << W2) - 1
NCOL2 = 2 * N2 - 1
# 2^264 mod p fold for carry-out of limb 23: 2^264 = 2^9*2^255 = 512*19 = 9728
FOLD2 = 19 * (1 << (N2 * W2 - 255))


def mul2(a, b):
    # a, b: (24, B) int32, limbs <= ~2^13
    cols = [None] * NCOL2
    for i in range(N2):
        prod = a[i][None, :] * b  # (24, B)
        for j in range(N2):
            k = i + j
            cols[k] = prod[j] if cols[k] is None else cols[k] + prod[j]
    x = jnp.stack(cols)  # (47, B)
    # fold high columns 24..46 into 0..22  (weight 2^264 ≡ 9728)
    lo = x[:N2]
    hi = x[N2:]
    lo = lo.at[: NCOL2 - N2].add(FOLD2 * hi)
    # parallel carry: 4 steps
    for _ in range(4):
        c = lo >> W2
        lo = (lo & M2) + jnp.concatenate(
            [FOLD2 * c[-1:], c[:-1]], axis=0
        )
    return lo


@jax.jit
def chain2(a, b):
    def body(c, _):
        return mul2(c, b), None

    c, _ = lax.scan(body, a, None, length=K)
    return c


# --- variant 3: f32 8-bit x 32 limbs ------------------------------------
N3, W3 = 32, 8
M3 = (1 << W3) - 1
NCOL3 = 2 * N3 - 1
FOLD3 = float(19 * (1 << (N3 * W3 - 255)))  # 2^256 ≡ 38


def mul3(a, b):
    # a, b: (32, B) f32, limbs < 2^8 (plus small headroom)
    cols = [None] * NCOL3
    for i in range(N3):
        prod = a[i][None, :] * b
        for j in range(N3):
            k = i + j
            cols[k] = prod[j] if cols[k] is None else cols[k] + prod[j]
    x = jnp.stack(cols)  # (63, B) values < 2^21 exact
    lo = x[:N3]
    hi = x[N3:]
    lo = lo.at[: NCOL3 - N3].add(FOLD3 * hi)
    for _ in range(4):
        c = jnp.floor(lo * (1.0 / 256.0))
        lo = (lo - 256.0 * c) + jnp.concatenate(
            [FOLD3 * c[-1:], c[:-1]], axis=0
        )
    return lo


@jax.jit
def chain3(a, b):
    def body(c, _):
        return mul3(c, b), None

    c, _ = lax.scan(body, a, None, length=K)
    return c


# --- correctness spot check + timing --------------------------------------
def limbs(val, n, w):
    out = np.zeros((n,), np.int64)
    for i in range(n):
        out[i] = val & ((1 << w) - 1)
        val >>= w
    return out


def unlimbs(x, w):
    v = 0
    for i in reversed(range(x.shape[0])):
        v = (v << w) + int(x[i])
    return v % P_INT


rng = np.random.default_rng(0)
av = int(rng.integers(0, 2**63)) * 12345 % P_INT
bv = int(rng.integers(0, 2**63)) * 98765 % P_INT
# expected: av * bv^K mod p
exp = av
for _ in range(K):
    exp = exp * bv % P_INT

a1 = jnp.asarray(np.broadcast_to(limbs(av, 20, 13)[:, None], (20, B)).astype(np.int32))
b1 = jnp.asarray(np.broadcast_to(limbs(bv, 20, 13)[:, None], (20, B)).astype(np.int32))
a2 = jnp.asarray(np.broadcast_to(limbs(av, N2, W2)[:, None], (N2, B)).astype(np.int32))
b2 = jnp.asarray(np.broadcast_to(limbs(bv, N2, W2)[:, None], (N2, B)).astype(np.int32))
a3 = jnp.asarray(np.broadcast_to(limbs(av, N3, W3)[:, None], (N3, B)).astype(np.float32))
b3 = jnp.asarray(np.broadcast_to(limbs(bv, N3, W3)[:, None], (N3, B)).astype(np.float32))

r1 = unlimbs(np.asarray(chain_old(a1, b1))[:, 0], 13)
r2 = unlimbs(np.asarray(chain2(a2, b2))[:, 0].astype(np.int64), W2)
r3 = unlimbs(np.asarray(chain3(a3, b3))[:, 0].astype(np.int64), W3)
print("correct:", r1 == exp, r2 == exp, r3 == exp)

t1 = timeit(chain_old, a1, b1)
t2 = timeit(chain2, a2, b2)
t3 = timeit(chain3, a3, b3)
for name, t in [("old-13x20-dotgen", t1), ("int32-11x24", t2), ("f32-8x32", t3)]:
    per = t / K
    print(
        f"{name}: {t*1e3:.2f} ms for {K} muls @B={B} -> "
        f"{per*1e6:.1f} us/batched-mul, {per/B*1e9:.2f} ns/lane-mul"
    )
