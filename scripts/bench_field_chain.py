"""Per-op cost of the field layer on the live chip: mul vs square vs carry,
measured as long chains (amortizes the tunnel dispatch floor, ~70 ms/call).

Used to build the bottom-up cost model for the verify kernel: per-sig time
should be ~(#muls * t_mul + #squares * t_sq); a mismatch means the kernel
is bound by something other than VPU arithmetic (issue slots, VMEM, Mosaic
scheduling) and op-count optimizations won't pay."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/.cache/jax")

import numpy as np
import jax
import jax.numpy as jnp

from cometbft_tpu.ops import fe25519 as fe
from _bench_common import timed as _timed

B = int(os.environ.get("B", "32768"))
K = int(os.environ.get("K", "400"))


def chain(op, kernel_mode):
    def f(v):
        x = fe.F(v, fe.RED_LO, fe.RED_HI)
        y = x
        if kernel_mode:
            with fe.kernel_mode(B):
                for _ in range(K):
                    y = op(y, x)
        else:
            for _ in range(K):
                y = op(y, x)
        return y.v

    return jax.jit(f)


def timed(f, v, label):
    t = _timed(f, args=(v,))
    print(f"{label:24s} {t*1e3:8.2f} ms  ({t / K / B * 1e9:6.2f} ns/op/lane)")
    return t


def main():
    print(f"platform={jax.devices()[0].platform} B={B} K={K}")
    rng = np.random.default_rng(0)
    v = jnp.asarray(
        rng.integers(fe.RED_LO, fe.RED_HI + 1, size=(fe.NLIMBS, B)).astype(
            np.int32
        )
    )
    sq = lambda y, x: fe.square(y)
    timed(chain(fe.mul, False), v, "mul (skew/XLA)")
    timed(chain(fe.mul, True), v, "mul (rows/kernel-mode)")
    timed(chain(sq, False), v, "square")
    timed(chain(lambda y, x: fe.red(fe.add(y, x)), False), v, "add+red")


if __name__ == "__main__":
    main()
