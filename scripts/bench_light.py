"""Light-client sync benchmark: 1k-validator sequential header sync
through the TPU batch-verify seam (BASELINE config #3; reference harness
light/client_benchmark_test.go — there the mock chain comes from
GenMockNode and the measured op is VerifyLightBlockAtHeight under
SequentialVerification).

Builds a synthetic chain — one validator set of V ed25519 validators, H
signed headers with consistent hashes — behind a mock Provider, then
times LightClient sequential sync from trust height 1 to H.  Every
commit verification routes through ``crypto.batch`` (the TPU seam), so
the measured number is the consensus-verify path end to end: sign-bytes
reconstruction, batch packing, device ladder, tally.

Standalone: COMETBFT_TPU_JAX_PLATFORM=cpu python scripts/bench_light.py
Knobs: BENCH_LIGHT_VALS (default 1000), BENCH_LIGHT_HEIGHTS (default 4).
Also callable from bench.py's staged TPU worker via ``run(emit)``.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHAIN_ID = "light-bench-chain"


def build_chain(n_vals: int, heights: int):
    """(provider, trust_options) for a synthetic H-height chain signed by
    one V-validator set.  Commits are assembled directly (the host just
    signed them; VoteSet's per-add verification would re-verify V·H sigs
    in pure python)."""
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.light.provider import Provider
    from cometbft_tpu.light.verifier import TrustOptions
    from cometbft_tpu.types.basic import BlockID, PartSetHeader, Timestamp
    from cometbft_tpu.types.block import Commit, ConsensusVersion, Header
    from cometbft_tpu.types.light import LightBlock, SignedHeader
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.types.vote import (
        BLOCK_ID_FLAG_COMMIT,
        PRECOMMIT_TYPE,
        CommitSig,
        canonical_vote_sign_bytes,
    )

    privs = [
        Ed25519PrivKey.from_seed(
            hashlib.sha256(b"light-bench-val-%d" % i).digest()
        )
        for i in range(n_vals)
    ]
    vals = ValidatorSet([Validator(p.pub_key(), 10) for p in privs])
    # commit signatures must follow the set's canonical validator order
    by_addr = {p.pub_key().address(): p for p in privs}
    privs = [by_addr[v.address] for v in vals.validators]
    vhash = vals.hash()
    base_ns = 1_700_000_000 * 10**9

    blocks = {}
    prev_bid = BlockID(
        hash=hashlib.sha256(b"genesis").digest(),
        part_set_header=PartSetHeader(1, hashlib.sha256(b"gp").digest()),
    )
    for h in range(1, heights + 1):
        ts = Timestamp.from_ns(base_ns + h * 10**9)
        header = Header(
            version=ConsensusVersion(block=11, app=1),
            chain_id=CHAIN_ID,
            height=h,
            time=ts,
            last_block_id=prev_bid,
            validators_hash=vhash,
            next_validators_hash=vhash,
            proposer_address=vals.validators[h % n_vals].address,
        )
        bid = BlockID(
            hash=header.hash(),
            part_set_header=PartSetHeader(
                1, hashlib.sha256(b"parts-%d" % h).digest()
            ),
        )
        sigs = []
        for priv in privs:
            sb = canonical_vote_sign_bytes(
                CHAIN_ID, PRECOMMIT_TYPE, h, 0, bid, ts
            )
            sigs.append(
                CommitSig(
                    block_id_flag=BLOCK_ID_FLAG_COMMIT,
                    validator_address=priv.pub_key().address(),
                    timestamp=ts,
                    signature=priv.sign(sb),
                )
            )
        commit = Commit(height=h, round_=0, block_id=bid, signatures=sigs)
        blocks[h] = LightBlock(SignedHeader(header, commit), vals)
        prev_bid = bid

    class ChainProvider(Provider):
        def chain_id(self) -> str:
            return CHAIN_ID

        def light_block(self, height: int):
            return blocks[height if height else heights]

        def report_evidence(self, ev) -> None:
            pass

    trust = TrustOptions(
        period_s=10**9, height=1, hash=blocks[1].hash()
    )
    return ChainProvider(), trust, base_ns


def run(emit, n_vals: int | None = None, heights: int | None = None) -> dict:
    """Build the chain, run sequential sync, emit one JSON record."""
    from cometbft_tpu.light import SEQUENTIAL, LightClient, LightStore
    from cometbft_tpu.store.kv import MemKV

    n_vals = n_vals or int(os.environ.get("BENCH_LIGHT_VALS", "1000"))
    heights = heights or int(os.environ.get("BENCH_LIGHT_HEIGHTS", "4"))
    t0 = time.perf_counter()
    provider, trust, base_ns = build_chain(n_vals, heights)
    setup_s = time.perf_counter() - t0

    now = base_ns / 1e9 + heights + 60
    client = LightClient(
        CHAIN_ID,
        trust,
        provider,
        [provider],
        LightStore(MemKV()),
        mode=SEQUENTIAL,
        now_fn=lambda: now,
    )
    t0 = time.perf_counter()
    lb = client.verify_light_block_at_height(heights, now=now)
    sync_s = time.perf_counter() - t0
    assert lb is not None and lb.height == heights
    n_commits = heights - 1  # height 1 is trusted, 2..H verified
    sigs = n_commits * n_vals
    rec = {
        "metric": "light_client_sync",
        "value": round(sigs / sync_s, 1),
        "unit": "sig-verifies/s",
        "validators": n_vals,
        "heights_verified": n_commits,
        "sync_s": round(sync_s, 3),
        "per_commit_ms": round(sync_s / max(n_commits, 1) * 1e3, 1),
        "setup_s": round(setup_s, 1),
    }
    emit(rec)
    return rec


def main() -> None:
    import jax

    plat = os.environ.get("COMETBFT_TPU_JAX_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    run(lambda rec: print(json.dumps(rec), flush=True))


if __name__ == "__main__":
    main()
