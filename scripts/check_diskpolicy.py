"""CI lint: keep future durable IO on the diskguard seam.

The disk-fault supervisor (``cometbft_tpu/libs/diskguard.py``,
docs/storage-robustness.md) only enforces the fail-stop vs degrade
durability policy — and only lets the sim inject deterministic storage
faults — for writes that go THROUGH it.  A new subsystem that calls
``open(path, "wb")`` / ``os.fsync`` / ``os.replace`` directly re-creates
the untested-folklore problem this repo just engineered away: a durable
surface with no policy, no injection coverage, and no metrics.

This gate fails on any NEW direct durable-IO call site in production
code (``cometbft_tpu/``) outside the seam itself:

  * ``open(...)`` with a write-capable mode literal ("w", "a", "+"),
  * ``os.fsync(...)`` (attribute form; ``f.flush()`` is fine — it is the
    fsync that makes a write a durability promise),
  * ``os.replace(...)`` (the atomic-publish rename).

Legacy sites are PINNED at their current per-file counts (each one is a
known quantity: WAL head management, blackbox segment files, dump
writers, …).  Growing a pinned file's count — or adding a site anywhere
else — is a failure: new code calls ``diskguard.file_write`` /
``diskguard.fsync`` / ``diskguard.replace`` / ``diskguard.atomic_write``
(or ``diskguard.guard`` around a backend-specific thunk) instead.
AST-based like scripts/check_verify_callsites.py: comments, docstrings
and string literals can mention the names freely.

Usage (wired into gate.sh):
    python scripts/check_diskpolicy.py [--repo-root PATH]
"""

from __future__ import annotations

import argparse
import ast
import pathlib
import sys

# the seam's own implementation layer: the one place raw durable IO and
# the policy/injection machinery are allowed to meet
ALLOWED_FILES = ("cometbft_tpu/libs/diskguard.py",)

# Pre-diskguard direct call sites, pinned at their current counts.
# Anything above these counts is NEW direct durable IO.
LEGACY_MAX: dict = {}  # filled below, after the scanner definition


def _write_mode(call: ast.Call) -> bool:
    """True when an ``open`` call's mode literal is write-capable."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default "r"
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return True  # computed mode: flag it — the lint must not guess
    return any(ch in mode.value for ch in ("w", "a", "+"))


def _call_sites(source: str) -> "list[tuple[int, str]]":
    """(lineno, description) for every durable-IO AST call site."""
    hits = []
    for node in ast.walk(ast.parse(source)):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "open":
            if _write_mode(node):
                hits.append((node.lineno, 'open(..., "w/a/+")'))
        elif isinstance(fn, ast.Attribute) and fn.attr in (
            "fsync",
            "replace",
        ):
            # only the os module's: str.replace / dict-like .replace on
            # other objects must not trip the gate
            if isinstance(fn.value, ast.Name) and fn.value.id == "os":
                hits.append((node.lineno, f"os.{fn.attr}(...)"))
    return sorted(hits)


LEGACY_MAX = {
    # CLI scaffolding written once by `cometbft-tpu init` / artifact
    # dumps (trace/postmortem output files, not durable node state)
    "cometbft_tpu/cmd/main.py": 5,
    "cometbft_tpu/config/config.py": 1,
    # consensus WAL: the guarded append/fsync path rides diskguard; the
    # remaining sites are head-file lifecycle (open-for-append,
    # graceful-close fsync) that predate the seam
    "cometbft_tpu/consensus/wal.py": 2,
    # black-box journal: head-segment open-for-append (the guarded
    # write/flush/fsync path is on the seam)
    "cometbft_tpu/libs/blackbox.py": 1,
    # flight-recorder anomaly dump writer (best-effort forensics file)
    "cometbft_tpu/libs/tracing.py": 1,
    # native build: compiled-library publish rename
    "cometbft_tpu/native/__init__.py": 1,
    # node key + p2p address book JSON persisted at boot/discovery
    "cometbft_tpu/node/nodekey.py": 1,
    "cometbft_tpu/p2p/pex.py": 2,
}


def scan(repo_root: pathlib.Path) -> "list[str]":
    """Return violation messages (empty = clean)."""
    violations = []
    pkg = repo_root / "cometbft_tpu"
    for path in sorted(pkg.rglob("*.py")):
        rel = path.relative_to(repo_root).as_posix()
        if rel in ALLOWED_FILES:
            continue
        try:
            hits = _call_sites(path.read_text(errors="replace"))
        except SyntaxError as e:
            violations.append(f"{rel}: unparsable ({e}) — cannot lint")
            continue
        cap = LEGACY_MAX.get(rel, 0)
        if len(hits) > cap:
            for lineno, line in hits:
                violations.append(f"{rel}:{lineno}: {line}")
            violations.append(
                f"{rel}: {len(hits)} direct durable-IO call site(s), "
                f"allowed {cap} — route new durable writes through "
                "cometbft_tpu/libs/diskguard.py "
                "(see docs/storage-robustness.md)"
            )
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--repo-root",
        default=str(pathlib.Path(__file__).resolve().parent.parent),
        help="repository root (default: this script's parent's parent)",
    )
    args = ap.parse_args(argv)
    violations = scan(pathlib.Path(args.repo_root))
    if violations:
        print("diskpolicy: FAIL", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("diskpolicy: OK (durable IO on the diskguard seam)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
