"""Bisect which part of the round-1 fe25519.mul costs 1.1ms/call."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/.cache/jax")

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from cometbft_tpu.ops import fe25519 as fe

B, K = 8192, 64
print("device:", jax.devices()[0].platform)


def timeit(fn, *args, reps=5):
    jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def chain(body):
    @jax.jit
    def f(a, b):
        def step(c, _):
            return body(c, b), None

        c, _ = lax.scan(step, a, None, length=K)
        return c

    return f


NL, BITS, MASK = fe.NLIMBS, fe.BITS, fe.MASK
_COLSUM = jnp.asarray(fe._COLSUM.astype(np.int32))


def dotgen_only(a, b):
    outer = (a[:, None, :] * b[None, :, :]).reshape(NL * NL, B)
    cols = lax.dot_general(
        _COLSUM, outer, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return cols[:NL] & MASK


def dotgen_chain_carry(a, b):
    outer = (a[:, None, :] * b[None, :, :]).reshape(NL * NL, B)
    cols_arr = lax.dot_general(
        _COLSUM, outer, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    carry, cols = fe._carry_chain(cols_arr)
    hi = jnp.concatenate([cols[NL:], carry[None]], axis=0)
    return (cols[:NL] + fe.FOLD * hi) & MASK


def full_mul(a, b):
    return fe.mul(a, b)


def carry_only(a, b):
    return fe._carry(a + b * 7)


rng = np.random.default_rng(0)
a = jnp.asarray(rng.integers(0, MASK, size=(NL, B)).astype(np.int32))
b = jnp.asarray(rng.integers(0, MASK, size=(NL, B)).astype(np.int32))

for name, body in [
    ("dotgen-only", dotgen_only),
    ("dotgen+chain39", dotgen_chain_carry),
    ("full fe.mul", full_mul),
    ("fe._carry only", carry_only),
]:
    t = timeit(chain(body), a, b)
    print(f"{name:16s}: {t*1e3:8.3f} ms total, {t/K*1e6:8.2f} us/iter")


# --- hypothesis: 20 rows (2.5 sublane tiles) vs 24 rows (3 tiles) ---------
def scan_carry_rows(nrows):
    def body(c, b):
        def step(carry, row):
            row = row + carry
            cc = row >> BITS
            return cc, row - (cc << BITS)

        cout, rows = lax.scan(step, jnp.zeros_like(c[0]), c + b)
        return rows

    return body


for nrows in (8, 16, 20, 24, 32):
    aa = jnp.asarray(rng.integers(0, MASK, size=(nrows, B)).astype(np.int32))
    bb = jnp.asarray(rng.integers(0, MASK, size=(nrows, B)).astype(np.int32))
    t = timeit(chain(scan_carry_rows(nrows)), aa, bb)
    print(f"scan-carry rows={nrows:2d}: {t/K*1e6:9.2f} us/iter")
