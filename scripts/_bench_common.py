"""Shared helpers for the chip benchmark scripts: signature-batch fixture
generation and min-of-N wall timing (one definition, four users)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/.cache/jax")

import numpy as np


def make_sig_dev(n: int, distinct_cap: int = 1024):
    """n signed (pub, msg, sig) triples tiled from ``distinct_cap``
    distinct python-oracle signatures, prepared and put on device.
    Returns the device-array dict matching verify_core's kwargs."""
    import jax.numpy as jnp

    from cometbft_tpu.crypto import ed25519_ref as ref
    from cometbft_tpu.ops import verify as ov

    distinct = min(n, distinct_cap)
    pubs, msgs, sigs = [], [], []
    for i in range(distinct):
        seed = i.to_bytes(4, "little") * 8
        pubs.append(ref.pubkey_from_seed(seed))
        msgs.append(b"bench-%d" % i)
        sigs.append(ref.sign(seed, b"bench-%d" % i))
    reps = -(-n // distinct)
    arrays, _, _ = ov.prepare_batch(
        (pubs * reps)[:n], (msgs * reps)[:n], (sigs * reps)[:n]
    )
    return {k: jnp.asarray(v) for k, v in arrays.items()}


def timed(fn, args=(), kwargs=None, label="", reps=7, per_n=None):
    """min-of-``reps`` wall time with a host transfer forcing completion
    (axon block_until_ready can return early on repeat executions)."""
    kwargs = kwargs or {}
    np.asarray(fn(*args, **kwargs))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(fn(*args, **kwargs))
        ts.append(time.perf_counter() - t0)
    t = min(ts)
    if label:
        extra = f"   {per_n/t/1e3:8.1f} k/s" if per_n else ""
        print(f"{label:34s} {t*1e3:9.2f} ms{extra}")
    return t
