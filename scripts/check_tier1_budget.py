"""Tier-1 wall-time budget gate.

The tier-1 suite runs under a hard 870 s timeout (ROADMAP.md) and has
twice brushed it; this gate fails CI at a SOFT budget of 800 s so a creep
past the margin shows up as a red check with headroom to fix it, instead
of as a flaky timeout.

Usage:
    # after the tier-1 invocation that tees to /tmp/_t1.log:
    python scripts/check_tier1_budget.py [/tmp/_t1.log] [--budget 800]
    # or gate an externally measured number:
    python scripts/check_tier1_budget.py --seconds 812.4

Parses the wall time from the LAST pytest summary line in the log
("=== 123 passed, 4 skipped in 682.33s ==="; "(0:11:22)" forms included).
An unparsable log is a FAILURE, not a pass — a truncated log usually
means the suite died or timed out.
"""

from __future__ import annotations

import argparse
import re
import sys

DEFAULT_BUDGET_S = 800.0
DEFAULT_LOG = "/tmp/_t1.log"

# "in 682.33s", "in 682.33s (0:11:22)"
_SUMMARY_RE = re.compile(r"\bin\s+([0-9]+(?:\.[0-9]+)?)s(?:\s+\([0-9:]+\))?\s*=*\s*$")

# "12.34s call     tests/test_sim.py::TestScenarios::test_x" — emitted when
# the suite runs with --durations=N (scripts/gate.sh does)
_DURATION_RE = re.compile(
    r"^\s*([0-9]+(?:\.[0-9]+)?)s\s+(call|setup|teardown)\s+(\S+)"
)

# "tier1-exec-cache: compiles=3 compile_s=61.2 hits=9 load_s=14.1 ..." —
# printed by tests/conftest.py's terminal summary (ops/warm_stats)
_EXEC_RE = re.compile(
    r"tier1-exec-cache:\s+compiles=(\d+)\s+compile_s=([0-9.]+)\s+"
    r"hits=(\d+)\s+load_s=([0-9.]+)"
)

# "tier1-trace: spans=1234 dropped=0 anomalies=2 dumps=1 overhead_s=0.04"
# — printed by tests/conftest.py's terminal summary (libs/tracing); the
# overhead share is gated: the flight recorder is default-on, so a
# regression in its record path would silently tax every verify
_TRACE_RE = re.compile(
    r"tier1-trace:\s+spans=(\d+)\s+dropped=(\d+)\s+anomalies=(\d+)\s+"
    r"dumps=(\d+)\s+overhead_s=([0-9.]+)"
)

# the recorder's measured in-process overhead must stay a rounding error
# of tier-1 wall time (the sched-bench gate in bench.py --obs is the
# precise one; this is the coarse suite-wide backstop)
TRACE_OVERHEAD_MAX_SHARE = 0.05

# tests whose dominant cost is a device-kernel compile (the population the
# warm-boot PR targets); used for the durations-table compile share
_COMPILE_HEAVY = (
    "test_bls_g1",
    "test_secp_batch",
    "test_pallas",
    "test_ed25519_jax",
    "test_ops",
    "test_mesh",
    "test_verify_stream",
)


def parse_wall_seconds(text: str) -> float | None:
    """Wall seconds from the last pytest summary line, or None."""
    last = None
    for line in text.splitlines():
        m = _SUMMARY_RE.search(line.strip())
        if m:
            last = float(m.group(1))
    return last


def parse_durations(text: str) -> list[tuple[float, str]]:
    """(seconds, test id) pairs from a --durations=N section, [] if the
    log has none."""
    out = []
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if m:
            out.append((float(m.group(1)), m.group(3)))
    return out


def sim_share(text: str, wall: float) -> str | None:
    """One-line report of the sim-scenario share of tier-1 wall time, or
    None when the log carries no --durations section.  A lower bound: the
    durations table only lists the slowest N items."""
    durations = parse_durations(text)
    if not durations or wall <= 0:
        return None
    sim_s = sum(s for s, tid in durations if "test_sim" in tid)
    listed_s = sum(s for s, _ in durations)
    return (
        f"tier1-budget: sim scenarios >= {sim_s:.1f}s of {wall:.1f}s wall "
        f"({100.0 * sim_s / wall:.1f}%; durations table covers "
        f"{listed_s:.1f}s)"
    )


def compile_share(text: str, wall: float) -> "list[str]":
    """Report lines for the compile-time share of tier-1 wall time.

    Two complementary views (both lower bounds):
      * the exec-cache summary line (exact in-process trace+compile
        seconds, but blind to spawned node subprocesses);
      * the durations table restricted to the compile-heavy kernel test
        files (captures a test's whole wall time, compile included)."""
    out = []
    if wall <= 0:
        return out
    m = None
    for m in _EXEC_RE.finditer(text):
        pass  # keep the LAST summary line, like the wall-time parse
    if m is not None:
        compiles, compile_s = int(m.group(1)), float(m.group(2))
        hits, load_s = int(m.group(3)), float(m.group(4))
        out.append(
            f"tier1-budget: kernel compiles {compiles} "
            f"({compile_s:.1f}s, {100.0 * compile_s / wall:.1f}% of wall); "
            f"exec-cache hits {hits} ({load_s:.1f}s loading)"
        )
    durations = parse_durations(text)
    if durations:
        heavy = sum(
            s for s, tid in durations
            if any(name in tid for name in _COMPILE_HEAVY)
        )
        out.append(
            f"tier1-budget: compile-heavy kernel tests >= {heavy:.1f}s of "
            f"{wall:.1f}s wall ({100.0 * heavy / wall:.1f}%; durations "
            "table lower bound)"
        )
    return out


def trace_share(text: str, wall: float) -> "tuple[list[str], bool]":
    """(report lines, ok) for the flight-recorder summary line.  A log
    with no line simply reports nothing (older logs, subprocess-only
    runs); a parsed overhead share past ``TRACE_OVERHEAD_MAX_SHARE``
    fails the gate."""
    m = None
    for m in _TRACE_RE.finditer(text):
        pass  # keep the LAST summary line, like the wall-time parse
    if m is None or wall <= 0:
        return [], True
    spans, dropped, anomalies, dumps = (int(m.group(i)) for i in range(1, 5))
    overhead_s = float(m.group(5))
    share = overhead_s / wall
    ok = share <= TRACE_OVERHEAD_MAX_SHARE
    lines = [
        f"tier1-budget: flight recorder {spans} spans ({dropped} dropped), "
        f"{anomalies} anomalies, {dumps} dumps; recorder overhead "
        f"{overhead_s:.3f}s = {100.0 * share:.2f}% of wall"
        + ("" if ok else
           f" -> FAIL (> {100.0 * TRACE_OVERHEAD_MAX_SHARE:g}%)")
    ]
    return lines, ok


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "log", nargs="?", default=DEFAULT_LOG,
        help=f"tier-1 pytest log (default {DEFAULT_LOG})",
    )
    ap.add_argument(
        "--budget", type=float, default=DEFAULT_BUDGET_S,
        help=f"soft wall-time budget in seconds (default {DEFAULT_BUDGET_S:g})",
    )
    ap.add_argument(
        "--seconds", type=float, default=None,
        help="gate this wall time directly instead of parsing a log",
    )
    args = ap.parse_args()

    text = ""
    if args.seconds is not None:
        wall = args.seconds
    else:
        try:
            with open(args.log, errors="replace") as f:
                text = f.read()
        except OSError as e:
            print(f"tier1-budget: cannot read {args.log}: {e}", file=sys.stderr)
            return 1
        wall = parse_wall_seconds(text)
        if wall is None:
            print(
                f"tier1-budget: no pytest summary line found in {args.log} "
                "(suite died or the log is truncated) -> FAIL",
                file=sys.stderr,
            )
            return 1
    share = sim_share(text, wall) if text else None
    if share:
        print(share)
    trace_ok = True
    if text:
        for line in compile_share(text, wall):
            print(line)
        trace_lines, trace_ok = trace_share(text, wall)
        for line in trace_lines:
            print(line)

    margin = args.budget - wall
    if wall > args.budget:
        print(
            f"tier1-budget: FAIL wall={wall:.1f}s exceeds budget "
            f"{args.budget:g}s by {-margin:.1f}s (hard timeout is 870s — "
            "slow-mark the new heaviest tests or shrink fixtures)"
        )
        return 1
    if not trace_ok:
        return 1
    print(
        f"tier1-budget: ok wall={wall:.1f}s budget={args.budget:g}s "
        f"(margin {margin:.1f}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
