#!/usr/bin/env python
"""Gate stage: SIGKILL forensics end-to-end (docs/observability.md).

Drives a deterministic sim cluster on the host-oracle device seam, hard-
crashes a validator mid-run (WAL + black-box journal both lose their
unflushed tails), then decodes the dead node's journal with the REAL
``cometbft-tpu postmortem --json`` CLI in a subprocess and asserts the
reconstruction:

  * the run is detected as an unclean shutdown (no clean-close sentinel),
  * the in-flight ``consensus.round`` anchor (height, round) matches the
    round the node was actually in when it died,
  * the last ``verify.dispatch`` attribution triple (tier, lanes,
    ordinal) is present — the device path really journaled,
  * a second same-seed run reproduces the postmortem byte-for-byte.

Exit 0 = green.  Run by gate.sh before every milestone snapshot.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEED = 42
CRASH_NODE = 1


def run_once(root: str) -> dict:
    """One seeded cluster run: reach height 2, crash node 1, return the
    postmortem decoded by the CLI subprocess."""
    from cometbft_tpu.libs import tracing
    from cometbft_tpu.ops import dispatch_stats
    from cometbft_tpu.sim.cluster import SimCluster
    from cometbft_tpu.sim.scenarios import (
        _backend_faults_setup,
        _backend_faults_teardown,
    )
    from cometbft_tpu.txingest import stats as istats
    from cometbft_tpu.verifysched import stats as sstats

    cluster = SimCluster(4, root, seed=SEED)
    # the same per-run hygiene run_scenario applies: virtual-clock span
    # times, zeroed ids/ordinals/counters — journal bytes become a pure
    # function of the seed
    tracer = tracing.get_tracer()
    tracer.reset()
    tracer.set_clock(cluster.clock.now)
    dispatch_stats.reset()
    sstats.reset()
    istats.reset()
    try:
        # host-oracle device seam (the backend scenarios' setup): forces
        # the supervised tpu path so verify.dispatch spans exist, without
        # paying real XLA dispatches on the CI host
        _backend_faults_setup()(cluster)
        try:
            assert cluster.run(until_height=2, max_time=60.0), (
                "cluster never reached height 2"
            )
            # step PAST the commit boundary until the victim's next round
            # anchor is open — the gate's whole point is dying mid-round
            victim = cluster.nodes[CRASH_NODE]
            while victim.cs._round_span is None or victim.cs.rs.height < 3:
                assert cluster.step(), "clock drained before round 3 opened"
            anchor = victim.cs._round_span.attrs
            expected = (anchor["h"], anchor["r"])
            cluster.crash(CRASH_NODE)
        finally:
            _backend_faults_teardown(cluster)

        bb_dir = os.path.join(root, f"node{CRASH_NODE}", "blackbox")
        out = subprocess.run(
            [
                sys.executable,
                "-m",
                "cometbft_tpu.cmd",
                "postmortem",
                bb_dir,
                "--json",
            ],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=REPO,
        )
        assert out.returncode == 0, (
            f"postmortem CLI failed rc={out.returncode}: {out.stderr[-800:]}"
        )
        report = json.loads(out.stdout)

        assert report["unclean_shutdown"] is True, "crash read as clean"
        assert not report["clean_close"]
        inf = report["in_flight"]
        assert inf is not None, "no in-flight round reconstructed"
        assert (inf["h"], inf["r"]) == expected, (
            f"in-flight round {inf['h']}/{inf['r']} != live state "
            f"{expected[0]}/{expected[1]} at crash"
        )
        assert inf["node"] == CRASH_NODE
        ld = report["last_dispatch"]
        assert ld is not None, "no verify.dispatch attribution journaled"
        assert ld["tier"] and ld["lanes"] and ld["dispatch"] is not None, ld
        assert report["last_committed_height"] >= 2
        return report
    finally:
        cluster.stop()
        tracer.set_clock(None)
        tracer.reset()


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="postmortem-gate-a-") as a:
        r1 = run_once(a)
    with tempfile.TemporaryDirectory(prefix="postmortem-gate-b-") as b:
        r2 = run_once(b)
    b1 = json.dumps(r1, sort_keys=True)
    b2 = json.dumps(r2, sort_keys=True)
    assert b1 == b2, "same-seed postmortems diverged"
    inf = r1["in_flight"]
    print(
        "check_postmortem: OK — node%d died in-flight at h=%s r=%s, "
        "last dispatch tier=%s lanes=%s ordinal=%s, %d journal records "
        "(%d corrupt skipped, torn=%s), byte-deterministic across "
        "two seed-%d runs"
        % (
            CRASH_NODE,
            inf["h"],
            inf["r"],
            r1["last_dispatch"]["tier"],
            r1["last_dispatch"]["lanes"],
            r1["last_dispatch"]["dispatch"],
            r1["journal"]["records"],
            r1["journal"]["corrupt_skipped"],
            r1["journal"]["torn_tail"],
            SEED,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
