"""Seed-sweep soak for the deterministic simulator (cometbft_tpu/sim/).

Two modes:

  * default — run every scenario (or a named subset) across K seeds and
    write a JSON summary row per (scenario, seed).
  * ``--matrix`` — the nightly lane: sweep scenario x seed x cluster-scale
    (scale-capable scenarios also run at each ``--scales`` size) and run
    every cell TWICE with the same seed, failing the row on any trace
    divergence — the byte-identical-trace-per-seed invariant, enforced as
    a gate instead of an anecdote.  Scale sweeps are what items 1-3 on the
    roadmap regress against: verification behavior only gets interesting
    at committee sizes in the hundreds (arXiv:2302.00418).

CI archives the JSON so a robustness regression shows up as a diffable
artifact — a seed that used to reach the target height and now stalls, an
invariant that starts failing, or a trace that stops replaying.

Usage:
    python scripts/sim_soak.py [--seeds K] [--scenario NAME ...]
                               [--out sim_soak.json] [--fail-fast]
    python scripts/sim_soak.py --matrix [--scales 8,25] [--seeds 2]

Every row is reproducible: rerun the exact failure with
    cometbft-tpu sim --seed <seed> --scenario <scenario> [--validators N]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.sim import SCENARIOS, run_scenario

# scenarios whose fault scripts scale with the cluster size (victim picks,
# rotation targets and churn indices all derive from n_vals)
SCALABLE = (
    "baseline",
    "partition-minority",
    "crash-restart",
    "fleet-churn",
)


def _row_extra(row: dict) -> str:
    extra = ""
    backend = row.get("backend") or {}
    if backend:
        # backend-* scenarios: breaker activity is part of the verdict a
        # reviewer wants at a glance
        extra += " demote=%d repromote=%d watchdog=%d opens=%d" % (
            backend.get("demotions", 0),
            backend.get("repromotions", 0),
            backend.get("watchdog_fires", 0),
            backend.get("breaker_opens", 0),
        )
    if backend.get("mesh_width") or backend.get("mesh_shrinks"):
        # elastic-mesh scenarios: the degradation shape at a glance —
        # final width plus how many times the mesh shrank and healed
        extra += " mesh=%dw shrink=%d restore=%d" % (
            backend.get("mesh_width", 0),
            backend.get("mesh_shrinks", 0),
            backend.get("mesh_restores", 0),
        )
    ingest = row.get("ingest") or {}
    if ingest:
        # tx-flood: admission shape is the at-a-glance verdict — batched
        # occupancy, sync sheds, dedup hits, rejections
        extra += " adm=%d shed=%d dedup=%d rej=%d occ=%.2f" % (
            ingest.get("admitted", 0),
            ingest.get("shed_to_sync", 0),
            ingest.get("cache_hits", 0),
            ingest.get("rejected_total", 0),
            ingest.get("batch_occupancy", 0.0),
        )
    proofs = row.get("proofs") or {}
    if proofs:
        # light-stampede: read-plane discipline at a glance — admitted
        # queries, cache hit rate, shed volume, coalesced tree builds,
        # device vs host trees
        extra += " proofs[q=%d hit=%.2f shed=%d build=%d dev=%d/%d]" % (
            proofs.get("queries_total", 0),
            proofs.get("proof_cache_hit_rate", 0.0),
            proofs.get("shed_total", 0),
            proofs.get("tree_builds_total", 0),
            proofs.get("trees_device", 0),
            proofs.get("trees_device", 0) + proofs.get("trees_host", 0),
        )
    bsync = row.get("bsync") or {}
    if bsync:
        # blocksync-storm / wan-catchup: catchup discipline at a glance —
        # heights synced, request/timeout volume, ban -> probe -> readmit
        # cycling, stall switches and the virtual-clock sync rate
        extra += (
            " bsync[h=%d req=%d to=%d ban=%d probe=%d/%d stall=%d "
            "hps=%.1f]"
            % (
                bsync.get("heights_synced", 0),
                bsync.get("requests", 0),
                bsync.get("timeouts", 0),
                bsync.get("bans", 0),
                bsync.get("probe_passes", 0),
                bsync.get("probes", 0),
                bsync.get("stall_switches", 0),
                bsync.get("heights_per_second", 0.0),
            )
        )
    evidence = row.get("evidence") or {}
    if evidence:
        # evidence scenarios: pool discipline under flood
        extra += " evadd=%d dedup=%d drop=%d rej=%d commit=%d" % (
            evidence.get("added", 0),
            evidence.get("dedup", 0),
            evidence.get("dropped", 0),
            evidence.get("rejected", 0),
            evidence.get("committed", 0),
        )
    if row.get("rotations"):
        extra += " rot=%d" % row["rotations"]
    disk = row.get("storage") or {}
    if disk:
        # disk-fault scenarios (libs/diskguard): injected faults, retry
        # recoveries, counted drops, fail-stop halts and boot-time WAL
        # tail repairs — the storage-plane verdict at a glance
        extra += " disk[inj=%d rt=%d dr=%d fatal=%d rp=%d]" % (
            disk.get("injected", 0),
            disk.get("retries", 0),
            disk.get("drops", 0),
            disk.get("fatals", 0),
            disk.get("repairs", 0),
        )
        if disk.get("fail_stopped_nodes"):
            extra += " failstop=%s" % ",".join(
                str(n) for n in disk["fail_stopped_nodes"]
            )
    bb = row.get("blackbox") or {}
    if bb:
        # black-box journal shape of the run: bytes on disk and (above
        # all) drops — a nonzero drop count means the bounded queue shed
        # forensics under load, which a reviewer wants to see in the row
        extra += " bb=%dB/%drec drop=%d" % (
            bb.get("bytes", 0),
            bb.get("records", 0),
            bb.get("dropped", 0),
        )
        if bb.get("postmortems"):
            extra += " pm=%d" % bb["postmortems"]
    spans = row.get("spans") or {}
    if spans:
        # flight-recorder shape of the run: span volume, anomaly kinds and
        # the worst p99 stage latencies (virtual ms) — a latency
        # regression shows up as a diffable column, not a rerun
        extra += " spans=%d" % spans.get("recorded", 0)
        anomalies = spans.get("anomalies") or {}
        if anomalies:
            extra += " anom=%s" % ",".join(
                "%s:%d" % kv for kv in sorted(anomalies.items())
            )
        if spans.get("dumps"):
            extra += " dumps=%d" % len(spans["dumps"])
        p99 = spans.get("p99_ms") or {}
        worst = sorted(p99.items(), key=lambda kv: -kv[1])[:3]
        if worst:
            extra += " p99ms=" + ",".join(
                "%s:%.1f" % (stage.split(".")[-1], ms) for stage, ms in worst
            )
        rounds = spans.get("rounds") or {}
        if rounds:
            # merged cross-node round timeline: commit-to-proposal linkage
            # plus per-step p99 (virtual ms) — the consensus-latency shape
            # of the run in one diffable column
            extra += " rounds=%d link=%d/%d" % (
                rounds.get("seen", 0),
                rounds.get("commits_linked", 0),
                rounds.get("commits_linked", 0)
                + rounds.get("commits_unlinked", 0),
            )
            steps = rounds.get("steps") or {}
            if steps:
                extra += " step_p99=" + ",".join(
                    "%s:%.0f"
                    % (
                        step.replace("RoundStep", "").lower(),
                        s.get("p99_ms", 0.0),
                    )
                    for step, s in sorted(steps.items())
                )
            quorum = rounds.get("quorum") or {}
            if quorum:
                extra += " q_p99=" + ",".join(
                    "%s:%.0f" % (k.split("_")[0], q.get("p99_ms", 0.0))
                    for k, q in sorted(quorum.items())
                )
    return extra


def _run_cell(name: str, seed: int, n_vals, divergence_check: bool) -> dict:
    """One (scenario, seed, scale) cell; with divergence_check the cell
    runs twice and the traces are byte-compared."""
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix=f"soak-{name}-{seed}-") as root:
        res = run_scenario(name, seed, root=root, n_vals=n_vals)
    row = res.summary()
    row["wall_seconds"] = round(time.monotonic() - t0, 3)
    if divergence_check:
        with tempfile.TemporaryDirectory(
            prefix=f"soak2-{name}-{seed}-"
        ) as root:
            res2 = run_scenario(name, seed, root=root, n_vals=n_vals)
        row["trace_identical"] = res.trace == res2.trace
    return row


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=None,
                    help="seeds per scenario (default: 5, matrix: 2)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument(
        "--scenario",
        action="append",
        default=None,
        help="scenario name (repeatable; default: all)",
    )
    ap.add_argument(
        "--matrix", action="store_true",
        help="nightly mode: scenario x seed x scale sweep with per-cell "
             "same-seed double runs (trace divergence fails the row)",
    )
    ap.add_argument(
        "--scales", default="8,25",
        help="comma-separated extra cluster sizes for scale-capable "
             "scenarios in --matrix mode (default 8,25)",
    )
    ap.add_argument("--out", default="sim_soak.json")
    ap.add_argument(
        "--fail-fast", action="store_true", help="stop at the first bad row"
    )
    args = ap.parse_args()
    seeds = args.seeds if args.seeds is not None else (2 if args.matrix else 5)

    names = args.scenario or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenarios: {unknown}; known: {list(SCENARIOS)}",
              file=sys.stderr)
        return 2

    # build the cell list: (scenario, seed, n_vals-override-or-None)
    cells = []
    for name in names:
        scales = [None]
        if args.matrix and name in SCALABLE:
            scales += [
                int(s) for s in args.scales.split(",") if s.strip()
            ]
        for n_vals in scales:
            for seed in range(args.seed_base, args.seed_base + seeds):
                cells.append((name, seed, n_vals))

    rows = []
    failures = 0
    t0 = time.monotonic()
    for name, seed, n_vals in cells:
        row = _run_cell(name, seed, n_vals, divergence_check=args.matrix)
        rows.append(row)
        ok = (
            row["reached"]
            and row["invariants_ok"]
            and row.get("trace_identical", True)
            # a journal that outgrew its configured segment budget is a
            # black-box regression, failed like any other invariant
            and (row.get("blackbox") or {}).get("budget_ok", True)
        )
        tag = "ok  " if ok else "FAIL"
        if not row.get("trace_identical", True):
            tag = "DIVG"
        # -1 slots are departed/never-spawned nodes (fleet-churn's leaver),
        # not stalled members — keep them out of the min column
        live_heights = [h for h in row["heights"] if h >= 0] or [-1]
        print(
            "%-20s seed=%-4d n=%-3d %s heights[min/max]=%d/%d events=%d "
            "wall=%.1fs%s"
            % (
                name,
                seed,
                row["n_vals"],
                tag,
                min(live_heights),
                max(live_heights),
                row["events"],
                row["wall_seconds"],
                _row_extra(row),
            )
        )
        if not ok:
            failures += 1
            for v in row["violations"]:
                print(f"  violation: {v}")
            if not row.get("trace_identical", True):
                print("  trace diverged between two same-seed runs")
            if args.fail_fast:
                break

    summary = {
        "mode": "matrix" if args.matrix else "sweep",
        "seeds_per_scenario": seeds,
        "scenarios": names,
        "scales": args.scales if args.matrix else None,
        "rows": rows,
        "failures": failures,
        "wall_seconds": round(time.monotonic() - t0, 3),
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2)
    print(f"\n{len(rows)} runs, {failures} failures -> {args.out}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
