"""Seed-sweep soak for the deterministic simulator (cometbft_tpu/sim/).

Runs every scenario (or a named subset) across K seeds and writes a JSON
summary row per (scenario, seed): heights reached, virtual time, event
count, commits verified, and the invariant verdict.  CI archives the JSON
so a robustness regression shows up as a diffable artifact — a seed that
used to reach the target height and now stalls, or an invariant that
starts failing — instead of an anecdote about a flaky test.

Usage:
    python scripts/sim_soak.py [--seeds K] [--scenario NAME ...]
                               [--out sim_soak.json] [--fail-fast]

Every row is reproducible: rerun the exact failure with
    cometbft-tpu sim --seed <seed> --scenario <scenario>
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.sim import SCENARIOS, run_scenario


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=5, help="seeds per scenario")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument(
        "--scenario",
        action="append",
        default=None,
        help="scenario name (repeatable; default: all)",
    )
    ap.add_argument("--out", default="sim_soak.json")
    ap.add_argument(
        "--fail-fast", action="store_true", help="stop at the first bad row"
    )
    args = ap.parse_args()

    names = args.scenario or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenarios: {unknown}; known: {list(SCENARIOS)}",
              file=sys.stderr)
        return 2

    rows = []
    failures = 0
    t0 = time.monotonic()
    for name in names:
        for seed in range(args.seed_base, args.seed_base + args.seeds):
            with tempfile.TemporaryDirectory(
                prefix=f"soak-{name}-{seed}-"
            ) as root:
                res = run_scenario(name, seed, root=root)
            row = res.summary()
            rows.append(row)
            ok = row["reached"] and row["invariants_ok"]
            backend = row.get("backend") or {}
            extra = ""
            if backend:
                # backend-* scenarios: breaker activity is part of the
                # verdict a reviewer wants at a glance
                extra = " demote=%d repromote=%d watchdog=%d opens=%d" % (
                    backend.get("demotions", 0),
                    backend.get("repromotions", 0),
                    backend.get("watchdog_fires", 0),
                    backend.get("breaker_opens", 0),
                )
            ingest = row.get("ingest") or {}
            if ingest:
                # tx-flood: admission shape is the at-a-glance verdict —
                # batched occupancy, sync sheds, dedup hits, rejections
                extra += " adm=%d shed=%d dedup=%d rej=%d occ=%.2f" % (
                    ingest.get("admitted", 0),
                    ingest.get("shed_to_sync", 0),
                    ingest.get("cache_hits", 0),
                    ingest.get("rejected_total", 0),
                    ingest.get("batch_occupancy", 0.0),
                )
            print(
                "%-20s seed=%-4d %s heights=%s events=%d%s"
                % (
                    name,
                    seed,
                    "ok  " if ok else "FAIL",
                    row["heights"],
                    row["events"],
                    extra,
                )
            )
            if not ok:
                failures += 1
                for v in row["violations"]:
                    print(f"  violation: {v}")
                if args.fail_fast:
                    break
        if failures and args.fail_fast:
            break

    summary = {
        "seeds_per_scenario": args.seeds,
        "scenarios": names,
        "rows": rows,
        "failures": failures,
        "wall_seconds": round(time.monotonic() - t0, 3),
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=2)
    print(f"\n{len(rows)} runs, {failures} failures -> {args.out}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
